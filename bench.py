"""Headline benchmark: the north-star bin-pack (BASELINE.json).

100k pending pods × 300 instance types — resource fit + taint/toleration +
required-label feasibility, first-feasible assignment, shelf-BFD node counts
— as one device call. The reference STUBS this signal entirely
(pkg/metrics/producers/pendingcapacity/producer.go:29-31) and its design doc
warns the naive host-side form "scales linearly with node groups and
unschedulable pods" (docs/designs/DESIGN.md); the baseline BUDGET here is
the north-star target of 200 ms p50 on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline > 1 means faster than the 200 ms budget.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

BASELINE_MS = 200.0


def build_inputs(pods: int, types: int, taints: int, labels: int, seed: int):
    import jax.numpy as jnp

    from karpenter_tpu.ops.binpack import BinPackInputs

    rng = np.random.default_rng(seed)
    R = 3  # cpu, memory, pods
    # pod requests: cpu in cores, memory in GiB, 1 pod slot
    req = np.stack(
        [
            rng.uniform(0.05, 8.0, pods),
            rng.uniform(0.1, 32.0, pods),
            np.ones(pods),
        ],
        axis=1,
    ).astype(np.float32)
    # instance types: cpu 2..128 cores, proportional memory, 110 pod slots
    cpu = rng.choice([2, 4, 8, 16, 32, 64, 96, 128], types).astype(np.float32)
    mem = cpu * rng.choice([2.0, 4.0, 8.0], types).astype(np.float32)
    alloc = np.stack([cpu, mem, np.full(types, 110.0, np.float32)], axis=1)
    intol = rng.random((pods, taints)) < 0.05
    group_taints = rng.random((types, taints)) < 0.1
    required = rng.random((pods, labels)) < 0.03
    group_labels = rng.random((types, labels)) < 0.8
    return BinPackInputs(
        pod_requests=jnp.asarray(req),
        pod_valid=jnp.ones((pods,), bool),
        pod_intolerant=jnp.asarray(intol),
        pod_required=jnp.asarray(required),
        group_allocatable=jnp.asarray(alloc),
        group_taints=jnp.asarray(group_taints),
        group_labels=jnp.asarray(group_labels),
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=100_000)
    ap.add_argument("--types", type=int, default=300)
    ap.add_argument("--taints", type=int, default=64)
    ap.add_argument("--labels", type=int, default=64)
    ap.add_argument("--buckets", type=int, default=32)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--backend",
        choices=("auto", "xla", "pallas"),
        default="auto",
        help="auto = fused Pallas kernel on TPU, XLA elsewhere",
    )
    args = ap.parse_args()

    import jax

    from karpenter_tpu.ops.binpack import solve

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    inputs = build_inputs(
        args.pods, args.types, args.taints, args.labels, args.seed
    )
    inputs = jax.device_put(inputs)
    jax.block_until_ready(inputs)

    t0 = time.perf_counter()
    out = solve(inputs, buckets=args.buckets, backend=args.backend)
    jax.block_until_ready(out)
    compile_ms = (time.perf_counter() - t0) * 1e3
    print(f"first call (compile+run): {compile_ms:.1f} ms", file=sys.stderr)

    times = []
    for _ in range(args.iters):
        t0 = time.perf_counter()
        out = solve(inputs, buckets=args.buckets, backend=args.backend)
        jax.block_until_ready(out)
        times.append((time.perf_counter() - t0) * 1e3)
    p50 = float(np.percentile(times, 50))
    p95 = float(np.percentile(times, 95))
    scheduled = int(np.sum(np.asarray(out.assigned) >= 0))
    print(
        f"p50={p50:.2f}ms p95={p95:.2f}ms scheduled={scheduled}/{args.pods} "
        f"unschedulable={int(out.unschedulable)} "
        f"nodes={int(np.sum(np.asarray(out.nodes_needed)))}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": (
                    f"pending-pods bin-pack p50 latency, "
                    f"{args.pods} pods x {args.types} instance types"
                ),
                "value": round(p50, 3),
                "unit": "ms",
                "vs_baseline": round(BASELINE_MS / p50, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
