"""Headline benchmark: the north-star bin-pack (BASELINE.json).

100k pending pods × 300 instance types — resource fit + taint/toleration +
required-label feasibility, first-feasible assignment, shelf-BFD node counts
— as one device call. The reference STUBS this signal entirely
(pkg/metrics/producers/pendingcapacity/producer.go:29-31) and its design doc
warns the naive host-side form "scales linearly with node groups and
unschedulable pods" (docs/designs/DESIGN.md); the baseline BUDGET here is
the north-star target of 200 ms p50 on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline > 1 means faster than the 200 ms budget.
"""

from __future__ import annotations

import argparse
import datetime
import json
import math
import os
import sys
import time

import numpy as np

BASELINE_MS = 200.0

# Raw-evidence sidecar (r3 verdict): every run appends its full raw record
# — argv, backend, devices, per-iteration times, transport-floor probe —
# to bench_evidence/runs.jsonl (and mirrors the newest to latest.json), so
# a perf claim is always reconstructable from committed data instead of
# resting on a summarized p50 in a doc table.
EVIDENCE: dict = {}
EVIDENCE_DIR = os.environ.get(
    "KARPENTER_BENCH_EVIDENCE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "bench_evidence"),
)


def record_evidence(**kv) -> None:
    """Stash raw measurement context for the evidence sidecar. Values
    must be JSON-serializable (lists, not ndarrays)."""
    EVIDENCE.update(kv)


def measure_transport_floor(iters: int = 20) -> dict:
    """p50 cost of the smallest possible host<->device interactions —
    the transport floor every sync'd measurement sits on top of:

    - put_ms: device_put of one f32 scalar + block_until_ready;
    - dispatch_ms: a compiled 1-element add, dispatch + block;
    - fetch_ms: device_get of a 1-element array.

    On a locally-attached chip these are tens of microseconds; through
    a network tunnel each is >= 1 RTT. Recording them next to every
    solve p50 makes the tunnel-tax attribution MEASURED instead of
    inferred — r2's builder capture claimed a 0.071 ms sync'd solve AND
    a 35-70 ms tunnel round-trip, which cannot both be true, and had no
    artifact to tell which was wrong (r3 verdict, weak #1)."""
    try:
        import jax
        import jax.numpy as jnp

        tiny = jnp.ones((1,), jnp.float32)
        add = jax.jit(lambda x: x + 1.0)
        jax.block_until_ready(add(tiny))  # compile outside timing
        put, disp, fetch = [], [], []
        host = np.ones((1,), np.float32)
        for _ in range(iters):
            t0 = time.perf_counter()
            dev = jax.device_put(host)
            jax.block_until_ready(dev)
            put.append((time.perf_counter() - t0) * 1e3)
            t0 = time.perf_counter()
            jax.block_until_ready(add(tiny))
            disp.append((time.perf_counter() - t0) * 1e3)
            t0 = time.perf_counter()
            jax.device_get(tiny)
            fetch.append((time.perf_counter() - t0) * 1e3)
        floor = {
            "put_ms": round(float(np.percentile(put, 50)), 4),
            "dispatch_ms": round(float(np.percentile(disp, 50)), 4),
            "fetch_ms": round(float(np.percentile(fetch, 50)), 4),
            "iters": iters,
        }
        print(
            "transport floor: "
            f"put={floor['put_ms']}ms dispatch={floor['dispatch_ms']}ms "
            f"fetch={floor['fetch_ms']}ms",
            file=sys.stderr,
        )
        return floor
    except Exception as e:  # noqa: BLE001 — evidence-only, never fatal
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _backend_evidence() -> dict:
    """Backend identity for the evidence record (safe pre-init): the
    RESOLVED jax backend, device kind/count, and jax version — the
    provenance stamp that makes a silent CPU fallback visible in every
    BENCH json instead of a 'cpu' row posing as TPU trajectory
    (BENCH_r0*.json all fell to CPU without saying so loudly)."""
    try:
        import jax

        devices = jax.devices()
        return {
            "backend": jax.default_backend(),
            "devices": [str(d) for d in devices],
            "device_kind": (
                devices[0].device_kind if devices else None
            ),
            "device_count": len(devices),
            "jax_version": jax.__version__,
        }
    except Exception as e:  # noqa: BLE001
        return {"backend_error": f"{type(e).__name__}: {e}"[:200]}


def warn_cpu_fallback(backend_info: dict) -> None:
    """The LOUD warning: a bench run that resolved to the CPU backend
    is a trajectory number, not a TPU claim — say so where the driver
    (and every human reading the captured stderr) cannot miss it."""
    if backend_info.get("backend") == "cpu":
        print(
            "=" * 72 + "\n"
            "WARNING: jax resolved to the CPU backend — this run is a "
            "CPU\ntrajectory number, NOT a TPU measurement. The result "
            "json carries\nbackend/device_kind/jax_version provenance; "
            "do not read it as a\nreal-chip claim.\n" + "=" * 72,
            file=sys.stderr,
        )


def _write_evidence(rec: dict) -> None:
    """Append the full raw record; never let evidence IO break the ONE
    JSON line contract."""
    try:
        full = {
            "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
                timespec="seconds"
            ),
            "argv": sys.argv[1:],
            **_backend_evidence(),
            **EVIDENCE,
            "result": rec,
        }
        # a run under a profiler/tracer is a DIAGNOSTIC, not a
        # measurement — instrumentation overhead inflates every number
        # (an r5 cProfile run recorded a 3.5x-inflated e2e tick before
        # this flag existed). Tag it so evidence consumers can filter.
        # On 3.12+ cProfile registers via sys.monitoring, not
        # sys.setprofile, so both registries are consulted.
        tool = None
        monitoring = getattr(sys, "monitoring", None)
        if monitoring is not None:
            tool = monitoring.get_tool(
                monitoring.PROFILER_ID
            ) or monitoring.get_tool(monitoring.DEBUGGER_ID)
        if (
            sys.getprofile() is not None
            or sys.gettrace() is not None
            or tool is not None
        ):
            full["diagnostic"] = "profiled"
        os.makedirs(EVIDENCE_DIR, exist_ok=True)
        line = json.dumps(full)
        with open(os.path.join(EVIDENCE_DIR, "runs.jsonl"), "a") as f:
            f.write(line + "\n")
        with open(os.path.join(EVIDENCE_DIR, "latest.json"), "w") as f:
            f.write(line + "\n")
    except Exception as e:  # noqa: BLE001
        print(f"evidence write failed: {e}", file=sys.stderr)


def probe_real_devices(
    probe_timeout: float = 120.0,
    retries: int = 2,
    hang_schedule: tuple = (),
):
    """Shared probe (utils/backend.py): (device_count, reason-if-failed)."""
    from karpenter_tpu.utils.backend import probe_default_backend

    return probe_default_backend(probe_timeout, retries, hang_schedule)


def ensure_backend(
    probe_timeout: float = 120.0,
    retries: int = 2,
    hang_schedule: tuple = (),
) -> str:
    """Make SOME backend usable before the first in-process jax call
    (utils/backend.py has the rationale). Returns '' when the default
    backend is healthy, else the reason for the CPU fallback.

    Unlike the control-plane entry points (fast CPU fallback on a hung
    tunnel), the benchmark waits out an outage on ``hang_schedule``: a
    CPU p50 at 100k scale is ~40x over budget and proves nothing about
    the design, so burning minutes on the chance the tunnel recovers is
    the right trade (round 2 lost its driver capture to exactly this)."""
    from karpenter_tpu.utils.backend import ensure_usable_backend

    return ensure_usable_backend(probe_timeout, retries, hang_schedule)


def _parse_hang_schedule(spec: str) -> tuple:
    """argparse type for --probe-hang-schedule: bad input must fail at
    parse time with rc 2, not surface later as a recorded evidence line
    (the blanket except in main emits JSON and exits 0)."""
    try:
        delays = tuple(float(d) for d in spec.split(",") if d.strip())
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected comma-separated seconds, got {spec!r}"
        )
    if any(d < 0 or not math.isfinite(d) for d in delays):
        raise argparse.ArgumentTypeError(
            f"negative or non-finite delay in {spec!r}"
        )
    return delays


def emit(
    metric: str,
    value,
    note: str = "",
    error: str = "",
    against_baseline: bool = True,
) -> None:
    """The ONE JSON line the driver records. Every exit path goes through
    here so a transient failure can never erase the round's evidence
    again. against_baseline=False suppresses the ratio for measurements
    the 200 ms full-tick budget doesn't apply to (e.g. --host-only,
    whose device half is deliberately stubbed)."""
    backend_info = _backend_evidence()
    rec = {
        "metric": metric,
        "value": (round(value, 3) if value is not None else None),
        "unit": "ms",
        "vs_baseline": (
            round(BASELINE_MS / value, 3)
            if value and against_baseline
            else None
        ),
        # backend provenance stamped into the BENCH json itself (not
        # just the evidence sidecar): resolved backend, device
        # kind/count, jax version — no more silent "cpu" rows posing
        # as TPU trajectory
        "backend": backend_info.get("backend"),
        "device_kind": backend_info.get("device_kind"),
        "device_count": backend_info.get("device_count"),
        "jax_version": backend_info.get("jax_version"),
    }
    if note:
        rec["note"] = note
    if error:
        rec["error"] = error
    warn_cpu_fallback(backend_info)
    _write_evidence(rec)
    print(json.dumps(rec))


def build_inputs(
    pods: int, types: int, taints: int, labels: int, seed: int,
    affinity: float = 0.0, anti: float = 0.0,
):
    import jax.numpy as jnp

    from karpenter_tpu.ops.binpack import BinPackInputs

    rng = np.random.default_rng(seed)
    R = 3  # cpu, memory, pods
    # pod requests: cpu in cores, memory in GiB, 1 pod slot
    req = np.stack(
        [
            rng.uniform(0.05, 8.0, pods),
            rng.uniform(0.1, 32.0, pods),
            np.ones(pods),
        ],
        axis=1,
    ).astype(np.float32)
    # instance types: cpu 2..128 cores, proportional memory, 110 pod slots
    cpu = rng.choice([2, 4, 8, 16, 32, 64, 96, 128], types).astype(np.float32)
    mem = cpu * rng.choice([2.0, 4.0, 8.0], types).astype(np.float32)
    alloc = np.stack([cpu, mem, np.full(types, 110.0, np.float32)], axis=1)
    intol = rng.random((pods, taints)) < 0.05
    group_taints = rng.random((types, taints)) < 0.1
    required = rng.random((pods, labels)) < 0.03
    group_labels = rng.random((types, labels)) < 0.8
    forbidden = None
    if affinity > 0:
        # fraction `affinity` of pods carry required node affinity; as in
        # production, pods share a handful of distinct affinity shapes —
        # each shape is a prototype forbidden row over the groups (the
        # host-evaluated matchExpression verdicts)
        prototypes = rng.random((4, types)) < 0.3
        which = rng.integers(0, prototypes.shape[0], pods)
        forbidden = prototypes[which] & (rng.random((pods, 1)) < affinity)
    return BinPackInputs(
        pod_requests=jnp.asarray(req),
        pod_valid=jnp.ones((pods,), bool),
        pod_intolerant=jnp.asarray(intol),
        pod_required=jnp.asarray(required),
        group_allocatable=jnp.asarray(alloc),
        group_taints=jnp.asarray(group_taints),
        group_labels=jnp.asarray(group_labels),
        pod_group_forbidden=(
            None if forbidden is None else jnp.asarray(forbidden)
        ),
        pod_exclusive=(
            # fraction `anti` of pods carry hostname self-anti-affinity
            # (one replica per node): the encoder's pod_exclusive operand
            None
            if anti <= 0
            else jnp.asarray(rng.random(pods) < anti)
        ),
    )


def build_multicluster_inputs(
    pods: int, clusters: int, types_per_cluster: int,
    taints: int, labels: int, seed: int, flex_fraction: float = 0.3,
):
    """BASELINE.json config 5: spot-interruption re-pack across clusters.

    K clusters each contribute types_per_cluster node groups carrying a
    cluster-identity label (first K slots of the label universe). Pods are
    spot-interruption refugees: 70% must stay in their home cluster
    (required cluster label — the nodeSelector a real multi-cluster
    scheduler would stamp), 30% are flexible and may re-pack anywhere.
    Same solver, same encoding — the cluster boundary IS a label
    constraint, so multi-cluster costs nothing extra on device.
    """
    import dataclasses

    import jax.numpy as jnp

    types = clusters * types_per_cluster
    base = build_inputs(pods, types, taints, labels, seed)
    rng = np.random.default_rng(seed + 1)

    group_labels = np.asarray(base.group_labels).copy()
    group_labels[:, :clusters] = False
    for c in range(clusters):
        group_labels[
            c * types_per_cluster : (c + 1) * types_per_cluster, c
        ] = True

    pod_required = np.asarray(base.pod_required).copy()
    pod_required[:, :clusters] = False
    home = rng.integers(0, clusters, pods)
    pinned = rng.random(pods) >= flex_fraction
    pod_required[np.arange(pods)[pinned], home[pinned]] = True

    return dataclasses.replace(
        base,
        group_labels=jnp.asarray(group_labels),
        pod_required=jnp.asarray(pod_required),
    )


# -- device-resident fleet state (make bench-resident) ------------------------


def _resident_world(pods: int, types: int, seed: int):
    """(cache, profiles, delta): a watch-fed pending-pod arena of `pods`
    DISTINCT shapes (the adversarial fleet — replicated workloads dedup
    away and make residency trivially cheap) over `types` group
    profiles, plus a private SnapshotDeltaCache. The REAL encode
    pipeline: churn events -> arena -> delta splice -> scatter plan."""
    from karpenter_tpu.api.core import Container, ObjectMeta, Pod, PodSpec
    from karpenter_tpu.api.core import PodStatus
    from karpenter_tpu.metrics.producers.pendingcapacity.encoder import (
        SnapshotDeltaCache,
    )
    from karpenter_tpu.store.columnar import PendingPodCache
    from karpenter_tpu.utils.quantity import Quantity

    rng = np.random.default_rng(seed)
    cache = PendingPodCache(store=None, capacity=2 * pods)

    def make_pod(name, cpu_millis):
        return Pod(
            metadata=ObjectMeta(name=name, namespace="default"),
            spec=PodSpec(containers=[Container(requests={
                "cpu": Quantity.parse(f"{cpu_millis}m"),
            })]),
            status=PodStatus(phase="Pending"),
        )

    for i in range(pods):
        p = make_pod(f"p{i}", 50 + i)  # every pod a distinct shape
        cache._upsert((p.metadata.namespace, p.metadata.name), p)
    profiles = []
    t_rng = np.random.default_rng(seed + 1)
    for t in range(types):
        cpu = float(t_rng.integers(2, 129))
        profiles.append((
            {"cpu": cpu, "memory": cpu * 4.0 * 1024**3, "pods": 110.0},
            {("pool", f"g{t}")},
            set(),
        ))
    return cache, profiles, SnapshotDeltaCache(), make_pod, rng


def _append_resident_row(path: str, record: dict) -> None:
    marker = "## Device-resident fleet state (make bench-resident)"
    header = (
        f"\n{marker}\n\n"
        "Steady-state tick latency with the device-resident fleet "
        "state ON vs OFF, interleaved over one watch-fed world (each "
        "tick: delta encode shared, then the SAME inputs dispatched "
        "through a resident-ON and a resident-OFF service back to back "
        "— drift cancels pairwise). Columns: churn ticks in the "
        "SHIPPED mode (scatter auto-gated to accelerator backends), "
        "unchanged-fleet ticks (the identity hit: zero encode, upload "
        "p50 ~0), and the forced-scatter mechanism speedup. "
        "HONEST READING on CPU: the \"device\" is host memory, so the "
        "scatter's copy-on-write cancels the memcpy upload it avoids "
        "(forced-scatter < 1x is expected there) and the auto gate "
        "keeps CPU on the hit/rebuild rungs; the transfer the scatter "
        "eliminates is the real accelerator link (PCIe / tunnel — "
        "PR 8 measured 35-70 ms/leaf through the tunnel).\n\n"
        "| Date | Backend | Pods x Types | Ticks | Churn p50 off/on "
        "(ms) | Churn speedup | Unchanged p50 off/on (ms) | Unchanged "
        "speedup | Unchanged upload p50 (ms) | Forced-scatter speedup "
        "(rows) |\n"
        "|---|---|---|---|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['pods']} x "
        f"{record['types']} | {record['ticks']} "
        f"| {record['solve_p50_off_ms']} / {record['solve_p50_on_ms']} "
        f"| {record['speedup']}x "
        f"| {record['unchanged_p50_off_ms']} / "
        f"{record['unchanged_p50_on_ms']} "
        f"| {record['unchanged_speedup']}x "
        f"| {record['unchanged_upload_p50_ms']} "
        f"| {record['scatter_speedup']}x "
        f"({record['scatter_rows_mean']}) |\n"
    )
    _append_table_row(path, marker, header, row)


def _resident_phase(  # lint: allow-complexity — one interleave arm per order flip + the unchanged-tick tail, each a couple of guards
    args, world, backend: str, scatter: str
) -> dict:
    """One interleaved resident-ON vs resident-OFF measurement phase
    over the shared churn world. `scatter` pins the ON service's
    scatter-rung gate ("auto" = the shipped default, "always" = force
    the changed-row scatter mechanism so its cost is measured even
    where the auto gate would hold). Parity is pinned every tick."""
    from karpenter_tpu.metrics.registry import GaugeRegistry
    from karpenter_tpu.solver import SolverService

    cache, profiles, delta, make_pod, rng, next_name = world
    svc_on = SolverService(registry=GaugeRegistry(), shard_threshold=0)
    svc_on._resident.scatter = scatter
    svc_off = SolverService(
        registry=GaugeRegistry(), shard_threshold=0, resident=False
    )
    on_ms, off_ms, scatter_rows, encode_ms = [], [], [], []

    def churn():
        cache._remove(
            ("default", f"p{int(rng.integers(0, next_name[0]))}")
        )
        p = make_pod(f"p{next_name[0]}", 50 + next_name[0])
        cache._upsert((p.metadata.namespace, p.metadata.name), p)
        next_name[0] += 1
        t0 = time.perf_counter()
        inputs = delta.encode(cache.snapshot(), profiles)
        encode_ms.append((time.perf_counter() - t0) * 1e3)
        return inputs

    def timed(svc, inputs):
        t0 = time.perf_counter()
        out = svc.solve(inputs, buckets=args.buckets, backend=backend)
        return (time.perf_counter() - t0) * 1e3, out

    try:
        for _ in range(5):  # warmup: compiles, first encodes, residency
            inputs = churn()
            timed(svc_on, inputs)
            timed(svc_off, inputs)
        for round_i in range(args.resident_ticks):
            inputs = churn()
            if round_i % 2 == 0:
                t_off, out_off = timed(svc_off, inputs)
                t_on, out_on = timed(svc_on, inputs)
            else:
                t_on, out_on = timed(svc_on, inputs)
                t_off, out_off = timed(svc_off, inputs)
            off_ms.append(t_off)
            on_ms.append(t_on)
            scatter_rows.append(svc_on._resident.last_scatter_rows)
            # parity pinned FIRST, every tick: resident == re-upload
            np.testing.assert_array_equal(
                np.asarray(out_on.assigned),
                np.asarray(out_off.assigned),
            )
            assert int(out_on.unschedulable) == int(
                out_off.unschedulable
            )
        # unchanged-fleet ticks: the SAME inputs object re-dispatches
        # against the resident buffers — zero encode, upload p50 ~0
        inputs = churn()
        timed(svc_on, inputs)
        timed(svc_off, inputs)
        hits_before = svc_on.stats.resident_hits
        unchanged_on, unchanged_off = [], []
        for _ in range(10):
            t_hit, _ = timed(svc_on, inputs)
            unchanged_on.append(t_hit)
            t_cold, _ = timed(svc_off, inputs)
            unchanged_off.append(t_cold)
        assert svc_on.stats.resident_hits - hits_before == 10
        uploads_on = list(svc_on._stages.get("upload", ()))
        uploads_off = list(svc_off._stages.get("upload", ()))
        stats = {
            "hits": svc_on.stats.resident_hits,
            "scatters": svc_on.stats.resident_scatters,
            "rebuilds": svc_on.stats.resident_rebuilds,
        }
    finally:
        svc_on.close()
        svc_off.close()
    p50_off = float(np.percentile(off_ms, 50))
    p50_on = float(np.percentile(on_ms, 50))
    return {
        "scatter_mode": scatter,
        "solve_p50_off_ms": round(p50_off, 3),
        "solve_p50_on_ms": round(p50_on, 3),
        "speedup": round(p50_off / p50_on, 2) if p50_on else None,
        "encode_p50_ms": round(float(np.percentile(encode_ms, 50)), 3),
        "scatter_rows_mean": int(np.mean(scatter_rows)),
        "upload_p50_off_ms": round(
            float(np.percentile(uploads_off, 50)), 4
        ) if uploads_off else None,
        "upload_p50_on_ms": round(
            float(np.percentile(uploads_on, 50)), 4
        ) if uploads_on else None,
        "unchanged_p50_on_ms": round(
            float(np.percentile(unchanged_on, 50)), 3
        ),
        "unchanged_p50_off_ms": round(
            float(np.percentile(unchanged_off, 50)), 3
        ),
        "unchanged_upload_p50_ms": round(
            float(np.percentile(uploads_on[-10:], 50)), 4
        ) if uploads_on else None,
        "solve_on_ms_raw": [round(t, 4) for t in on_ms],
        "solve_off_ms_raw": [round(t, 4) for t in off_ms],
        **stats,
    }


def run_resident(args, metric: str, note: str) -> None:
    """Device-resident fleet state: resident-ON vs resident-OFF over
    the identical churn-tick sequence, in the SHIPPED default mode
    (scatter auto-gated to accelerator backends) and with the scatter
    mechanism forced, plus the unchanged-tick identity-hit column
    (ISSUE 13 acceptance: honest note where the CPU transport floor
    mutes the win — on CPU "device" memory IS host memory, so the
    scatter's copy-on-write cancels the memcpy upload it avoids)."""
    import jax

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    backend = "xla" if args.backend in ("auto", "numpy") else args.backend
    cache, profiles, delta, make_pod, rng = _resident_world(
        args.pods, args.types, args.seed
    )
    world = (cache, profiles, delta, make_pod, rng, [args.pods])
    shipped = _resident_phase(args, world, backend, "auto")
    forced = _resident_phase(args, world, backend, "always")
    unchanged_speedup = (
        round(
            shipped["unchanged_p50_off_ms"]
            / shipped["unchanged_p50_on_ms"], 2,
        )
        if shipped["unchanged_p50_on_ms"]
        else None
    )
    record = {
        "config": f"{args.pods} pods x {args.types} types resident",
        "backend": jax.default_backend(),
        "pods": args.pods,
        "types": args.types,
        "ticks": args.resident_ticks,
        # headline: the shipped default
        "solve_p50_off_ms": shipped["solve_p50_off_ms"],
        "solve_p50_on_ms": shipped["solve_p50_on_ms"],
        "speedup": shipped["speedup"],
        "unchanged_p50_on_ms": shipped["unchanged_p50_on_ms"],
        "unchanged_p50_off_ms": shipped["unchanged_p50_off_ms"],
        "unchanged_speedup": unchanged_speedup,
        "unchanged_upload_p50_ms": shipped["unchanged_upload_p50_ms"],
        "encode_p50_ms": shipped["encode_p50_ms"],
        # the forced-scatter mechanism measurement
        "scatter_speedup": forced["speedup"],
        "scatter_rows_mean": forced["scatter_rows_mean"],
        "scatter_upload_p50_on_ms": forced["upload_p50_on_ms"],
        "upload_p50_off_ms": shipped["upload_p50_off_ms"],
        "hits": shipped["hits"],
        "rebuilds": shipped["rebuilds"],
        "scatters": forced["scatters"],
    }
    record_evidence(
        resident_shipped=shipped, resident_forced=forced,
        resident=record,
    )
    print(
        f"shipped: solve p50 off={record['solve_p50_off_ms']}ms "
        f"on={record['solve_p50_on_ms']}ms "
        f"speedup={record['speedup']}x | unchanged tick "
        f"{record['unchanged_p50_off_ms']}ms -> "
        f"{record['unchanged_p50_on_ms']}ms "
        f"({record['unchanged_speedup']}x, upload p50 "
        f"{record['unchanged_upload_p50_ms']}ms) | forced scatter "
        f"{record['scatter_speedup']}x @ {record['scatter_rows_mean']} "
        f"rows",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_to_baseline(
            f"{record['config']} ({record['backend']})", record
        )
    if args.append_benchmarks:
        _append_resident_row(args.append_benchmarks, record)
    emit(
        f"{metric} ({jax.default_backend()})",
        record["solve_p50_on_ms"],
        note=(
            f"{note}; " if note else ""
        ) + f"resident churn speedup {record['speedup']}x, "
        f"unchanged-tick {record['unchanged_speedup']}x (upload p50 "
        f"{record['unchanged_upload_p50_ms']}ms), forced-scatter "
        f"{record['scatter_speedup']}x on this backend",
        against_baseline=False,
    )


def main() -> None:  # lint: allow-complexity — bench config dispatch, one arm per measured configuration
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=100_000)
    ap.add_argument("--types", type=int, default=300)
    ap.add_argument("--taints", type=int, default=64)
    ap.add_argument("--labels", type=int, default=64)
    ap.add_argument("--buckets", type=int, default=32)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--affinity", type=float, default=0.0,
        help="fraction of pods carrying required node affinity (adds the "
        "pod_group_forbidden [P, T] mask operand to the solve)",
    )
    ap.add_argument(
        "--anti", type=float, default=0.0,
        help="fraction of pods carrying hostname self-anti-affinity — "
        "one replica per node (adds the pod_exclusive [P] operand to "
        "the solve)",
    )
    ap.add_argument(
        "--spread", type=float, default=0.0,
        help="with --e2e: fraction of pods carrying a hard zone "
        "topologySpreadConstraint with a self-matching selector; nodes "
        "gain zone labels and a slab of BOUND pods churns each tick, so "
        "every measured tick pays the existing-pod occupancy census "
        "(DomainCensus) recompute on top of the split expansion",
    )
    ap.add_argument(
        "--backend",
        choices=("auto", "xla", "pallas", "numpy"),
        default="auto",
        help="auto = fused Pallas kernel on TPU, the numpy degraded-mode "
        "program on a CPU default backend, XLA elsewhere",
    )
    ap.add_argument(
        "--churn",
        type=int,
        default=-1,
        help="pods replaced per e2e tick through the store watch path "
        "(-1 = 1%% of --pods); keeps the e2e number honest: every tick "
        "pays incremental feed maintenance + re-encode + re-transfer",
    )
    ap.add_argument("--probe-timeout", type=float, default=120.0)
    ap.add_argument("--probe-retries", type=int, default=2)
    ap.add_argument(
        "--probe-hang-schedule",
        type=_parse_hang_schedule,
        default="300,600",
        help="comma-separated extra delays (s) slept between hang-probe "
        "cycles to wait out a HUNG tunnel (each cycle also burns "
        "--probe-timeout s hanging, so '300,600' re-probes at ~t+7m and "
        "~t+19m); '' = give up after the first hang like the "
        "control-plane entry points. "
        "Ignored by --mesh, which needs more devices than the one real "
        "chip and so always measures on the virtual CPU mesh",
    )
    ap.add_argument(
        "--slices",
        type=int,
        default=1,
        metavar="S",
        help="with --mesh: model S TPU slices (3D slice x pods x groups "
        "mesh; pod rows shard across slices, the one histogram reduction "
        "rides DCN)",
    )
    ap.add_argument(
        "--clusters",
        type=int,
        default=0,
        metavar="K",
        help="multi-cluster re-pack (BASELINE config 5): K clusters of "
        "--types node groups each; 70%% of pods pinned to their home "
        "cluster via required labels, 30%% free to re-pack across",
    )
    ap.add_argument(
        "--decide",
        type=int,
        default=0,
        metavar="N",
        help="benchmark the batched HPA decision kernel over a fleet of "
        "N autoscalers x 4 metrics instead of the bin-pack",
    )
    ap.add_argument(
        "--solver-service",
        action="store_true",
        help="benchmark the shared solve service (karpenter_tpu/solver): "
        "--concurrency threads submit concurrently through the coalescing "
        "queue vs. the same load on direct ops/binpack calls; reports both "
        "p50/p99 plus coalesce factor and dispatch counts",
    )
    ap.add_argument(
        "--concurrency",
        type=int,
        default=8,
        help="with --solver-service/--hotpath: concurrent submitter "
        "threads",
    )
    ap.add_argument(
        "--hotpath",
        action="store_true",
        help="benchmark the solver-service HOT PATH: idle-queue "
        "single-caller latency through the service vs a direct "
        "ops/binpack call (the adaptive-window acceptance ratio), the "
        "coalesce factor preserved under --concurrency concurrent "
        "callers, and the per-stage breakdown (queue-wait / pad / "
        "dispatch / scatter) from the service's latency rings",
    )
    ap.add_argument(
        "--consolidate",
        action="store_true",
        help="benchmark batched consolidation candidate evaluation "
        "(karpenter_tpu/consolidation): --candidates drain candidates "
        "evaluated in ONE service.consolidate dispatch vs. the same "
        "masked bin-packs submitted sequentially; reports candidates/sec "
        "both ways and the speedup",
    )
    ap.add_argument(
        "--candidates",
        type=int,
        default=32,
        help="with --consolidate: cluster nodes (every loaded node is a "
        "drain candidate); --pods spread across them",
    )
    ap.add_argument(
        "--preempt",
        action="store_true",
        help="benchmark batched eviction planning (ops/preempt.py via "
        "service.preempt): --candidates pending pods planned against "
        "--types node columns x --pods victims in ONE dispatch vs. the "
        "same plans submitted one candidate at a time; reports "
        "candidates/sec both ways and the speedup",
    )
    ap.add_argument(
        "--forecast",
        action="store_true",
        help="benchmark the batched forecast kernel "
        "(karpenter_tpu/forecast): --series metric series forecast in "
        "ONE device dispatch vs the same series dispatched one at a "
        "time; reports series/sec both ways and the speedup",
    )
    ap.add_argument(
        "--series",
        type=int,
        default=512,
        help="with --forecast: number of metric series in the fleet",
    )
    ap.add_argument(
        "--history",
        type=int,
        default=64,
        help="with --forecast: history samples per series",
    )
    ap.add_argument(
        "--journal",
        action="store_true",
        help="benchmark protective-state journal overhead on the "
        "reconcile hot path (karpenter_tpu/recovery): the same seeded "
        "world ticks with the journal ON vs OFF (target: <5%% tick-"
        "latency regression), plus raw StateJournal.append throughput",
    )
    ap.add_argument(
        "--journal-ticks",
        type=int,
        default=40,
        help="with --journal: measured manager ticks per configuration",
    )
    ap.add_argument(
        "--trace",
        action="store_true",
        help="benchmark reconcile-tracing overhead on the hot path "
        "(karpenter_tpu/observability): the same seeded world ticks "
        "with the tracer ENABLED vs DISABLED (target: <5%% tick-"
        "latency regression), plus raw span open/close throughput",
    )
    ap.add_argument(
        "--trace-ticks",
        type=int,
        default=40,
        help="with --trace: measured manager ticks per configuration",
    )
    ap.add_argument(
        "--provenance",
        action="store_true",
        help="benchmark decision-provenance overhead on the reconcile "
        "hot path (karpenter_tpu/observability/provenance.py): the "
        "same seeded world ticks with the ledger ENABLED vs DISABLED "
        "interleaved (target: <=5%% tick-latency regression), plus raw "
        "batch-commit throughput",
    )
    ap.add_argument(
        "--provenance-ticks",
        type=int,
        default=200,
        help="with --provenance: measured manager ticks per "
        "configuration",
    )
    ap.add_argument(
        "--cost",
        action="store_true",
        help="benchmark the batched multi-objective cost/SLO refinement "
        "(ops/cost.py via karpenter_tpu/cost): --cost-rows autoscalers "
        "refined in ONE device dispatch vs the same rows dispatched one "
        "HA at a time; pins XLA == numpy bit-parity on every output "
        "before timing; reports rows/sec both ways and the speedup",
    )
    ap.add_argument(
        "--cost-rows",
        type=int,
        default=512,
        help="with --cost: SLO-opted autoscaler rows in the fleet",
    )
    ap.add_argument(
        "--cost-metrics",
        type=int,
        default=3,
        help="with --cost: metrics per autoscaler row",
    )
    ap.add_argument(
        "--poolgroup",
        action="store_true",
        help="benchmark the joint pool-group allocation "
        "(ops/poolgroup.py via karpenter_tpu/poolgroups): "
        "--poolgroup-groups groups' candidate ladders scored in ONE "
        "batched dispatch vs the groups*pools per-pool cost dispatches "
        "the joint plane replaces; pins XLA == numpy bit-parity on "
        "every output leaf AND joint == per-pool cost ladder under "
        "slack constraints before timing",
    )
    ap.add_argument(
        "--poolgroup-groups",
        type=int,
        default=64,
        help="with --poolgroup: pool groups in the fleet",
    )
    ap.add_argument(
        "--poolgroup-pools",
        type=int,
        default=4,
        help="with --poolgroup: member pools per group (2..4)",
    )
    ap.add_argument(
        "--poolgroup-metrics",
        type=int,
        default=3,
        help="with --poolgroup: metrics per member pool",
    )
    ap.add_argument(
        "--multitenant",
        action="store_true",
        help="benchmark the multi-tenant control plane "
        "(docs/multitenancy.md): --tenants simulated tenant clusters' "
        "decide+cost matrices through ONE MultiTenantScheduler "
        "(cross-tenant concatenated dispatches) vs a sequential "
        "per-tenant loop through the same SolverService seam; "
        "cross-tenant == independent parity is pinned on the device "
        "AND numpy paths before timing",
    )
    ap.add_argument(
        "--tenants",
        type=int,
        default=1000,
        help="with --multitenant: simulated tenant cluster count",
    )
    ap.add_argument(
        "--tenant-rows",
        type=int,
        default=4,
        help="with --multitenant: autoscaler rows per tenant cluster",
    )
    ap.add_argument(
        "--tenant-metrics",
        type=int,
        default=2,
        help="with --multitenant: metrics per autoscaler row",
    )
    ap.add_argument(
        "--shard",
        action="store_true",
        help="benchmark the SHARDED dispatch strategy (docs/solver-"
        "service.md 'Sharded dispatch'): one fleet-scale bin-pack "
        "(--pods x --types) through the SolverService seam on meshes of "
        "--shard-scaling device counts (virtual CPU devices when real "
        "chips are absent — scale evidence for the sharded program, not "
        "a TPU perf claim), with sharded outputs pinned against the "
        "single-device and numpy paths before timing",
    )
    ap.add_argument(
        "--shard-scaling",
        default="1,2,4,8",
        help="with --shard: comma-separated mesh device counts; 1 = the "
        "single-device baseline through the same service seam",
    )
    ap.add_argument(
        "--constraints",
        action="store_true",
        help="benchmark the declarative constraint plane "
        "(docs/constraints.md): ONE batched constrained solve (spread "
        "+ reservation + anti-affinity + compact groups as masked "
        "integer operands) vs the per-group sequential loop a "
        "constraint-naive integration would run, interleaved arms, "
        "with per-group verdict parity pinned before timing",
    )
    ap.add_argument(
        "--constraint-groups",
        type=int,
        default=8,
        help="with --constraints: constraint group count (cycling "
        "spread/reservation/anti/compact kinds)",
    )
    ap.add_argument(
        "--publish-baseline",
        action="store_true",
        help="with --solver-service: write the result into BASELINE.json's "
        "'published' map",
    )
    ap.add_argument(
        "--append-benchmarks",
        default="",
        metavar="FILE",
        help="with --solver-service: append a markdown row to this "
        "benchmarks table (e.g. docs/BENCHMARKS.md)",
    )
    ap.add_argument(
        "--resident",
        action="store_true",
        help="benchmark the device-resident fleet state: churn-tick "
        "solve latency with residency ON (changed-row scatter) vs OFF "
        "(full re-upload) interleaved over one watch-fed world, plus "
        "the unchanged-tick identity-hit column "
        "(docs/solver-service.md 'Device-resident fleet state')",
    )
    ap.add_argument(
        "--resident-ticks",
        type=int,
        default=60,
        help="with --resident: measured churn ticks per configuration",
    )
    ap.add_argument(
        "--introspect",
        action="store_true",
        help="benchmark the solver introspection plane "
        "(docs/observability.md 'Device telemetry & introspection'): "
        "reconcile tick latency with the compile ledger + device "
        "memory telemetry + XLA cost attribution ENABLED vs DISABLED, "
        "interleaved over the shared churn world (the bench-trace "
        "discipline; target <=2% median paired overhead)",
    )
    ap.add_argument(
        "--introspect-ticks",
        type=int,
        default=200,
        help="with --introspect: measured ticks per configuration",
    )
    ap.add_argument(
        "--eventloop",
        action="store_true",
        help="benchmark the event-driven reconcile loop: the seeded "
        "pod-arrival trace replayed tick-paced vs event-driven "
        "(simulate.simulate_eventloop), reporting e2e p50/p99 off the "
        "karpenter_reconcile_e2e_seconds histogram, the solve-"
        "amplification factor, and the churn-storm coalescing proof "
        "(docs/solver-service.md 'Event-driven reconcile')",
    )
    ap.add_argument(
        "--eventloop-ticks",
        type=int,
        default=40,
        help="with --eventloop: backstop ticks per replayed arm",
    )
    ap.add_argument(
        "--eventloop-arrivals",
        type=int,
        default=60,
        help="with --eventloop: seeded pod arrivals in the trace",
    )
    ap.add_argument(
        "--eventloop-storm",
        type=int,
        default=1000,
        help="with --eventloop: churn-storm events in one debounce "
        "window",
    )
    ap.add_argument(
        "--eventloop-debounce",
        type=float,
        default=0.05,
        help="with --eventloop: replayed event-pass debounce window "
        "seconds",
    )
    ap.add_argument(
        "--simlab",
        action="store_true",
        help="benchmark SimLab batched cluster stepping "
        "(docs/simulator.md): N independently-seeded simulated "
        "clusters advanced as ONE vmapped sim_rollout dispatch through "
        "the SolverService seam vs the per-cluster sequential loop a "
        "simulator-naive harness would run (N dispatches of the same "
        "compiled program); batched == sequential == numpy parity "
        "pinned bitwise before timing",
    )
    ap.add_argument(
        "--simlab-clusters",
        type=int,
        default=256,
        help="with --simlab: simulated clusters per batched dispatch",
    )
    ap.add_argument(
        "--simlab-ticks",
        type=int,
        default=64,
        help="with --simlab: episode length in ticks per cluster",
    )
    ap.add_argument(
        "--simlab-rows",
        type=int,
        default=8,
        help="with --simlab: HA rows (replica columns) per cluster",
    )
    ap.add_argument(
        "--fusedtick",
        action="store_true",
        help="benchmark the fused steady-state tick "
        "(docs/solver-service.md 'Fused tick'): the whole fleet's "
        "forecast -> decide -> cost ladder as ONE compiled program "
        "through SolverService.fused_tick vs the chained per-stage "
        "wire (one program per stage + host glue between); fused == "
        "chained == numpy pinned bitwise before timing, plus the "
        "--fused-tick dispatches-per-tick collapse over the shared "
        "churn-runtime world",
    )
    ap.add_argument(
        "--fusedtick-rows",
        type=int,
        default=256,
        help="with --fusedtick: autoscaler rows per fleet batch",
    )
    ap.add_argument(
        "--fusedtick-metrics",
        type=int,
        default=3,
        help="with --fusedtick: metric columns per autoscaler row",
    )
    ap.add_argument(
        "--fusedtick-series",
        type=int,
        default=128,
        help="with --fusedtick: forecast series scattered into the "
        "fleet grid",
    )
    ap.add_argument(
        "--fusedtick-samples",
        type=int,
        default=32,
        help="with --fusedtick: history samples per forecast series",
    )
    ap.add_argument(
        "--fusedtick-ticks",
        type=int,
        default=40,
        help="with --fusedtick: timed reconcile ticks per runtime arm "
        "(the dispatches-per-tick observable)",
    )
    ap.add_argument(
        "--failover",
        action="store_true",
        help="benchmark replicated-control-plane failover "
        "(karpenter_tpu/replication): the seeded leader-kill world at "
        "fleet scale — kill the biggest owner mid-storm, measure the "
        "handoff blackout (ticks from kill to every victim tenant back "
        "at its desired level) and audit exactly-once actuation across "
        "the handoff",
    )
    ap.add_argument(
        "--failover-tenants",
        type=int,
        default=256,
        help="with --failover: tenants partitioned across the replicas",
    )
    ap.add_argument(
        "--failover-replicas",
        type=int,
        default=4,
        help="with --failover: solver replicas contending for partitions",
    )
    ap.add_argument(
        "--failover-partitions",
        type=int,
        default=16,
        help="with --failover: tenant partitions (lease granularity)",
    )
    ap.add_argument(
        "--failover-ticks",
        type=int,
        default=40,
        help="with --failover: total simulated ticks (kill at tick 12)",
    )
    ap.add_argument(
        "--e2e",
        action="store_true",
        help="headline the full reconcile tick (columnar-cache snapshot + "
        "encode + host->device transfer + solve) instead of the solver",
    )
    ap.add_argument(
        "--host-only",
        action="store_true",
        help="with --e2e: swap the device solve for a shape-correct no-op "
        "so the tick measures ONLY the host half (store churn + watch "
        "fan-out + profiles + snapshot + dedup encode + status/gauge "
        "writes) — the docs/BENCHMARKS.md host-path number",
    )
    ap.add_argument(
        "--mesh",
        type=int,
        default=0,
        metavar="N",
        help="run the solve sharded over an N-device pods x groups mesh "
        "(virtual CPU devices when N real chips are absent), assert "
        "equality with the single-device solve, report the sharded p50",
    )
    args = ap.parse_args()

    if args.clusters and (args.mesh or args.e2e or args.decide):
        ap.error(
            "--clusters models its own workload (BASELINE config 5) and "
            "cannot combine with --mesh/--e2e/--decide; run it standalone"
        )
    if args.affinity and (args.clusters or args.decide):
        ap.error(
            "--affinity applies to the solver bench, --mesh, and --e2e; "
            "--clusters/--decide build their own workloads"
        )
    if not 0.0 <= args.affinity <= 1.0:
        ap.error("--affinity must be a fraction in [0, 1]")
    if args.anti and (args.clusters or args.decide or args.mesh):
        ap.error(
            "--anti applies to the solver bench and --e2e (which builds "
            "real podAntiAffinity specs); --clusters/--decide/--mesh "
            "build their own workloads"
        )
    if not 0.0 <= args.anti <= 1.0:
        ap.error("--anti must be a fraction in [0, 1]")
    if args.spread and not args.e2e:
        ap.error("--spread applies to --e2e only (it builds real "
                 "topologySpreadConstraint specs + bound-pod occupancy)")
    if not 0.0 <= args.spread <= 1.0:
        ap.error("--spread must be a fraction in [0, 1]")
    if args.slices < 1:
        ap.error("--slices must be >= 1")
    if args.slices > 1 and not args.mesh:
        ap.error("--slices requires --mesh")
    if args.slices > 1 and args.mesh % args.slices:
        ap.error(
            f"--mesh {args.mesh} not divisible into --slices {args.slices}"
        )
    if args.host_only and not args.e2e:
        ap.error("--host-only only applies to --e2e")
    if args.solver_service and (
        args.mesh or args.e2e or args.decide or args.clusters
    ):
        ap.error(
            "--solver-service benchmarks the service front door on the "
            "plain solver workload; it cannot combine with "
            "--mesh/--e2e/--decide/--clusters"
        )
    if args.hotpath and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service or args.consolidate
    ):
        ap.error(
            "--hotpath benchmarks the service hot path on the plain "
            "solver workload; it cannot combine with other modes"
        )
    if args.consolidate and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service
    ):
        ap.error(
            "--consolidate builds its own cluster workload; it cannot "
            "combine with --mesh/--e2e/--decide/--clusters/"
            "--solver-service"
        )
    if args.candidates < 2:
        ap.error("--candidates must be >= 2 (a drain needs a receiver)")
    if args.concurrency < 1:
        ap.error("--concurrency must be >= 1")
    if args.forecast and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service or args.hotpath or args.consolidate
    ):
        ap.error(
            "--forecast builds its own workload (metric histories); it "
            "cannot combine with other modes"
        )
    if args.preempt and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service or args.hotpath or args.consolidate
        or args.forecast
    ):
        ap.error(
            "--preempt builds its own workload (candidates x nodes x "
            "victims); it cannot combine with other modes"
        )
    if args.series < 2:
        ap.error("--series must be >= 2")
    if args.history < 4:
        ap.error("--history must be >= 4")
    if args.journal and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service or args.hotpath or args.consolidate
        or args.forecast or args.preempt
    ):
        ap.error(
            "--journal builds its own ticking world; it cannot combine "
            "with other modes"
        )
    if args.trace and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service or args.hotpath or args.consolidate
        or args.forecast or args.preempt or args.journal or args.shard
    ):
        ap.error(
            "--trace builds its own ticking world; it cannot combine "
            "with other modes"
        )
    if args.provenance and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service or args.hotpath or args.consolidate
        or args.forecast or args.preempt or args.journal or args.trace
        or args.shard or args.multitenant or args.cost
    ):
        ap.error(
            "--provenance builds its own ticking world; it cannot "
            "combine with other modes"
        )
    if args.cost and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service or args.hotpath or args.consolidate
        or args.forecast or args.preempt or args.journal or args.trace
        or args.shard or args.provenance
    ):
        ap.error(
            "--cost builds its own workload (SLO-opted fleet rows); it "
            "cannot combine with other modes"
        )
    if args.cost_rows < 2:
        ap.error("--cost-rows must be >= 2")
    if args.cost_metrics < 1:
        ap.error("--cost-metrics must be >= 1")
    if args.poolgroup and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service or args.hotpath or args.consolidate
        or args.forecast or args.preempt or args.journal or args.trace
        or args.shard or args.provenance or args.cost
    ):
        ap.error(
            "--poolgroup builds its own workload (a fleet of pool "
            "groups); it cannot combine with other modes"
        )
    if args.poolgroup_groups < 1:
        ap.error("--poolgroup-groups must be >= 1")
    if not 2 <= args.poolgroup_pools <= 4:
        ap.error("--poolgroup-pools must be in 2..4")
    if args.poolgroup_metrics < 1:
        ap.error("--poolgroup-metrics must be >= 1")
    if args.multitenant and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service or args.hotpath or args.consolidate
        or args.forecast or args.preempt or args.journal or args.trace
        or args.cost or args.shard
    ):
        ap.error(
            "--multitenant builds its own workload (N tenant fleets); "
            "it cannot combine with other modes"
        )
    if args.multitenant and args.tenants < 2:
        ap.error("--tenants must be >= 2")
    if args.multitenant and (
        args.tenant_rows < 1 or args.tenant_metrics < 1
    ):
        ap.error("--tenant-rows and --tenant-metrics must be >= 1")
    if args.shard and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service or args.hotpath or args.consolidate
        or args.forecast or args.preempt or args.journal
    ):
        ap.error(
            "--shard benchmarks the service's sharded dispatch on the "
            "plain solver workload; it cannot combine with other modes"
        )
    if args.shard:
        try:
            scaling = [int(n) for n in args.shard_scaling.split(",")]
        except ValueError:
            ap.error(f"--shard-scaling {args.shard_scaling!r}: expected "
                     "comma-separated device counts")
        if not scaling or any(n < 1 for n in scaling):
            ap.error("--shard-scaling device counts must be >= 1")
        args.shard_scaling = scaling
    if args.resident and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service or args.hotpath or args.consolidate
        or args.forecast or args.preempt or args.journal or args.trace
        or args.shard or args.cost or args.multitenant or args.provenance
    ):
        ap.error(
            "--resident builds its own watch-fed churn world; it cannot "
            "combine with other modes"
        )
    if args.resident and args.resident_ticks < 4:
        ap.error("--resident-ticks must be >= 4")
    if args.eventloop and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service or args.hotpath or args.consolidate
        or args.forecast or args.preempt or args.journal or args.trace
        or args.shard or args.cost or args.multitenant
        or args.provenance or args.resident
    ):
        ap.error(
            "--eventloop replays its own two-arm arrival trace; it "
            "cannot combine with other modes"
        )
    if args.introspect and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service or args.hotpath or args.consolidate
        or args.forecast or args.preempt or args.journal or args.trace
        or args.shard or args.cost or args.multitenant
        or args.provenance or args.resident or args.eventloop
    ):
        ap.error(
            "--introspect builds its own ticking world; it cannot "
            "combine with other modes"
        )
    if args.introspect and args.introspect_ticks < 4:
        ap.error("--introspect-ticks must be >= 4")
    if args.eventloop and (
        args.eventloop_ticks < 4 or args.eventloop_arrivals < 1
        or args.eventloop_storm < 1 or args.eventloop_debounce <= 0
    ):
        ap.error(
            "--eventloop needs ticks >= 4, arrivals/storm >= 1, "
            "debounce > 0"
        )
    if args.constraints and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service or args.hotpath or args.consolidate
        or args.forecast or args.preempt or args.journal or args.trace
        or args.shard or args.cost or args.multitenant
        or args.provenance or args.resident or args.eventloop
        or args.introspect
    ):
        ap.error(
            "--constraints builds its own constrained workload; it "
            "cannot combine with other modes"
        )
    if args.constraints and args.constraint_groups < 1:
        ap.error("--constraint-groups must be >= 1")
    if args.simlab and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service or args.hotpath or args.consolidate
        or args.forecast or args.preempt or args.journal or args.trace
        or args.shard or args.cost or args.multitenant
        or args.provenance or args.resident or args.eventloop
        or args.introspect or args.constraints
    ):
        ap.error(
            "--simlab builds its own simulated-cluster workload; it "
            "cannot combine with other modes"
        )
    if args.simlab and (
        args.simlab_clusters < 2 or args.simlab_ticks < 4
        or args.simlab_rows < 1
    ):
        ap.error(
            "--simlab needs clusters >= 2, ticks >= 4, rows >= 1"
        )
    if args.fusedtick and (
        args.mesh or args.e2e or args.decide or args.clusters
        or args.solver_service or args.hotpath or args.consolidate
        or args.forecast or args.preempt or args.journal or args.trace
        or args.shard or args.cost or args.multitenant
        or args.provenance or args.resident or args.eventloop
        or args.introspect or args.constraints or args.simlab
        or args.failover
    ):
        ap.error(
            "--fusedtick builds its own fleet-batch workload; it "
            "cannot combine with other modes"
        )
    if args.fusedtick and (
        args.fusedtick_rows < 2 or args.fusedtick_metrics < 1
        or args.fusedtick_series < 1 or args.fusedtick_samples < 4
        or args.fusedtick_ticks < 4
    ):
        ap.error(
            "--fusedtick needs rows >= 2, metrics >= 1, series >= 1, "
            "samples >= 4, ticks >= 4"
        )
    if (args.publish_baseline or args.append_benchmarks) and not (
        args.solver_service or args.consolidate or args.hotpath
        or args.forecast or args.preempt or args.journal or args.shard
        or args.trace or args.cost or args.poolgroup
        or args.multitenant
        or args.provenance or args.resident or args.eventloop
        or args.introspect or args.constraints or args.simlab
        or args.fusedtick or args.failover
    ):
        ap.error(
            "--publish-baseline/--append-benchmarks only apply to "
            "--solver-service/--consolidate/--hotpath/--forecast/"
            "--preempt/--journal/--shard/--trace/--cost/--poolgroup/"
            "--multitenant/"
            "--provenance/--resident/--eventloop/--introspect/"
            "--constraints/--simlab/--fusedtick/--failover (nothing "
            "would be published otherwise)"
        )

    if args.fusedtick:
        metric = (
            f"fused steady-state tick p50, {args.fusedtick_rows} "
            f"autoscalers x {args.fusedtick_metrics} metrics x "
            f"{args.fusedtick_series} forecast series (one fused "
            f"forecast->decide->cost program vs the chained per-stage "
            f"wire, interleaved; bitwise parity pinned)"
        )
    elif args.failover:
        metric = (
            f"failover handoff blackout p99, {args.failover_tenants} "
            f"tenants x {args.failover_replicas} replicas over "
            f"{args.failover_partitions} partitions (leader killed "
            f"mid-storm; exactly-once actuation journal-audited)"
        )
    elif args.simlab:
        metric = (
            f"vmapped batched cluster-stepping p50, "
            f"{args.simlab_clusters} clusters x {args.simlab_ticks} "
            f"ticks x {args.simlab_rows} rows (one sim_rollout "
            f"dispatch vs the per-cluster sequential loop; numpy "
            f"parity pinned)"
        )
    elif args.constraints:
        metric = (
            f"batched constrained solve p50, {args.pods} pods x "
            f"{args.types} instance types x {args.constraint_groups} "
            f"constraint groups (one masked-operand dispatch vs the "
            f"per-group sequential loop, interleaved)"
        )
    elif args.introspect:
        metric = (
            f"reconcile tick p50 with the solver introspection plane, "
            f"{args.introspect_ticks} ticks (compile ledger + device "
            f"telemetry + cost attribution ENABLED vs DISABLED)"
        )
    elif args.eventloop:
        metric = (
            f"watch-event -> actuation e2e p99 with event-driven "
            f"reconcile, {args.eventloop_arrivals} arrivals x "
            f"{args.eventloop_ticks} ticks (event passes vs tick-paced "
            f"on one seeded trace; {args.eventloop_storm}-event churn "
            f"storm coalesced)"
        )
    elif args.resident:
        metric = (
            f"churn-tick solve p50 with the device-resident fleet "
            f"state, {args.pods} pods x {args.types} types, "
            f"{args.resident_ticks} ticks (resident scatter ON vs full "
            f"re-upload OFF, parity pinned every tick)"
        )
    elif args.shard:
        metric = (
            f"sharded fleet solve p50 through the SolverService seam, "
            f"{args.pods} pods x {args.types} instance types over "
            f"{max(args.shard_scaling)}-device mesh (device-count "
            f"scaling {args.shard_scaling}; sharded == single-device "
            f"== numpy pinned)"
        )
    elif args.journal:
        metric = (
            f"reconcile tick p50 with the protective-state journal, "
            f"{args.journal_ticks} ticks (journal ON vs OFF + raw "
            f"append throughput)"
        )
    elif args.trace:
        metric = (
            f"reconcile tick p50 with reconcile tracing, "
            f"{args.trace_ticks} ticks (tracer ENABLED vs DISABLED + "
            f"raw span throughput)"
        )
    elif args.provenance:
        metric = (
            f"reconcile tick p50 with the decision-provenance ledger, "
            f"{args.provenance_ticks} ticks (ledger ENABLED vs "
            f"DISABLED + raw batch-commit throughput)"
        )
    elif args.multitenant:
        metric = (
            f"multi-tenant aggregate decisions/sec, {args.tenants} "
            f"tenant clusters x {args.tenant_rows} autoscalers "
            f"(cross-tenant concatenated decide+cost vs sequential "
            f"per-tenant loop; concat == independent parity pinned)"
        )
    elif args.poolgroup:
        metric = (
            f"joint pool-group allocation p50, "
            f"{args.poolgroup_groups} groups x {args.poolgroup_pools} "
            f"pools (one batched dispatch vs the per-pool cost "
            f"dispatches it replaces; numpy + cost-ladder parity "
            f"pinned)"
        )
    elif args.cost:
        metric = (
            f"batched multi-objective cost/SLO refine p50, "
            f"{args.cost_rows} autoscalers x {args.cost_metrics} "
            f"metrics (one dispatch vs per-HA loop; numpy parity "
            f"pinned)"
        )
    elif args.preempt:
        metric = (
            f"batched eviction-planning p50, {args.candidates} "
            f"candidates x {args.types} node columns x {args.pods} "
            f"victims (one dispatch vs per-candidate loop)"
        )
    elif args.forecast:
        metric = (
            f"batched metric forecast p50, {args.series} series x "
            f"{args.history} history samples (Holt-Winters + robust "
            f"linear, one dispatch vs per-series loop)"
        )
    elif args.hotpath:
        metric = (
            f"solver-service idle-queue bin-pack p50 latency, "
            f"{args.pods} pods x {args.types} instance types "
            f"(vs direct; coalesce preserved at "
            f"{args.concurrency} callers)"
        )
    elif args.solver_service:
        metric = (
            f"solver-service coalesced bin-pack p50 latency, {args.pods} "
            f"pods x {args.types} instance types, {args.concurrency} "
            f"concurrent callers"
        )
    elif args.consolidate:
        metric = (
            f"batched consolidation candidate evaluation p50, "
            f"{args.candidates} drain candidates x {args.pods} bound "
            f"pods (one masked bin-pack per candidate, one dispatch)"
        )
    elif args.decide:
        metric = (
            f"batched HPA decision kernel p50 latency, fleet of "
            f"{args.decide} autoscalers x 4 metrics (recommendation + "
            f"select policy + stabilization + rate-limit policies + bounds)"
        )
    elif args.mesh:
        shape = (
            f"{args.slices}-slice x pods x groups"
            if args.slices > 1
            else "pods x groups"
        )
        metric = (
            f"sharded bin-pack p50 latency over a {args.mesh}-device "
            f"{shape} mesh, {args.pods} pods x {args.types} "
            f"instance types (outputs == single-device)"
        )
    elif args.e2e:
        metric = (
            f"end-to-end reconcile tick p50, {args.pods} pods x "
            f"{args.types} node groups (full solve_pending: profile"
            f" + snapshot + encode + transfer + solve + status)"
        )
    elif args.clusters:
        metric = (
            f"multi-cluster re-pack p50 latency, {args.pods} pods across "
            f"{args.clusters} clusters x {args.types} instance types each "
            f"(70% cluster-pinned, 30% flexible)"
        )
    else:
        metric = (
            f"pending-pods bin-pack p50 latency, "
            f"{args.pods} pods x {args.types} instance types"
        )
    if args.affinity:
        # distinct metric key: affinity-constrained runs must never mix
        # into the unconstrained series when aggregated by metric name
        metric += f", {args.affinity:.0%} pods with node affinity"
    if args.anti:
        metric += f", {args.anti:.0%} pods one-per-node"
    if args.spread:
        metric += (
            f", {args.spread:.0%} pods zone-spread w/ occupancy census"
        )
    try:
        if args.mesh:
            run_mesh(args, metric)
            return
        if args.shard:
            # handles its own backend selection (needs a multi-device
            # mesh, so real-chip probing + virtual-CPU fallback mirror
            # run_mesh)
            run_shard(args, metric)
            return
        note = ensure_backend(
            args.probe_timeout, args.probe_retries, args.probe_hang_schedule
        )
        if note:
            # CPU fallback: keep wall clock bounded at the 100k scale
            args.iters = min(args.iters, 5)
        run(args, metric, note)
    except Exception as e:  # noqa: BLE001 — one JSON line, never a traceback
        import traceback

        traceback.print_exc()
        emit(metric, None, error=f"{type(e).__name__}: {e}"[:300])
        sys.exit(0)


def _warm_native_kernel(args) -> None:
    """Block on the C kernel build before ANY dispatch (incl. --e2e/
    --decide) and outside every timed region — the async production path
    would otherwise leave early measured iterations on the numpy
    fallback (like jit warmup, one-time setup is excluded from the
    measurement)."""
    import jax

    if jax.default_backend() == "cpu" and args.backend in ("auto", "numpy"):
        from karpenter_tpu.native import load_kbinpack

        if load_kbinpack() is None:
            print("native kernel unavailable: numpy stages", file=sys.stderr)


def _bench_inputs(args):
    if args.clusters:
        return build_multicluster_inputs(
            args.pods, args.clusters, args.types,
            max(args.taints, 8), max(args.labels, args.clusters + 8),
            args.seed,
        )
    return build_inputs(
        args.pods, args.types, args.taints, args.labels, args.seed,
        affinity=args.affinity, anti=args.anti,
    )


def _journal_world(runtime):
    """The chaos-suite world: one profiled node group, one pending pod,
    an SNG, and a queue-metric HA — every tick drives an encode + solve
    + decide + status writes, i.e. the real reconcile hot path the
    journal must not slow down."""
    from karpenter_tpu.api.core import (
        Node, NodeCondition, NodeSpec, NodeStatus, ObjectMeta, Pod,
        PodSpec, resource_list,
    )
    from karpenter_tpu.api.horizontalautoscaler import (
        Behavior, CrossVersionObjectReference, HorizontalAutoscaler,
        HorizontalAutoscalerSpec, Metric, MetricTarget,
        PrometheusMetricSource, ScalingRules,
    )
    from karpenter_tpu.api.metricsproducer import (
        MetricsProducer, MetricsProducerSpec, PendingCapacitySpec,
    )
    from karpenter_tpu.api.scalablenodegroup import (
        ScalableNodeGroup, ScalableNodeGroupSpec,
    )

    store = runtime.store
    store.create(Node(
        metadata=ObjectMeta(name="n1", labels={"pool": "a"}),
        spec=NodeSpec(),
        status=NodeStatus(
            allocatable=resource_list(cpu="8", memory="16Gi", pods="16"),
            conditions=[NodeCondition("Ready", "True")],
        ),
    ))
    store.create(Pod(metadata=ObjectMeta(name="p1"), spec=PodSpec()))
    store.create(MetricsProducer(
        metadata=ObjectMeta(name="pending"),
        spec=MetricsProducerSpec(
            pending_capacity=PendingCapacitySpec(
                node_selector={"pool": "a"}, node_group_ref="g",
            )
        ),
    ))
    store.create(ScalableNodeGroup(
        metadata=ObjectMeta(name="g"),
        spec=ScalableNodeGroupSpec(
            replicas=3, type="FakeNodeGroup", id="g"
        ),
    ))
    store.create(HorizontalAutoscaler(
        metadata=ObjectMeta(name="ha"),
        spec=HorizontalAutoscalerSpec(
            scale_target_ref=CrossVersionObjectReference(
                kind="ScalableNodeGroup", name="g"
            ),
            min_replicas=1, max_replicas=100,
            metrics=[Metric(prometheus=PrometheusMetricSource(
                query='karpenter_queue_length{name="q"}',
                target=MetricTarget(type="AverageValue", value=4),
            ))],
            # no scale-down hold: the churn tick toggles the queue
            # metric (see _churn_runtime) so every tick actuates and
            # the karpenter_reconcile_e2e_seconds histogram fills —
            # the lead-time surface bench-journal publishes
            behavior=Behavior(
                scale_down=ScalingRules(stabilization_window_seconds=0)
            ),
        ),
    ))
    runtime.registry.register("queue", "length").set("q", "default", 12.0)


def _churn_runtime(journal_dir=None, **options_kw):
    """The seeded churn world both overhead benches (--journal and
    --trace-overhead) measure: a consolidating runtime over
    _journal_world with a tick() that toggles a churn pod so the encode
    memo misses and every tick pays a real solve. Their overhead
    percentages sit side by side in BASELINE.json against the same
    ~4ms tick, so both MUST measure this exact world. Returns
    (runtime, tick); the caller owns runtime.close()."""
    from karpenter_tpu.api.core import ObjectMeta, Pod, PodSpec
    from karpenter_tpu.cloudprovider.fake import FakeFactory
    from karpenter_tpu.runtime import KarpenterRuntime, Options

    clock = {"now": 1_000_000.0}
    provider = FakeFactory()
    provider.node_replicas["g"] = 3
    opts = dict(consolidate=True, journal_dir=journal_dir)
    opts.update(options_kw)
    runtime = KarpenterRuntime(
        Options(**opts),
        cloud_provider_factory=provider,
        clock=lambda: clock["now"],
    )
    _journal_world(runtime)
    queue_gauge = runtime.registry.gauge("queue", "length")
    flip = {"high": False}

    def tick():
        try:
            runtime.store.delete("Pod", "default", "churn-pod")
        except KeyError:
            runtime.store.create(
                Pod(metadata=ObjectMeta(name="churn-pod"), spec=PodSpec())
            )
        # toggle the decision signal so every tick carries a REAL
        # actuation (desired 3 <-> 5): the provider write path and the
        # e2e lead-time histogram are part of the tick both overhead
        # benches claim to measure
        flip["high"] = not flip["high"]
        queue_gauge.set("q", "default", 20.0 if flip["high"] else 12.0)
        clock["now"] += 61.0
        runtime.manager.reconcile_all()

    return runtime, tick


def _journal_tick_times(args, journal_dir):
    """(per-tick wall times, e2e lead-time percentiles) for one
    configuration (journal on/off) over the identical seeded world.
    The e2e numbers come from the PR 9 karpenter_reconcile_e2e_seconds
    histogram the churn world's per-tick actuations fill — the
    provisioning-lead observable warm pools attack (docs/cost.md)."""
    runtime, tick = _churn_runtime(journal_dir)

    times = []
    try:
        for _ in range(5):  # warmup: compiles, first encodes
            tick()
        for _ in range(args.journal_ticks):
            t0 = time.perf_counter()
            tick()
            times.append((time.perf_counter() - t0) * 1e3)
        hist = runtime.registry.gauge("reconcile", "e2e_seconds")
        e2e = {
            "e2e_p50_ms": round(
                (hist.percentile("ScalableNodeGroup", "-", 50) or 0.0)
                * 1e3, 3,
            ),
            "e2e_p99_ms": round(
                (hist.percentile("ScalableNodeGroup", "-", 99) or 0.0)
                * 1e3, 3,
            ),
            "e2e_samples": hist.count("ScalableNodeGroup", "-"),
        }
    finally:
        runtime.close()
    return times, e2e


def _append_throughput(journal_dir, n=20_000):
    from karpenter_tpu.recovery import StateJournal

    journal = StateJournal(journal_dir)
    handle = journal.handle("bench")
    t0 = time.perf_counter()
    for i in range(n):
        handle.set(("k", i % 64), {"v": i})
    elapsed = time.perf_counter() - t0
    journal.close()
    return {
        "append_us": round(elapsed / n * 1e6, 3),
        "appends_per_sec": int(n / elapsed),
    }


def _append_journal_row(path: str, record: dict) -> None:
    marker = "## Journal overhead (make bench-journal)"
    header = (
        f"\n{marker}\n\n"
        "Reconcile tick latency with the protective-state journal "
        "(karpenter_tpu/recovery) ON vs OFF over the identical seeded "
        "world, plus raw append throughput. Acceptance target: journal "
        "overhead under 5% of tick latency.\n\n"
        "| Date | Backend | Ticks | Tick p50 off/on (ms) | Overhead | "
        "Append (µs) | Appends/s |\n"
        "|---|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['ticks']} "
        f"| {record['tick_p50_off_ms']} / {record['tick_p50_on_ms']} "
        f"| {record['overhead_pct']}% "
        f"| {record['append_us']} | {record['appends_per_sec']} |\n"
    )
    _append_table_row(path, marker, header, row)


def run_journal(args, metric: str, note: str) -> None:
    """Journal append overhead on the reconcile hot path (ISSUE 7
    acceptance: <5% tick-latency regression vs the unjournaled tick).
    Same seeded world both ways; the ON configuration journals FSM
    transitions, breaker/backoff state, and forecast history through
    the real runtime wiring."""
    import shutil
    import tempfile

    import jax

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    off, _ = _journal_tick_times(args, None)
    root = tempfile.mkdtemp(prefix="karpenter-bench-journal-")
    try:
        on, e2e = _journal_tick_times(args, os.path.join(root, "ticks"))
        throughput = _append_throughput(os.path.join(root, "appends"))
    finally:
        shutil.rmtree(root, ignore_errors=True)
    p50_off = float(np.percentile(off, 50))
    p50_on = float(np.percentile(on, 50))
    overhead = (p50_on / p50_off - 1.0) * 100.0 if p50_off else 0.0
    record = {
        "config": f"{args.journal_ticks} ticks",
        "backend": jax.default_backend(),
        "ticks": args.journal_ticks,
        "tick_p50_off_ms": round(p50_off, 3),
        "tick_p50_on_ms": round(p50_on, 3),
        "tick_p99_off_ms": round(float(np.percentile(off, 99)), 3),
        "tick_p99_on_ms": round(float(np.percentile(on, 99)), 3),
        "overhead_pct": round(overhead, 2),
        **throughput,
        # event-observed -> actuation-acked lead time over the journaled
        # run (the PR 9 histogram; docs/cost.md quantifies warm pools
        # against the same observable)
        **e2e,
    }
    record_evidence(
        tick_off_ms=[round(t, 4) for t in off],
        tick_on_ms=[round(t, 4) for t in on],
        journal=record,
    )
    print(
        f"tick p50 off={record['tick_p50_off_ms']}ms "
        f"on={record['tick_p50_on_ms']}ms "
        f"overhead={record['overhead_pct']}% | append "
        f"{record['append_us']}µs ({record['appends_per_sec']}/s) | "
        f"e2e lead p50={record['e2e_p50_ms']}ms "
        f"p99={record['e2e_p99_ms']}ms (n={record['e2e_samples']})",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_to_baseline(
            f"{record['config']} journal overhead ({record['backend']})",
            record,
        )
    if args.append_benchmarks:
        _append_journal_row(args.append_benchmarks, record)
    emit(
        f"{metric} ({jax.default_backend()})",
        p50_on,
        note=(
            f"{note}; " if note else ""
        ) + f"journal overhead {record['overhead_pct']}% "
        f"(off p50 {record['tick_p50_off_ms']}ms), append "
        f"{record['append_us']}µs",
        against_baseline=False,
    )


def _trace_tick_times(args):
    """Per-tick wall times with the tracer ENABLED vs DISABLED, measured
    INTERLEAVED over one seeded world (the one `--journal` measures —
    churn pod toggled each tick so every tick pays a real encode +
    solve + decide, i.e. the span-instrumented hot path). The only
    difference between adjacent ticks is the tracer's `enabled` flag,
    so wall-clock drift (thermal, page cache, background load) that
    dominates a sub-5% effect in back-to-back runs cancels; the
    off/on order flips each round so the churn create/delete asymmetry
    balances across configurations too. Returns
    (off_ms, on_ms, spans_per_tick)."""
    from karpenter_tpu.observability import default_tracer

    tracer = default_tracer()
    runtime, tick = _churn_runtime()

    def timed(enabled):
        tracer.enabled = enabled
        t0 = time.perf_counter()
        tick()
        return (time.perf_counter() - t0) * 1e3

    off, on = [], []
    try:
        for _ in range(5):  # warmup: compiles, first encodes
            tick()
        spans_before = tracer.spans_total
        for round_i in range(args.trace_ticks):
            if round_i % 2 == 0:
                off.append(timed(False))
                on.append(timed(True))
            else:
                on.append(timed(True))
                off.append(timed(False))
        spans_per_tick = (
            (tracer.spans_total - spans_before) / args.trace_ticks
        )
    finally:
        tracer.enabled = True
        tracer.clear()
        runtime.close()
    return off, on, round(spans_per_tick, 1)


def _span_throughput(n: int = 20_000) -> dict:
    """Raw open/close cost of one span on a private tracer — the
    per-span floor the per-tick overhead decomposes into."""
    from karpenter_tpu.observability.tracing import Tracer

    tracer = Tracer(capacity=1024)
    t0 = time.perf_counter()
    for _ in range(n):
        with tracer.span("bench"):
            pass
    elapsed = time.perf_counter() - t0
    return {
        "span_us": round(elapsed / n * 1e6, 3),
        "spans_per_sec": int(n / elapsed),
    }


def _append_trace_row(path: str, record: dict) -> None:
    marker = "## Tracing overhead (make bench-trace)"
    header = (
        f"\n{marker}\n\n"
        "Reconcile tick latency with the reconcile tracer "
        "(karpenter_tpu/observability) ENABLED vs DISABLED over the "
        "identical seeded world, plus span volume and raw span "
        "open/close throughput. Acceptance target: tracing overhead "
        "under 5% of tick latency.\n\n"
        "| Date | Backend | Ticks | Tick p50 off/on (ms) | Overhead | "
        "Spans/tick | Span (µs) | Spans/s |\n"
        "|---|---|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['ticks']} "
        f"| {record['tick_p50_off_ms']} / {record['tick_p50_on_ms']} "
        f"| {record['overhead_pct']}% | {record['spans_per_tick']} "
        f"| {record['span_us']} | {record['spans_per_sec']} |\n"
    )
    _append_table_row(path, marker, header, row)


def run_trace(args, metric: str, note: str) -> None:
    """Tracing overhead on the reconcile hot path (ISSUE 9 acceptance:
    <5% tick-latency regression vs the untraced tick). Same seeded
    world both ways; the ENABLED configuration mints a trace per tick
    and spans every layer through the real runtime wiring (manager ->
    metrics query -> solver request/dispatch -> SNG actuation)."""
    import jax

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    off, on, spans_per_tick = _trace_tick_times(args)
    throughput = _span_throughput()
    p50_off = float(np.percentile(off, 50))
    p50_on = float(np.percentile(on, 50))
    # overhead from the MEDIAN PAIRED per-round difference, not the
    # ratio of two independent p50s: each round's off/on ticks are
    # wall-clock adjacent, so drift that swamps a sub-5% effect in
    # independent percentiles cancels pairwise
    delta = float(np.median(np.asarray(on) - np.asarray(off)))
    overhead = (delta / p50_off) * 100.0 if p50_off else 0.0
    record = {
        "config": f"{args.trace_ticks} ticks",
        "backend": jax.default_backend(),
        "ticks": args.trace_ticks,
        "tick_p50_off_ms": round(p50_off, 3),
        "tick_p50_on_ms": round(p50_on, 3),
        "tick_p99_off_ms": round(float(np.percentile(off, 99)), 3),
        "tick_p99_on_ms": round(float(np.percentile(on, 99)), 3),
        "overhead_pct": round(overhead, 2),
        "spans_per_tick": spans_per_tick,
        **throughput,
    }
    record_evidence(
        tick_off_ms=[round(t, 4) for t in off],
        tick_on_ms=[round(t, 4) for t in on],
        trace=record,
    )
    print(
        f"tick p50 off={record['tick_p50_off_ms']}ms "
        f"on={record['tick_p50_on_ms']}ms "
        f"overhead={record['overhead_pct']}% | "
        f"{record['spans_per_tick']} spans/tick, span "
        f"{record['span_us']}µs ({record['spans_per_sec']}/s)",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_to_baseline(
            f"{record['config']} tracing overhead ({record['backend']})",
            record,
        )
    if args.append_benchmarks:
        _append_trace_row(args.append_benchmarks, record)
    emit(
        f"{metric} ({jax.default_backend()})",
        p50_on,
        note=(
            f"{note}; " if note else ""
        ) + f"tracing overhead {record['overhead_pct']}% "
        f"(off p50 {record['tick_p50_off_ms']}ms), "
        f"{record['spans_per_tick']} spans/tick @ "
        f"{record['span_us']}µs",
        against_baseline=False,
    )


def _append_eventloop_row(path: str, record: dict) -> None:
    marker = "## Event-driven reconcile (make bench-eventloop)"
    header = (
        f"\n{marker}\n\n"
        "One seeded pod-arrival trace replayed tick-paced vs "
        "event-driven (debounced coalesced event passes; the tick "
        "demoted to a resync backstop). e2e = the "
        "karpenter_reconcile_e2e_seconds histogram (watch-event -> "
        "actuation-ack), read via HistogramVec.percentile. "
        "Amplification = event-arm solver work / tick-arm solver work; "
        "the storm column is the churn-storm arm (N events inside one "
        "debounce window must coalesce, not fan out).\n\n"
        "| Date | Backend | Trace | e2e p50/p99 tick (s) | "
        "e2e p50/p99 event (s) | p99 speedup | Amplification | "
        "Storm events -> passes | Storm amp |\n"
        "|---|---|---|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['config']} "
        f"| {record['tick_p50_s']} / {record['tick_p99_s']} "
        f"| {record['event_p50_s']} / {record['event_p99_s']} "
        f"| {record['p99_speedup']}x | {record['amplification']}x "
        f"| {record['storm_events']} -> {record['storm_passes']} "
        f"| {record['storm_amplification']}x |\n"
    )
    _append_table_row(path, marker, header, row)


def run_eventloop(args, metric: str, note: str) -> None:
    """Event-driven reconcile proof (ISSUE 14 acceptance): the seeded
    arrival trace replayed through both loop modes by
    simulate.simulate_eventloop — wall-clock-free (scripted clock,
    manual event passes), so the published latencies are the SIMULATED
    lead times an operator's histogram would show at the replayed tick
    interval, not artifacts of how fast this host replays ticks."""
    import jax

    from karpenter_tpu.simulate import simulate_eventloop

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    report = simulate_eventloop(
        ticks=args.eventloop_ticks,
        arrivals=args.eventloop_arrivals,
        storm_events=args.eventloop_storm,
        debounce_s=args.eventloop_debounce,
        seed=args.seed,
    )
    tick = report["tick_paced"]["e2e_seconds"]
    event = report["event_driven"]["e2e_seconds"]
    storm = report["event_driven"]["storm"]
    record = {
        "config": (
            f"{args.eventloop_arrivals} arrivals x "
            f"{args.eventloop_ticks} ticks"
        ),
        "backend": jax.default_backend(),
        "interval_s": report["config"]["interval_s"],
        "debounce_s": report["config"]["debounce_s"],
        "tick_p50_s": round(tick["p50_s"] or 0.0, 4),
        "tick_p99_s": round(tick["p99_s"] or 0.0, 4),
        "event_p50_s": round(event["p50_s"] or 0.0, 4),
        "event_p99_s": round(event["p99_s"] or 0.0, 4),
        "p99_speedup": report["e2e_p99_s"]["speedup"],
        "amplification": report["solve_amplification"],
        "event_passes": report["event_driven"]["event_passes"],
        "storm_events": storm["events"],
        "storm_passes": storm["passes"],
        "storm_amplification": storm["amplification"],
        "fixed_point_match": report["fixed_point_match"],
    }
    record_evidence(eventloop=report)
    print(
        f"e2e p99 tick={record['tick_p99_s']}s "
        f"event={record['event_p99_s']}s "
        f"({record['p99_speedup']}x); amplification "
        f"{record['amplification']}x; storm {record['storm_events']} "
        f"events -> {record['storm_passes']} passes "
        f"({record['storm_amplification']}x)",
        file=sys.stderr,
    )
    if not record["fixed_point_match"]:
        emit(metric, None, error="event-driven fixed point diverged "
             "from the tick-paced run")
        sys.exit(0)
    if args.publish_baseline:
        _publish_to_baseline(
            f"{record['config']} eventloop ({record['backend']})",
            record,
        )
    if args.append_benchmarks:
        _append_eventloop_row(args.append_benchmarks, record)
    emit(
        f"{metric} ({jax.default_backend()})",
        record["event_p99_s"] * 1e3,  # emit()'s unit is ms
        note=(
            f"{note}; " if note else ""
        ) + f"tick-paced p99 {record['tick_p99_s']}s -> event-driven "
        f"p99 {record['event_p99_s']}s ({record['p99_speedup']}x) at "
        f"debounce {record['debounce_s']}s; solve amplification "
        f"{record['amplification']}x; {record['storm_events']}-event "
        f"storm -> {record['storm_passes']} passes",
        against_baseline=False,
    )


def run_simlab(args, metric: str, note: str) -> None:  # lint: allow-complexity — bench arm: parity pin + interleaved timing + publish, linear
    """SimLab batched cluster stepping (ISSUE 17 acceptance): N
    independently-seeded simulated clusters advanced as ONE vmapped
    sim_rollout dispatch through the SolverService seam vs the
    per-cluster sequential loop (N dispatches of the same compiled
    program). Parity — batched == sequential == numpy mirror, bitwise
    on every output field — is pinned BEFORE any timing; interleaved
    arms so drift cancels."""
    import jax

    from karpenter_tpu.metrics.registry import GaugeRegistry
    from karpenter_tpu.ops import simstep as SK
    from karpenter_tpu.simlab import BatchedSimEnv
    from karpenter_tpu.simlab.builtin import make_trails
    from karpenter_tpu.simlab.policy import FROZEN_KNOBS
    from karpenter_tpu.solver.service import SolverService

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    clusters, ticks, rows = (
        args.simlab_clusters, args.simlab_ticks, args.simlab_rows
    )
    svc = SolverService(registry=GaugeRegistry())
    # the cost theme: diurnal demand + spot spikes + a seeded fault
    # schedule, so the measured program carries every kernel feature
    env = BatchedSimEnv(
        lambda seed: make_trails(
            seed, ticks=ticks, rows=rows, diurnal=True, amplitude=96.0,
            price_spike=1.5, fault_probability=0.05,
        ),
        clusters=clusters,
        seed=args.seed,
        service=svc,
        backend="xla",
    )
    batched_inputs = SK.SimRolloutInputs(
        replicas0=np.asarray(env.trails.replicas0, np.float32),
        streak0=np.zeros_like(
            np.asarray(env.trails.replicas0, np.float32)
        ),
        demand=env.trails.demand, forecast=env.trails.forecast,
        price=env.trails.price, fault=env.trails.fault,
        knobs=np.broadcast_to(
            FROZEN_KNOBS, (clusters, FROZEN_KNOBS.shape[0])
        ).copy(),
        cap=np.float32(env.params.cap),
        hourly=np.float32(env.params.hourly),
        step_limit=np.float32(env.params.step_limit),
        min_replicas=np.float32(env.params.min_replicas),
        max_replicas=np.float32(env.params.max_replicas),
    )
    slices = [
        SK._cluster_slice(batched_inputs, b) for b in range(clusters)
    ]

    # parity pin BEFORE timing: batched == sequential == numpy, bitwise
    batched = svc.sim_rollout(batched_inputs, backend="xla")
    mirror = SK.sim_rollout_numpy(batched_inputs)
    fields = ("replicas", "violation", "cost", "backlog", "target")
    for field in fields:
        if not (
            np.asarray(getattr(batched, field))
            == np.asarray(getattr(mirror, field))
        ).all():
            emit(metric, None, error=f"batched/numpy mismatch: {field}")
            sys.exit(0)
    for b in (0, clusters // 2, clusters - 1):
        seq = svc.sim_rollout(slices[b], backend="xla")
        for field in fields:
            if not (
                np.asarray(getattr(seq, field))
                == np.asarray(getattr(batched, field))[b]
            ).all():
                emit(
                    metric, None,
                    error=f"batched/sequential mismatch: {field} "
                    f"cluster {b}",
                )
                sys.exit(0)
    if svc.stats.sim_mirror_serves:
        emit(
            metric, None,
            error="device path unavailable (mirror served during "
            "parity); the batched-vs-sequential comparison needs XLA",
        )
        sys.exit(0)
    print("parity: batched == sequential == numpy (bitwise)",
          file=sys.stderr)

    # warm both compiled programs outside the timed region
    jax.block_until_ready(SK.sim_rollout_vmapped(batched_inputs).replicas)
    jax.block_until_ready(SK.sim_rollout_jit(slices[0]).replicas)

    base_dispatches = svc.stats.sim_dispatches
    batched_times, seq_times = [], []
    for _ in range(args.iters):
        t0 = time.perf_counter()
        svc.sim_rollout(batched_inputs, backend="xla")
        batched_times.append((time.perf_counter() - t0) * 1e3)
        t0 = time.perf_counter()
        for one in slices:
            svc.sim_rollout(one, backend="xla")
        seq_times.append((time.perf_counter() - t0) * 1e3)
    if svc.stats.sim_mirror_serves:
        emit(metric, None, error="mirror served during timing")
        sys.exit(0)

    batched_p50 = float(np.percentile(batched_times, 50))
    seq_p50 = float(np.percentile(seq_times, 50))
    speedup = seq_p50 / max(batched_p50, 1e-9)
    # cluster-days per minute at a 10s simulated tick: the ROADMAP
    # "thousands of cluster-days per minute" claim, measured
    sim_days = clusters * ticks * 10.0 / 86_400.0
    days_per_min = sim_days / (batched_p50 / 1e3) * 60.0
    record = {
        "config": f"{clusters} clusters x {ticks} ticks x {rows} rows",
        "backend": jax.default_backend(),
        "batched_p50_ms": round(batched_p50, 3),
        "sequential_p50_ms": round(seq_p50, 3),
        "speedup": round(speedup, 1),
        "dispatches_sequential": clusters,
        "cluster_days_per_min": round(days_per_min, 1),
        "parity": "bitwise",
    }
    record_evidence(
        simlab={
            "batched_ms": [round(t, 4) for t in batched_times],
            "sequential_ms": [round(t, 4) for t in seq_times],
            "dispatches": svc.stats.sim_dispatches - base_dispatches,
        }
    )
    print(
        f"batched p50 {record['batched_p50_ms']}ms vs sequential "
        f"{record['sequential_p50_ms']}ms ({record['speedup']}x); "
        f"{record['cluster_days_per_min']} simulated cluster-days/min",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_to_baseline(
            f"{record['config']} simlab ({record['backend']})", record
        )
    if args.append_benchmarks:
        _append_simlab_row(args.append_benchmarks, record)
    emit(
        f"{metric} ({jax.default_backend()})",
        record["batched_p50_ms"],
        note=(
            f"{note}; " if note else ""
        ) + f"one vmapped dispatch {record['batched_p50_ms']}ms vs "
        f"{clusters} sequential dispatches "
        f"{record['sequential_p50_ms']}ms ({record['speedup']}x); "
        f"{record['cluster_days_per_min']} cluster-days/min; parity "
        f"pinned bitwise",
        against_baseline=False,
    )


def _append_simlab_row(path: str, record: dict) -> None:
    marker = "## SimLab batched cluster stepping (make bench-simlab)"
    header = (
        f"\n{marker}\n\n"
        "N independently-seeded simulated clusters (docs/simulator.md) "
        "advanced one whole episode as ONE vmapped sim_rollout "
        "dispatch through the SolverService seam, vs the per-cluster "
        "sequential loop (N dispatches of the same compiled program). "
        "Batched == sequential == numpy mirror pinned bitwise before "
        "timing; interleaved arms.\n\n"
        "| Date | Backend | Problem | Batched p50 (ms) | "
        "Sequential p50 (ms) | Speedup | Cluster-days/min |\n"
        "|---|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['config']} "
        f"| {record['batched_p50_ms']} | {record['sequential_p50_ms']} "
        f"| {record['speedup']}x | {record['cluster_days_per_min']} |\n"
    )
    _append_table_row(path, marker, header, row)


def _fusedtick_inputs(seed, n, m, s, t):
    """A seeded full-presence fleet batch: every stage of the fused
    megakernel engaged (forecast series scattered over the grid, SLO
    rows mostly valid) so the measured program carries the whole
    forecast -> decide -> cost ladder."""
    from karpenter_tpu.forecast import models as FM
    from karpenter_tpu.ops import decision as DK
    from karpenter_tpu.ops import fusedtick as FT

    r = np.random.RandomState(seed)
    k = 2
    now = 1000.0
    decision = DK.DecisionInputs(
        metric_value=r.uniform(0, 100, (n, m)).astype(np.float32),
        target_value=r.uniform(1, 80, (n, m)).astype(np.float32),
        target_type=r.randint(0, 3, (n, m)).astype(np.int32),
        metric_valid=r.rand(n, m) > 0.2,
        spec_replicas=r.randint(1, 20, n).astype(np.int32),
        status_replicas=r.randint(1, 20, n).astype(np.int32),
        min_replicas=r.randint(0, 3, n).astype(np.int32),
        max_replicas=r.randint(20, 40, n).astype(np.int32),
        up_window=r.randint(0, 60, n).astype(np.int32),
        down_window=r.randint(0, 120, n).astype(np.int32),
        up_policy=r.randint(0, 2, n).astype(np.int32),
        down_policy=r.randint(0, 2, n).astype(np.int32),
        last_scale_time=(now - r.uniform(0, 300, n)).astype(np.float32),
        has_last_scale=r.rand(n) > 0.3,
        now=np.float32(now),
        up_ptype=r.randint(0, 3, (n, k)).astype(np.int32),
        up_pvalue=r.randint(1, 10, (n, k)).astype(np.int32),
        up_pperiod=r.randint(15, 120, (n, k)).astype(np.int32),
        up_pvalid=r.rand(n, k) > 0.4,
        down_ptype=r.randint(0, 3, (n, k)).astype(np.int32),
        down_pvalue=r.randint(1, 10, (n, k)).astype(np.int32),
        down_pperiod=r.randint(15, 120, (n, k)).astype(np.int32),
        down_pvalid=r.rand(n, k) > 0.4,
    )
    forecast = FM.ForecastInputs(
        values=r.uniform(0, 100, (s, t)).astype(np.float32),
        valid=r.rand(s, t) > 0.2,
        times=np.cumsum(r.uniform(10, 20, (s, t)), 1).astype(np.float32),
        weights=np.ones((s, t), np.float32),
        horizon=np.full(s, 60.0, np.float32),
        step_s=np.full(s, 15.0, np.float32),
        model=r.randint(0, 2, s).astype(np.int32),
        season=np.full(s, 4, np.int32),
        alpha=np.full(s, 0.5, np.float32),
        beta=np.full(s, 0.1, np.float32),
        gamma=np.full(s, 0.1, np.float32),
    )
    return FT.FusedTickInputs(
        decision=decision,
        forecast=forecast,
        series_row=r.randint(0, n, s).astype(np.int32),
        series_col=r.randint(0, m, s).astype(np.int32),
        series_need=np.full(s, 2, np.int32),
        series_blend=r.rand(s) > 0.3,
        ha_min=r.randint(0, 3, n).astype(np.int32),
        ha_max=r.randint(20, 40, n).astype(np.int32),
        unit_cost=r.uniform(0.1, 3.0, n).astype(np.float32),
        slo_weight=r.uniform(0, 2, n).astype(np.float32),
        max_hourly_cost=r.uniform(5, 50, n).astype(np.float32),
        slo_valid=r.rand(n) > 0.4,
        slo_target=r.uniform(1, 80, (n, m)).astype(np.float32),
        observed=r.uniform(0, 100, (n, m)).astype(np.float32),
        demand_base_valid=r.rand(n, m) > 0.3,
        prior_point=r.uniform(0, 100, (n, m)).astype(np.float32),
        prior_sigma2=r.uniform(0, 10, (n, m)).astype(np.float32),
        prior_valid=r.rand(n, m) > 0.5,
    )


def _fusedtick_world_ticks(fused: bool, warmup: int, ticks: int):
    """(per-tick wall times, dispatches-per-tick) over the shared
    churn-runtime world with --fused-tick on/off: the HA plane's
    forecast + SLO stages engaged so the chained arm pays one program
    per stage while the fused arm pays ONE (the
    karpenter_solver_dispatches_per_tick observable)."""
    from karpenter_tpu.api.horizontalautoscaler import (
        ForecastSpec, SLOSpec,
    )

    runtime, tick = _churn_runtime(
        consolidate=False, fused_tick=fused,
    )
    times = []
    try:
        # the dispatch-count observable needs the compiled path ("auto"
        # resolves to numpy on CPU; decisions are bit-identical)
        runtime.solver_service.backend = "xla"
        # the producer's pending-capacity solve would ride along in
        # both arms; drop it so the gauge isolates the HA-plane ladder
        runtime.store.delete("MetricsProducer", "default", "pending")
        ha = runtime.store.get("HorizontalAutoscaler", "default", "ha")
        ha.spec.behavior.forecast = ForecastSpec(
            horizon_seconds=30.0, min_samples=3, model="linear",
        )
        ha.spec.behavior.slo = SLOSpec(
            target_value=3.0, violation_cost_weight=25.0,
        )
        # store.get hands back a clone; write the engaged stages back
        runtime.store.update(ha)
        for _ in range(warmup):
            tick()
        for _ in range(ticks):
            t0 = time.perf_counter()
            tick()
            times.append((time.perf_counter() - t0) * 1e3)
        dispatches = (
            runtime.solver_service.stats.last_dispatches_per_tick
        )
        stats = runtime.solver_service.stats
        if fused and not stats.fused_dispatches:
            raise RuntimeError(
                "--fused-tick runtime arm never dispatched the fused "
                "program"
            )
    finally:
        runtime.close()
    return times, dispatches


def run_fusedtick(args, metric: str, note: str) -> None:  # lint: allow-complexity — bench arm: parity pin + interleaved timing + publish, linear
    """The fused steady-state tick (ISSUE 18 acceptance): the whole
    fleet batch's forecast -> decide -> cost ladder as ONE compiled
    program through SolverService.fused_tick vs the chained per-stage
    wire (one program per stage, numpy host glue between). Parity —
    fused == chained == numpy mirror, bitwise on every output leaf —
    is pinned BEFORE any timing; interleaved arms so drift cancels.
    A second arm replays the shared churn-runtime world with
    --fused-tick on/off and reads the dispatches-per-tick collapse
    from the introspection stats."""
    import jax

    from karpenter_tpu.metrics.registry import GaugeRegistry
    from karpenter_tpu.ops import fusedtick as FT
    from karpenter_tpu.solver.service import SolverService

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    n, m = args.fusedtick_rows, args.fusedtick_metrics
    s, t = args.fusedtick_series, args.fusedtick_samples
    inputs = _fusedtick_inputs(args.seed, n, m, s, t)
    svc = SolverService(registry=GaugeRegistry(), backend="xla")

    # parity pin BEFORE timing: fused == chained == numpy, bitwise
    fused_out = svc.fused_tick(inputs)
    chained_out = FT.fused_tick_chained(inputs)
    mirror_out = FT.fused_tick_numpy(inputs)
    if svc.stats.fused_mirror_serves or svc.stats.fused_chained_serves:
        emit(
            metric, None,
            error="device path unavailable (fallback served during "
            "parity); the fused-vs-chained comparison needs XLA",
        )
        sys.exit(0)
    as_np = lambda out: jax.tree_util.tree_leaves(  # noqa: E731
        jax.tree_util.tree_map(np.asarray, out)
    )
    for other, name in ((chained_out, "chained"), (mirror_out, "numpy")):
        for i, (a, b) in enumerate(zip(as_np(fused_out), as_np(other))):
            if a.tobytes() != b.tobytes():
                emit(
                    metric, None,
                    error=f"fused/{name} mismatch: leaf {i}",
                )
                sys.exit(0)
    print("parity: fused == chained == numpy (bitwise)", file=sys.stderr)

    # kernel arm: interleaved fused vs chained dispatch, both timed at
    # the ops seam (the parity pin above already exercised — and
    # compiled — the full service ladder; timing the raw programs keeps
    # the service-wrapper overhead out of BOTH arms symmetrically)
    fused_times, chained_times = [], []
    for _ in range(args.iters):
        t0 = time.perf_counter()
        jax.block_until_ready(FT.fused_tick_jit(inputs))
        fused_times.append((time.perf_counter() - t0) * 1e3)
        t0 = time.perf_counter()
        FT.fused_tick_chained(inputs)
        chained_times.append((time.perf_counter() - t0) * 1e3)
    svc.close()

    fused_p50 = float(np.percentile(fused_times, 50))
    chained_p50 = float(np.percentile(chained_times, 50))
    speedup = chained_p50 / max(fused_p50, 1e-9)
    decisions_per_s = n / max(fused_p50 / 1e3, 1e-9)

    # runtime arm: the shared churn world, fused on vs off
    warmup = 6
    chained_ticks, tick_d_chained = _fusedtick_world_ticks(
        False, warmup, args.fusedtick_ticks
    )
    fused_ticks, tick_d_fused = _fusedtick_world_ticks(
        True, warmup, args.fusedtick_ticks
    )

    record = {
        "config": (
            f"{n} autoscalers x {m} metrics x {s} series x "
            f"{t} samples"
        ),
        "backend": jax.default_backend(),
        "fused_p50_ms": round(fused_p50, 3),
        "chained_p50_ms": round(chained_p50, 3),
        "speedup": round(speedup, 2),
        "decisions_per_s": int(decisions_per_s),
        "programs_fused": 1,
        "programs_chained": FT.programs(inputs),
        "tick_p50_fused_ms": round(
            float(np.percentile(fused_ticks, 50)), 3
        ),
        "tick_p50_chained_ms": round(
            float(np.percentile(chained_ticks, 50)), 3
        ),
        "tick_dispatches_fused": tick_d_fused,
        "tick_dispatches_chained": tick_d_chained,
        "parity": "bitwise",
    }
    record_evidence(
        fusedtick={
            "fused_ms": [round(x, 4) for x in fused_times],
            "chained_ms": [round(x, 4) for x in chained_times],
            "tick_fused_ms": [round(x, 4) for x in fused_ticks],
            "tick_chained_ms": [round(x, 4) for x in chained_ticks],
        }
    )
    print(
        f"fused p50 {record['fused_p50_ms']}ms vs chained "
        f"{record['chained_p50_ms']}ms ({record['speedup']}x); "
        f"{record['decisions_per_s']} decisions/sec; runtime tick "
        f"dispatches {record['tick_dispatches_chained']} -> "
        f"{record['tick_dispatches_fused']}",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_to_baseline(
            f"{record['config']} fusedtick ({record['backend']})", record
        )
    if args.append_benchmarks:
        _append_fusedtick_row(args.append_benchmarks, record)
    emit(
        f"{metric} ({jax.default_backend()})",
        record["fused_p50_ms"],
        note=(
            f"{note}; " if note else ""
        ) + f"one fused program {record['fused_p50_ms']}ms vs "
        f"{record['programs_chained']}-program chained wire "
        f"{record['chained_p50_ms']}ms ({record['speedup']}x); "
        f"{record['decisions_per_s']} decisions/sec; runtime "
        f"dispatches/tick {record['tick_dispatches_chained']} -> "
        f"{record['tick_dispatches_fused']}; parity pinned bitwise",
        against_baseline=False,
    )


def _append_fusedtick_row(path: str, record: dict) -> None:
    marker = "## Fused steady-state tick (make bench-fusedtick)"
    header = (
        f"\n{marker}\n\n"
        "The whole fleet batch's forecast -> decide -> cost ladder as "
        "ONE compiled program (SolverService.fused_tick, --fused-tick) "
        "vs the chained per-stage wire — one program per stage with "
        "numpy host glue between. Fused == chained == numpy mirror "
        "pinned bitwise before timing; interleaved arms. The runtime "
        "columns replay the shared churn world and read the "
        "karpenter_solver_dispatches_per_tick collapse.\n\n"
        "| Date | Backend | Problem | Fused p50 (ms) | "
        "Chained p50 (ms) | Speedup | Decisions/sec | "
        "Dispatches/tick |\n"
        "|---|---|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['config']} "
        f"| {record['fused_p50_ms']} | {record['chained_p50_ms']} "
        f"| {record['speedup']}x | {record['decisions_per_s']} "
        f"| {record['tick_dispatches_chained']} -> "
        f"{record['tick_dispatches_fused']} |\n"
    )
    _append_table_row(path, marker, header, row)


def _provenance_tick_times(args):
    """Per-tick wall times with the decision-provenance ledger ENABLED
    vs DISABLED, measured INTERLEAVED over the shared churn world (the
    exact world bench-journal and bench-trace measure, so the three
    published overhead percentages sit side by side against the same
    ~4ms tick). Adjacent off/on ticks + flipped order per round: drift
    cancels pairwise (the bench-trace rationale). Returns
    (off_ms, on_ms, records_per_tick)."""
    from karpenter_tpu.observability import (
        default_ledger,
        reset_default_ledger,
        set_default_ledger,
    )

    saved = default_ledger()
    ledger = reset_default_ledger(enabled=False)
    runtime, tick = _churn_runtime()

    def timed(enabled):
        ledger.enabled = enabled
        t0 = time.perf_counter()
        tick()
        return (time.perf_counter() - t0) * 1e3

    off, on = [], []
    try:
        for _ in range(5):  # warmup: compiles, first encodes
            tick()
        records_before = ledger.records_total
        for round_i in range(args.provenance_ticks):
            if round_i % 2 == 0:
                off.append(timed(False))
                on.append(timed(True))
            else:
                on.append(timed(True))
                off.append(timed(False))
        records_per_tick = (
            (ledger.records_total - records_before)
            / args.provenance_ticks
        )
    finally:
        runtime.close()
        set_default_ledger(saved)
    return off, on, round(records_per_tick, 1)


def _ledger_throughput(n: int = 5_000, rows: int = 8) -> dict:
    """Raw begin+annotate+commit cost of one `rows`-row batch on a
    private ledger — the per-batch floor the per-tick overhead
    decomposes into."""
    from karpenter_tpu.observability.provenance import DecisionLedger

    ledger = DecisionLedger(capacity=4096, enabled=True)
    names = [f"r{i}" for i in range(rows)]
    desired = np.arange(rows, dtype=np.int32)
    t0 = time.perf_counter()
    for _ in range(n):
        batch = ledger.begin("ha", rows, name=names)
        batch.annotate(base_desired=desired, final_desired=desired)
        ledger.commit(batch)
    elapsed = time.perf_counter() - t0
    return {
        "commit_us": round(elapsed / n * 1e6, 3),
        "commits_per_sec": int(n / elapsed),
    }


def _append_provenance_row(path: str, record: dict) -> None:
    marker = "## Provenance overhead (make bench-provenance)"
    header = (
        f"\n{marker}\n\n"
        "Reconcile tick latency with the decision-provenance ledger "
        "(karpenter_tpu/observability/provenance.py) ENABLED vs "
        "DISABLED over the identical seeded world (the bench-journal/"
        "bench-trace churn world), plus raw batch-commit throughput. "
        "Acceptance target: provenance overhead under 5% of tick "
        "latency; provenance OFF is property-pinned byte-identical "
        "(tests/test_provenance.py).\n\n"
        "| Date | Backend | Ticks | Tick p50 off/on (ms) | Overhead | "
        "Records/tick | Commit (µs) | Commits/s |\n"
        "|---|---|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['ticks']} "
        f"| {record['tick_p50_off_ms']} / {record['tick_p50_on_ms']} "
        f"| {record['overhead_pct']}% | {record['records_per_tick']} "
        f"| {record['commit_us']} | {record['commits_per_sec']} |\n"
    )
    _append_table_row(path, marker, header, row)


def run_provenance(args, metric: str, note: str) -> None:
    """Decision-provenance overhead on the reconcile hot path (ISSUE 12
    acceptance: <=5% median paired tick overhead with the ledger on).
    Same seeded world both ways; the ENABLED configuration records one
    columnar batch per tick through the real annotation sites
    (BatchAutoscaler -> forecast -> cost -> solver decide)."""
    import jax

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    off, on, records_per_tick = _provenance_tick_times(args)
    throughput = _ledger_throughput()
    p50_off = float(np.percentile(off, 50))
    p50_on = float(np.percentile(on, 50))
    # median PAIRED difference (the bench-trace discipline): adjacent
    # off/on ticks cancel wall-clock drift a sub-5% effect drowns in
    delta = float(np.median(np.asarray(on) - np.asarray(off)))
    overhead = (delta / p50_off) * 100.0 if p50_off else 0.0
    record = {
        "config": f"{args.provenance_ticks} ticks",
        "backend": jax.default_backend(),
        "ticks": args.provenance_ticks,
        "tick_p50_off_ms": round(p50_off, 3),
        "tick_p50_on_ms": round(p50_on, 3),
        "tick_p99_off_ms": round(float(np.percentile(off, 99)), 3),
        "tick_p99_on_ms": round(float(np.percentile(on, 99)), 3),
        "overhead_pct": round(overhead, 2),
        "records_per_tick": records_per_tick,
        **throughput,
    }
    record_evidence(
        tick_off_ms=[round(t, 4) for t in off],
        tick_on_ms=[round(t, 4) for t in on],
        provenance=record,
    )
    print(
        f"tick p50 off={record['tick_p50_off_ms']}ms "
        f"on={record['tick_p50_on_ms']}ms "
        f"overhead={record['overhead_pct']}% | "
        f"{record['records_per_tick']} records/tick, commit "
        f"{record['commit_us']}µs ({record['commits_per_sec']}/s)",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_to_baseline(
            f"{record['config']} provenance overhead "
            f"({record['backend']})",
            record,
        )
    if args.append_benchmarks:
        _append_provenance_row(args.append_benchmarks, record)
    emit(
        f"{metric} ({jax.default_backend()})",
        p50_on,
        note=(
            f"{note}; " if note else ""
        ) + f"provenance overhead {record['overhead_pct']}% "
        f"(off p50 {record['tick_p50_off_ms']}ms), "
        f"{record['records_per_tick']} records/tick @ "
        f"{record['commit_us']}µs/commit",
        against_baseline=False,
    )


def _introspect_tick_times(args):
    """Per-tick wall times with the solver introspection plane ENABLED
    vs DISABLED, measured INTERLEAVED over the shared churn world (the
    exact world bench-journal/bench-trace/bench-provenance measure, so
    the four published overhead percentages sit side by side against
    the same ~4ms tick). Adjacent off/on ticks + flipped order per
    round: drift cancels pairwise (the bench-trace rationale). Warm-up
    runs ENABLED so the compile ledger and cost attribution are paid
    there; steady-state ticks then measure the honest per-tick cost
    (storm-window close + memory poll + resident gauges). Returns
    (off_ms, on_ms, ledger_records)."""
    runtime, tick = _churn_runtime()
    plane = runtime.solver_introspection
    # force the compiled XLA path ("auto" resolves to the numpy host
    # program on CPU): the compile ledger observes jitted dispatches,
    # and both interleaved arms pay the identical tick either way
    runtime.solver_service.backend = "xla"

    def timed(enabled):
        plane.enabled = enabled
        t0 = time.perf_counter()
        tick()
        return (time.perf_counter() - t0) * 1e3

    off, on = [], []
    try:
        plane.enabled = True
        for _ in range(5):  # warmup: compiles (ledger-recorded), encodes
            tick()
        for round_i in range(args.introspect_ticks):
            if round_i % 2 == 0:
                off.append(timed(False))
                on.append(timed(True))
            else:
                on.append(timed(True))
                off.append(timed(False))
        records = plane.ledger.records_total
    finally:
        runtime.close()
    return off, on, records


def _append_introspect_row(path: str, record: dict) -> None:
    marker = "## Introspection overhead (make bench-introspect)"
    header = (
        f"\n{marker}\n\n"
        "Reconcile tick latency with the solver introspection plane "
        "(karpenter_tpu/observability/devicetelemetry.py: compile "
        "ledger + storm detection, device memory telemetry, resident-"
        "LRU byte accounting, XLA cost attribution) ENABLED vs "
        "DISABLED over the identical seeded world (the bench-journal/"
        "bench-trace/bench-provenance churn world). Acceptance target: "
        "introspection overhead under 2% of tick latency; introspect "
        "OFF is property-pinned byte-identical "
        "(tests/test_introspect.py).\n\n"
        "| Date | Backend | Ticks | Tick p50 off/on (ms) | Overhead | "
        "Ledger rows |\n"
        "|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['ticks']} "
        f"| {record['tick_p50_off_ms']} / {record['tick_p50_on_ms']} "
        f"| {record['overhead_pct']}% | {record['ledger_records']} |\n"
    )
    _append_table_row(path, marker, header, row)


def run_introspect(args, metric: str, note: str) -> None:
    """Solver-introspection overhead on the reconcile hot path (ISSUE
    15 acceptance: <=2% median paired tick overhead with telemetry
    on). Same seeded world both ways; the ENABLED configuration runs
    the real per-tick pass (compile-storm window, device memory poll,
    resident entry gauges) plus per-miss ledger/attribution work —
    zero at steady state, which is the point."""
    import jax

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    off, on, ledger_records = _introspect_tick_times(args)
    p50_off = float(np.percentile(off, 50))
    p50_on = float(np.percentile(on, 50))
    # median PAIRED difference (the bench-trace discipline)
    delta = float(np.median(np.asarray(on) - np.asarray(off)))
    overhead = (delta / p50_off) * 100.0 if p50_off else 0.0
    record = {
        "config": f"{args.introspect_ticks} ticks",
        "backend": jax.default_backend(),
        "ticks": args.introspect_ticks,
        "tick_p50_off_ms": round(p50_off, 3),
        "tick_p50_on_ms": round(p50_on, 3),
        "tick_p99_off_ms": round(float(np.percentile(off, 99)), 3),
        "tick_p99_on_ms": round(float(np.percentile(on, 99)), 3),
        "overhead_pct": round(overhead, 2),
        "ledger_records": ledger_records,
    }
    record_evidence(
        tick_off_ms=[round(t, 4) for t in off],
        tick_on_ms=[round(t, 4) for t in on],
        introspect=record,
    )
    print(
        f"tick p50 off={record['tick_p50_off_ms']}ms "
        f"on={record['tick_p50_on_ms']}ms "
        f"overhead={record['overhead_pct']}% | "
        f"{record['ledger_records']} compile-ledger rows (warm-up)",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_to_baseline(
            f"{record['config']} introspection overhead "
            f"({record['backend']})",
            record,
        )
    if args.append_benchmarks:
        _append_introspect_row(args.append_benchmarks, record)
    emit(
        f"{metric} ({jax.default_backend()})",
        p50_on,
        note=(
            f"{note}; " if note else ""
        ) + f"introspection overhead {record['overhead_pct']}% "
        f"(off p50 {record['tick_p50_off_ms']}ms), "
        f"{record['ledger_records']} compile-ledger rows",
        against_baseline=False,
    )


def _constraint_bench_world(args):
    """The constrained workload: membership over cycling constraint
    kinds compiled into masked operands on one BinPackInputs (the
    compiler path, so the spread exactness contract holds)."""
    import jax
    import jax.numpy as jnp

    from karpenter_tpu.api.core import RESERVATION_LABEL, ZONE_LABEL
    from karpenter_tpu.constraints import ConstraintGroup, SpreadSpec
    from karpenter_tpu.constraints.compiler import compile_rows

    rng = np.random.default_rng(args.seed)
    G = args.constraint_groups
    alloc = {"cpu": 64.0, "memory": 256.0, "pods": 110.0}
    zones = [f"z{i + 1}" for i in range(4)]
    profiles = []
    for t in range(args.types):
        labels = {(ZONE_LABEL, zones[t % len(zones)])}
        if t % 7 == 3:
            labels = {(RESERVATION_LABEL, f"res{t % 3}")}
        profiles.append((dict(alloc), labels, set()))
    kinds = ["spread", "reservation", "anti", "compact"]
    groups = []
    for g in range(G):
        kind = kinds[g % len(kinds)]
        sel = {"team": f"t{g}"}
        if kind == "spread":
            groups.append(ConstraintGroup(
                name=f"g{g}", pod_selector=sel, spread=SpreadSpec()
            ))
        elif kind == "reservation":
            groups.append(ConstraintGroup(
                name=f"g{g}", pod_selector=sel,
                reservation=f"res{g % 3}",
            ))
        elif kind == "anti":
            groups.append(ConstraintGroup(
                name=f"g{g}", pod_selector=sel, anti_affinity=True
            ))
        else:
            groups.append(ConstraintGroup(
                name=f"g{g}", pod_selector=sel, compact=True
            ))
    P = args.pods
    membership = rng.integers(0, G + 1, P).astype(np.int32)
    weights = rng.integers(1, 4, P).astype(np.int32)
    valid = np.ones(P, bool)
    compiled = compile_rows(membership, weights, valid, profiles, groups)
    P2 = len(compiled.rep)
    requests = np.zeros((P2, 3), np.float32)
    requests[:, 0] = rng.integers(1, 8, P2)  # cpu
    requests[:, 1] = rng.integers(1, 16, P2)  # memory
    requests[:, 2] = 1.0  # pods
    group_allocatable = np.tile(
        np.asarray([alloc["cpu"], alloc["memory"], alloc["pods"]],
                   np.float32),
        (args.types, 1),
    )
    from karpenter_tpu.ops.binpack import BinPackInputs

    base = dict(
        pod_requests=jnp.asarray(requests),
        pod_valid=jnp.ones(P2, bool),
        pod_intolerant=jnp.zeros((P2, 4), bool),
        pod_required=jnp.zeros((P2, 4), bool),
        group_allocatable=jnp.asarray(group_allocatable),
        group_taints=jnp.zeros((args.types, 4), bool),
        group_labels=jnp.zeros((args.types, 4), bool),
        pod_weight=jnp.asarray(compiled.row_weight),
    )
    for name, value in (
        ("pod_claim", compiled.claim),
        ("group_reservation", compiled.group_reservation),
        ("pod_pack_class", compiled.pack_class),
        ("pod_spread_slot", compiled.spread_slot),
        ("group_domain", compiled.group_domain),
        ("spread_cap", compiled.spread_cap),
        ("pod_exclusive", compiled.exclusive),
    ):
        if value is not None:
            base[name] = jnp.asarray(value)
    inputs = jax.device_put(BinPackInputs(**base))
    jax.block_until_ready(inputs)
    row_membership = membership[compiled.rep]
    return inputs, row_membership, G


def run_constraints(args, metric: str, note: str) -> None:  # lint: allow-complexity — bench arm: parity pin + interleaved timing + publish, linear
    """The constraint-plane headline: ONE batched masked-operand solve
    over every constraint group vs the per-group sequential loop a
    constraint-naive integration would run (G+1 dispatches of the same
    compiled program with the other groups' rows invalidated).
    Interleaved arms; per-group verdict parity pinned before timing."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from karpenter_tpu.ops.binpack import binpack

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    inputs, row_membership, G = _constraint_bench_world(args)

    def solo(g):
        rows = row_membership == g
        return _dc.replace(
            inputs,
            pod_valid=jnp.asarray(
                np.asarray(inputs.pod_valid) & rows
            ),
            pod_weight=jnp.asarray(np.where(
                rows, np.asarray(inputs.pod_weight), 0
            ).astype(np.int32)),
        )
    solos = [solo(g) for g in range(G + 1)]

    def batched_arm():
        return jax.block_until_ready(binpack(inputs, buckets=args.buckets))

    def sequential_arm():
        outs = []
        for s in solos:
            outs.append(
                jax.block_until_ready(binpack(s, buckets=args.buckets))
            )
        return outs

    # warm both programs, then pin parity: the batched verdicts on each
    # group's rows must equal that group's independent solve
    ref = batched_arm()
    per_group = sequential_arm()
    ref_assigned = np.asarray(ref.assigned)
    for g, out in enumerate(per_group):
        rows = row_membership == g
        if not rows.any():
            continue
        if not np.array_equal(
            np.asarray(out.assigned)[rows], ref_assigned[rows]
        ):
            emit(metric, None, error=(
                f"parity failure: group {g} solo verdicts diverge "
                f"from the batched solve"
            ))
            raise SystemExit(1)

    batched_ms, sequential_ms = [], []
    for i in range(args.iters):
        arms = [("b", batched_arm), ("s", sequential_arm)]
        if i % 2:  # interleave: flip arm order every iteration
            arms.reverse()
        for tag, fn in arms:
            t0 = time.perf_counter()
            fn()
            dt = (time.perf_counter() - t0) * 1e3
            (batched_ms if tag == "b" else sequential_ms).append(dt)

    p50_b = float(np.percentile(batched_ms, 50))
    p50_s = float(np.percentile(sequential_ms, 50))
    record = {
        "config": (
            f"{args.pods} pods x {args.types} types x "
            f"{G} constraint groups"
        ),
        "backend": jax.default_backend(),
        "groups": G,
        "batched_p50_ms": round(p50_b, 3),
        "sequential_p50_ms": round(p50_s, 3),
        "speedup": round(p50_s / p50_b, 2) if p50_b else 0.0,
        "dispatches_batched": 1,
        "dispatches_sequential": G + 1,
    }
    record_evidence(
        batched_ms=[round(t, 4) for t in batched_ms],
        sequential_ms=[round(t, 4) for t in sequential_ms],
        constraints=record,
    )
    print(
        f"batched p50={record['batched_p50_ms']}ms vs per-group "
        f"p50={record['sequential_p50_ms']}ms "
        f"({record['speedup']}x, {G + 1} dispatches -> 1)",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_to_baseline(
            f"{record['config']} constrained solve "
            f"({record['backend']})",
            record,
        )
    if args.append_benchmarks:
        _append_constraints_row(args.append_benchmarks, record)
    emit(
        f"{metric} ({jax.default_backend()})",
        p50_b,
        note=(
            f"{note}; " if note else ""
        ) + f"per-group sequential p50 {record['sequential_p50_ms']}ms "
        f"({record['speedup']}x); parity pinned",
        against_baseline=False,
    )


def _append_constraints_row(path: str, record: dict) -> None:
    marker = "## Constraint plane (make bench-constraints)"
    header = (
        f"\n{marker}\n\n"
        "One batched masked-operand solve carrying EVERY constraint "
        "group (zone spread + reservation claims + anti-affinity + "
        "compact placement compiled to integer operands; "
        "docs/constraints.md) vs the per-group sequential loop a "
        "constraint-naive integration would run (G+1 dispatches of the "
        "same compiled program). Interleaved arms; per-group verdict "
        "parity pinned before timing.\n\n"
        "| Date | Backend | Problem | Batched p50 (ms) | "
        "Per-group p50 (ms) | Speedup | Dispatches |\n"
        "|---|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['config']} "
        f"| {record['batched_p50_ms']} | {record['sequential_p50_ms']} "
        f"| {record['speedup']}x "
        f"| {record['dispatches_sequential']} -> 1 |\n"
    )
    _append_table_row(path, marker, header, row)


def _append_failover_row(path: str, record: dict) -> None:
    marker = "## Failover blackout (make bench-failover)"
    header = (
        f"\n{marker}\n\n"
        "Replicated-control-plane leader kill (karpenter_tpu/"
        "replication): the seeded failover world kills the biggest "
        "owner mid-storm; blackout is ticks from the kill until every "
        "victim tenant is back at its desired level under a survivor, "
        "with exactly-once actuation journal-audited across the "
        "handoff. Acceptance: blackout p99 within 3 lease durations, "
        "zero duplicate and zero lost writes.\n\n"
        "| Date | Backend | Tenants x Replicas | Partitions | Lease (s) "
        "| Blackout p99 (ticks / s) | Reconverge (ticks) | Dup / Lost "
        "|\n"
        "|---|---|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['tenants']} x "
        f"{record['replicas']} | {record['partitions']} "
        f"| {record['lease_duration_s']} "
        f"| {record['blackout_ticks_p99']} / {record['blackout_p99_s']} "
        f"| {record['reconverge_ticks']} "
        f"| {record['duplicate_actuations']} / "
        f"{record['lost_actuations']} |\n"
    )
    _append_table_row(path, marker, header, row)


def run_failover(args, metric: str, note: str) -> None:
    """Replicated-control-plane failover at fleet scale (ISSUE:
    replicated control plane): the seeded leader-kill world
    (simulate_failover — the `--simulate --failover` scenario) at
    --failover-tenants x --failover-replicas, auditing the handoff
    blackout and the exactly-once contract. Pure host-side control
    plane — no device dispatch — but the backend provenance is stamped
    anyway so a published row names the environment it ran in."""
    import time as _time

    import jax

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    from karpenter_tpu.simulate import simulate_failover

    t0 = _time.perf_counter()
    report = simulate_failover(
        tenants=args.failover_tenants,
        replicas=args.failover_replicas,
        partitions=args.failover_partitions,
        ticks=args.failover_ticks,
        seed=args.seed,
    )
    wall_s = _time.perf_counter() - t0
    if not report["converged"]:
        raise RuntimeError(
            "failover world failed to reconverge: "
            + json.dumps(report, sort_keys=True)[:500]
        )
    config = report["config"]
    record = {
        "config": f"{config['tenants']} tenants x "
        f"{config['replicas']} replicas",
        "backend": jax.default_backend(),
        "tenants": config["tenants"],
        "replicas": config["replicas"],
        "partitions": config["partitions"],
        "ticks": config["ticks"],
        "kill_tick": config["kill_tick"],
        "lease_duration_s": config["lease_duration_s"],
        "blackout_ticks_p99": report["blackout_ticks_p99"],
        "blackout_p99_s": report["blackout_s_p99"],
        "reconverge_ticks": report["reconverge_ticks"],
        "converged": report["converged"],
        "duplicate_actuations": report["duplicate_actuations"],
        "lost_actuations": report["lost_actuations"],
        "stale_write_rejected": report["stale_write_rejected"],
        "fence_rejections": report["fence_rejections"],
        "victim_tenants": len(report["victim_tenants"]),
        "handoffs_after_kill": report["handoffs_after_kill"],
        "writes_digest": report["writes_digest"],
        "wall_s": round(wall_s, 3),
    }
    record_evidence(failover=record)
    print(
        f"blackout p99={record['blackout_ticks_p99']} ticks "
        f"({record['blackout_p99_s']}s) reconverge="
        f"{record['reconverge_ticks']} ticks | victims="
        f"{record['victim_tenants']} dup="
        f"{record['duplicate_actuations']} lost="
        f"{record['lost_actuations']} stale_rejected="
        f"{record['stale_write_rejected']} | wall {record['wall_s']}s",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_to_baseline(
            f"{record['config']} failover ({record['backend']})", record
        )
    if args.append_benchmarks:
        _append_failover_row(args.append_benchmarks, record)
    emit(
        f"{metric} ({jax.default_backend()})",
        record["blackout_p99_s"] * 1e3,  # emit()'s unit is ms
        note=(
            f"{note}; " if note else ""
        ) + f"reconverge {record['reconverge_ticks']} ticks, "
        f"{record['victim_tenants']} victim tenants, dup/lost "
        f"{record['duplicate_actuations']}/"
        f"{record['lost_actuations']}",
        against_baseline=False,
    )


def run(args, metric: str, note: str) -> None:  # lint: allow-complexity — bench mode dispatch, one arm per measured configuration
    import jax

    _warm_native_kernel(args)

    if args.fusedtick:
        run_fusedtick(args, metric, note)
        return
    if args.failover:
        run_failover(args, metric, note)
        return
    if args.simlab:
        run_simlab(args, metric, note)
        return
    if args.constraints:
        run_constraints(args, metric, note)
        return
    if args.introspect:
        run_introspect(args, metric, note)
        return
    if args.eventloop:
        run_eventloop(args, metric, note)
        return
    if args.resident:
        run_resident(args, metric, note)
        return
    if args.journal:
        run_journal(args, metric, note)
        return
    if args.trace:
        run_trace(args, metric, note)
        return
    if args.provenance:
        run_provenance(args, metric, note)
        return
    if args.multitenant:
        run_multitenant(args, metric, note)
        return
    if args.poolgroup:
        run_poolgroup(args, metric, note)
        return
    if args.cost:
        run_cost(args, metric, note)
        return
    if args.preempt:
        run_preempt(args, metric, note)
        return
    if args.forecast:
        run_forecast(args, metric, note)
        return
    if args.hotpath:
        run_hotpath(args, metric, note)
        return
    if args.solver_service:
        run_solver_service(args, metric, note)
        return
    if args.consolidate:
        run_consolidate(args, metric, note)
        return
    if args.decide:
        run_decide(args, metric, note)
        return
    if args.e2e:
        run_e2e(args, metric, note)
        return

    from karpenter_tpu.ops.binpack import solve

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    inputs = jax.device_put(_bench_inputs(args))
    jax.block_until_ready(inputs)

    t0 = time.perf_counter()
    out = solve(inputs, buckets=args.buckets, backend=args.backend)
    jax.block_until_ready(out)
    compile_ms = (time.perf_counter() - t0) * 1e3
    print(f"first call (compile+run): {compile_ms:.1f} ms", file=sys.stderr)

    times = []
    for _ in range(args.iters):
        t0 = time.perf_counter()
        out = solve(inputs, buckets=args.buckets, backend=args.backend)
        jax.block_until_ready(out)
        times.append((time.perf_counter() - t0) * 1e3)
    record_evidence(
        compile_ms=round(compile_ms, 3),
        iter_ms=[round(t, 4) for t in times],
        transport_floor=measure_transport_floor(),
    )
    p50 = float(np.percentile(times, 50))
    p95 = float(np.percentile(times, 95))
    scheduled = int(np.sum(np.asarray(out.assigned) >= 0))
    # BASELINE.json's other axis: full-fleet bin-pack DECISIONS per
    # second, i.e. back-to-back solves of the whole problem
    dps = 1000.0 / p50 if p50 else 0.0
    print(
        f"p50={p50:.2f}ms p95={p95:.2f}ms scheduled={scheduled}/{args.pods} "
        f"unschedulable={int(out.unschedulable)} "
        f"nodes={int(np.sum(np.asarray(out.nodes_needed)))} "
        f"decisions/sec={dps:.0f}",
        file=sys.stderr,
    )
    extra = f"{dps:.0f} full-fleet decisions/sec"
    emit(
        f"{metric} ({jax.default_backend()})",
        p50,
        note=f"{note}; {extra}" if note else extra,
    )


def _measure_concurrent(call, inputs_list, iters: int):
    """Per-request latencies (ms) with len(inputs_list) submitter threads
    issuing `iters` sequential calls each — the concurrent-callers load
    shape the solver service's coalescing window exists for."""
    import threading

    latencies = [[] for _ in inputs_list]
    barrier = threading.Barrier(len(inputs_list))

    def submitter(i):
        barrier.wait()
        for _ in range(iters):
            t0 = time.perf_counter()
            call(inputs_list[i])
            latencies[i].append((time.perf_counter() - t0) * 1e3)

    threads = [
        threading.Thread(target=submitter, args=(i,))
        for i in range(len(inputs_list))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return [t for per in latencies for t in per]


def _solver_service_record(args, backend, direct, service, svc) -> dict:
    reqs = max(1, svc.stats.requests)
    return {
        "config": f"{args.pods} pods x {args.types} types",
        "backend": backend,
        "concurrency": args.concurrency,
        "direct_p50_ms": round(float(np.percentile(direct, 50)), 3),
        "direct_p99_ms": round(float(np.percentile(direct, 99)), 3),
        "service_p50_ms": round(float(np.percentile(service, 50)), 3),
        "service_p99_ms": round(float(np.percentile(service, 99)), 3),
        "avg_coalesce_factor": round(reqs / max(1, svc.stats.dispatches), 2),
        "dispatches": svc.stats.dispatches,
        "requests": svc.stats.requests,
        "compile_cache_misses": svc.stats.compile_cache_misses,
    }


def _publish_to_baseline(key: str, record: dict) -> None:
    """Land a result in BASELINE.json's `published` map (the satellite
    contract: measured configs graduate from claim to committed data).
    Shared by every publishing bench mode."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE.json")
    with open(path) as f:
        baseline = json.load(f)
    baseline.setdefault("published", {})[key] = {
        k: v for k, v in record.items() if k != "config"
    }
    with open(path, "w") as f:
        json.dump(baseline, f, indent=2)
        f.write("\n")
    print(f"published to BASELINE.json: {key}", file=sys.stderr)


def _append_table_row(path: str, marker: str, header: str, row: str) -> None:
    """Append one markdown row to the benchmarks table identified by
    `marker`, creating the section (at end of file) on first use.
    Shared by every publishing bench mode.

    The row lands at the end of the MARKER'S OWN table, not the end of
    the file: once several sections exist, an EOF append would splice a
    row into whatever table happened to be last (which is exactly how
    the hot-path table once grew a bench-solver-shaped row)."""
    with open(path) as f:
        content = f.read()
    if not row.endswith("\n"):
        row += "\n"
    if marker not in content:
        with open(path, "w") as f:
            f.write(
                content.rstrip("\n") + "\n"
                + header.rstrip("\n") + "\n" + row
            )
        print(f"appended row to {path}", file=sys.stderr)
        return
    lines = content.splitlines(keepends=True)
    start = next(
        i for i, line in enumerate(lines) if line.startswith(marker)
    )
    insert_at = len(lines)
    last_table_line = None
    for i in range(start + 1, len(lines)):
        if lines[i].startswith("## "):  # the next section
            insert_at = i
            break
        if lines[i].lstrip().startswith("|"):
            last_table_line = i
    if last_table_line is not None:
        insert_at = last_table_line + 1
    lines.insert(insert_at, row)
    with open(path, "w") as f:
        f.write("".join(lines))
    print(f"appended row to {path}", file=sys.stderr)


def _publish_solver_baseline(record: dict) -> None:
    _publish_to_baseline(
        f"{record['config']} solver service ({record['backend']})", record
    )


def _append_benchmarks_row(path: str, record: dict) -> None:
    marker = "## Solver service (make bench-solver)"
    header = (
        f"\n{marker}\n\n"
        "Direct `ops/binpack` calls vs. the shared solve service "
        "(coalescing + shape-bucketed compile cache), same concurrent "
        "load on both paths.\n\n"
        "| Date | Backend | Config | Callers | Direct p50/p99 (ms) | "
        "Service p50/p99 (ms) | Avg coalesce | Dispatches |\n"
        "|---|---|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['config']} "
        f"| {record['concurrency']} "
        f"| {record['direct_p50_ms']} / {record['direct_p99_ms']} "
        f"| {record['service_p50_ms']} / {record['service_p99_ms']} "
        f"| {record['avg_coalesce_factor']}x "
        f"| {record['dispatches']} |\n"
    )
    _append_table_row(path, marker, header, row)


def run_solver_service(args, metric: str, note: str) -> None:
    """Direct vs. coalesced: the same C-concurrent-callers load through
    plain ops/binpack.solve and through the shared solve service. The
    service number includes its queue/window/scatter overhead — the
    honest cost of coalescing — while direct calls contend for the
    device serially."""
    import jax

    from karpenter_tpu.ops.binpack import solve as direct_solve
    from karpenter_tpu.solver import SolverService

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    # distinct seeds so coalesced problems are genuinely different work;
    # same shape = one compile bucket, as in a fleet of same-scale ticks
    inputs_list = [
        build_inputs(
            args.pods, args.types, args.taints, args.labels,
            args.seed + i, affinity=args.affinity, anti=args.anti,
        )
        for i in range(args.concurrency)
    ]

    def direct(x):
        jax.block_until_ready(
            direct_solve(x, buckets=args.buckets, backend=args.backend)
        )

    svc = SolverService(
        window_s=0.002, max_batch=args.concurrency, backend=args.backend
    )

    def through_service(x):
        svc.solve(x, buckets=args.buckets)

    try:
        # warm both paths outside the timed region (compiles + first
        # coalesced batch size)
        t0 = time.perf_counter()
        direct(inputs_list[0])
        _measure_concurrent(through_service, inputs_list, 1)
        print(
            f"warmup (compiles): {(time.perf_counter() - t0) * 1e3:.1f} ms",
            file=sys.stderr,
        )
        direct_lat = _measure_concurrent(direct, inputs_list, args.iters)
        service_lat = _measure_concurrent(
            through_service, inputs_list, args.iters
        )
        record = _solver_service_record(
            args, jax.default_backend(), direct_lat, service_lat, svc
        )
    finally:
        svc.close()
    record_evidence(
        direct_iter_ms=[round(t, 4) for t in direct_lat],
        service_iter_ms=[round(t, 4) for t in service_lat],
        solver_service=record,
        transport_floor=measure_transport_floor(),
    )
    print(
        f"direct p50={record['direct_p50_ms']}ms "
        f"p99={record['direct_p99_ms']}ms | service "
        f"p50={record['service_p50_ms']}ms "
        f"p99={record['service_p99_ms']}ms "
        f"coalesce={record['avg_coalesce_factor']}x "
        f"dispatches={record['dispatches']}",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_solver_baseline(record)
    if args.append_benchmarks:
        _append_benchmarks_row(args.append_benchmarks, record)
    extra = (
        f"direct p50={record['direct_p50_ms']}ms/"
        f"p99={record['direct_p99_ms']}ms; coalesce "
        f"{record['avg_coalesce_factor']}x over "
        f"{record['requests']} requests in "
        f"{record['dispatches']} dispatches"
    )
    emit(
        f"{metric} ({jax.default_backend()})",
        record["service_p50_ms"],
        note=f"{note}; {extra}" if note else extra,
    )


def _hotpath_record(args, backend, direct_idle, service_idle,
                    service_conc, svc, idle_stages=None) -> dict:
    """The hotpath evidence record: idle-queue service-vs-direct (the
    acceptance ratio), the concurrent coalesce factor (must be
    preserved), and the per-stage breakdown — queue-wait, pad
    (the service-side encode), dispatch, scatter (the crop).
    `idle_stages` is the stage snapshot taken right after the
    closed-loop idle phase — its `upload` p50 is the unchanged-fleet
    transfer cost the device-resident fleet state drives to ~0
    (identity hits record 0.0 upload samples)."""
    direct_p50 = float(np.percentile(direct_idle, 50))
    service_p50 = float(np.percentile(service_idle, 50))
    reqs = max(1, svc.stats.requests)
    idle_upload = None
    if idle_stages and "upload" in idle_stages:
        idle_upload = idle_stages["upload"]["p50_ms"]
    return {
        "idle_upload_p50_ms": idle_upload,
        "resident_hits": svc.stats.resident_hits,
        "config": f"{args.pods} pods x {args.types} types",
        "backend": backend,
        "concurrency": args.concurrency,
        "direct_idle_p50_ms": round(direct_p50, 3),
        "direct_idle_p99_ms": round(
            float(np.percentile(direct_idle, 99)), 3
        ),
        "service_idle_p50_ms": round(service_p50, 3),
        "service_idle_p99_ms": round(
            float(np.percentile(service_idle, 99)), 3
        ),
        "idle_ratio": round(service_p50 / max(direct_p50, 1e-9), 3),
        "service_concurrent_p50_ms": round(
            float(np.percentile(service_conc, 50)), 3
        ),
        "avg_coalesce_factor": round(
            reqs / max(1, svc.stats.dispatches), 2
        ),
        "dispatches": svc.stats.dispatches,
        "requests": svc.stats.requests,
        "compile_cache_misses": svc.stats.compile_cache_misses,
        "immediate_dispatches": svc.stats.immediate_dispatches,
        "pipeline_overlaps": svc.stats.pipeline_overlaps,
        "stage_p50_ms": {
            stage: p["p50_ms"]
            for stage, p in svc.stage_percentiles().items()
        },
    }


def _publish_hotpath_baseline(record: dict) -> None:
    _publish_to_baseline(
        f"{record['config']} solver hotpath ({record['backend']})", record
    )


def _append_hotpath_row(path: str, record: dict) -> None:
    marker = "## Solver hot path (make bench-hotpath)"
    header = (
        f"\n{marker}\n\n"
        "Idle-queue single-caller latency through the service vs a "
        "direct `ops/binpack` call — the adaptive-window guard (the "
        "ratio is the acceptance bound) — plus the coalesce factor "
        "under concurrent load, which pipelined dispatch must "
        "preserve. Stage columns are the service-side breakdown: "
        "queue-wait, pad (encode), upload (host->device transfer, "
        "isolated), dispatch, scatter (crop).\n\n"
        "| Date | Backend | Config | Direct idle p50 (ms) | Service "
        "idle p50 (ms) | Ratio | Coalesce (concurrent) | queue-wait / "
        "pad / upload / dispatch / scatter p50 (ms) |\n"
        "|---|---|---|---|---|---|---|---|\n"
    )
    stages = record["stage_p50_ms"]
    breakdown = " / ".join(
        str(stages.get(s, "-"))
        for s in ("queue_wait", "pad", "upload", "dispatch", "scatter")
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['config']} "
        f"| {record['direct_idle_p50_ms']} "
        f"| {record['service_idle_p50_ms']} "
        f"| {record['idle_ratio']}x "
        f"| {record['avg_coalesce_factor']}x @ {record['concurrency']} "
        f"| {breakdown} |\n"
    )
    _append_table_row(path, marker, header, row)


def run_hotpath(args, metric: str, note: str) -> None:
    """The solver hot-path acceptance measurement: a LONE caller on an
    idle queue must ride the service at direct-call latency (adaptive
    window: no batching-timer tax), while a concurrent burst must still
    coalesce. Per-stage p50s localize any regression to queue-wait /
    pad / dispatch / scatter."""
    import jax

    from karpenter_tpu.ops.binpack import solve as direct_solve
    from karpenter_tpu.solver import SolverService

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    inputs_list = [
        build_inputs(
            args.pods, args.types, args.taints, args.labels,
            args.seed + i, affinity=args.affinity, anti=args.anti,
        )
        for i in range(args.concurrency)
    ]
    single = inputs_list[0]

    def direct(x):
        jax.block_until_ready(
            direct_solve(x, buckets=args.buckets, backend=args.backend)
        )

    svc = SolverService(
        window_s=0.002, max_batch=args.concurrency, backend=args.backend
    )

    def through_service(x):
        svc.solve(x, buckets=args.buckets)

    try:
        t0 = time.perf_counter()
        direct(single)
        _measure_concurrent(through_service, inputs_list, 1)  # warm
        print(
            f"warmup (compiles): {(time.perf_counter() - t0) * 1e3:.1f} ms",
            file=sys.stderr,
        )
        direct_idle, service_idle = [], []
        for _ in range(args.iters):
            t0 = time.perf_counter()
            direct(single)
            direct_idle.append((time.perf_counter() - t0) * 1e3)
        for _ in range(args.iters):
            t0 = time.perf_counter()
            through_service(single)
            service_idle.append((time.perf_counter() - t0) * 1e3)
        # snapshot the stage rings HERE: the idle loop is the
        # unchanged-fleet closed loop (same inputs object each tick),
        # whose upload p50 the resident fleet state drives to ~0 — the
        # concurrent burst below would dilute it with real uploads
        idle_stages = svc.stage_percentiles()
        service_conc = _measure_concurrent(
            through_service, inputs_list, args.iters
        )
        record = _hotpath_record(
            args, jax.default_backend(), direct_idle, service_idle,
            service_conc, svc, idle_stages=idle_stages,
        )
    finally:
        svc.close()
    record_evidence(
        direct_idle_iter_ms=[round(t, 4) for t in direct_idle],
        service_idle_iter_ms=[round(t, 4) for t in service_idle],
        service_concurrent_iter_ms=[round(t, 4) for t in service_conc],
        hotpath=record,
        stage_percentiles=record["stage_p50_ms"],
        transport_floor=measure_transport_floor(),
    )
    print(
        f"idle: direct p50={record['direct_idle_p50_ms']}ms | service "
        f"p50={record['service_idle_p50_ms']}ms "
        f"(ratio {record['idle_ratio']}x) | concurrent service "
        f"p50={record['service_concurrent_p50_ms']}ms "
        f"coalesce={record['avg_coalesce_factor']}x | unchanged-fleet "
        f"upload p50={record['idle_upload_p50_ms']}ms "
        f"({record['resident_hits']} resident hits) | stages "
        f"{record['stage_p50_ms']}",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_hotpath_baseline(record)
    if args.append_benchmarks:
        _append_hotpath_row(args.append_benchmarks, record)
    extra = (
        f"direct idle p50={record['direct_idle_p50_ms']}ms (ratio "
        f"{record['idle_ratio']}x); coalesce "
        f"{record['avg_coalesce_factor']}x under {args.concurrency} "
        f"callers; stages(ms) {record['stage_p50_ms']}"
    )
    emit(
        f"{metric} ({jax.default_backend()})",
        record["service_idle_p50_ms"],
        note=f"{note}; {extra}" if note else extra,
        against_baseline=False,
    )


def build_consolidation_cluster(candidates: int, pods: int, seed: int):
    """A synthetic fragmented cluster in the in-memory store: every node
    is a drain candidate; utilization is deliberately uneven (rng pod
    counts, small requests) so a realistic fraction of drains fit."""
    from karpenter_tpu.api.core import (
        Container,
        Node,
        NodeCondition,
        NodeSpec,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from karpenter_tpu.api.metricsproducer import (
        MetricsProducer,
        MetricsProducerSpec,
        PendingCapacitySpec,
    )
    from karpenter_tpu.store import Store
    from karpenter_tpu.utils.quantity import Quantity

    rng = np.random.default_rng(seed)
    store = Store()
    store.create(
        MetricsProducer(
            metadata=ObjectMeta(name="bench"),
            spec=MetricsProducerSpec(
                pending_capacity=PendingCapacitySpec(
                    node_selector={"pool": "bench"},
                    node_group_ref="bench-group",
                )
            ),
        )
    )
    for n in range(candidates):
        store.create(
            Node(
                metadata=ObjectMeta(
                    name=f"node-{n:04d}", labels={"pool": "bench"}
                ),
                spec=NodeSpec(),
                status=NodeStatus(
                    allocatable={
                        "cpu": Quantity.parse("16"),
                        "memory": Quantity.parse("64Gi"),
                        "pods": Quantity.parse("110"),
                    },
                    conditions=[NodeCondition("Ready", "True")],
                ),
            )
        )
    # skewed spread (u^2 concentrates pods on low-index nodes): the head
    # nodes run hot and veto, the long tail is lightly loaded and drains
    # — the fragmented-cluster shape consolidation exists for
    for i in range(pods):
        n = int(candidates * rng.random() ** 2) % candidates
        cpu = float(rng.choice([0.25, 0.5, 1.0, 2.0]))
        store.create(
            Pod(
                metadata=ObjectMeta(name=f"pod-{i:05d}"),
                spec=PodSpec(
                    node_name=f"node-{n:04d}",
                    containers=[
                        Container(
                            requests={
                                "cpu": Quantity.parse(str(cpu)),
                                "memory": Quantity.parse(
                                    f"{int(cpu * 2048)}Mi"
                                ),
                            }
                        )
                    ],
                ),
            )
        )
    return store


def _consolidate_record(args, backend, batched, sequential,
                        candidates: int, drainable: int, svc) -> dict:
    batched_p50 = float(np.percentile(batched, 50))
    sequential_p50 = float(np.percentile(sequential, 50))
    return {
        "config": (
            f"{candidates} candidates x {args.pods} bound pods "
            f"consolidation"
        ),
        "backend": backend,
        "candidates": candidates,
        "drainable": drainable,
        "batched_p50_ms": round(batched_p50, 3),
        "sequential_p50_ms": round(sequential_p50, 3),
        "batched_cps": round(candidates * 1000.0 / batched_p50, 1),
        "sequential_cps": round(
            candidates * 1000.0 / sequential_p50, 1
        ),
        "speedup": round(sequential_p50 / batched_p50, 2),
        "dispatches": svc.stats.dispatches,
        "compile_cache_misses": svc.stats.compile_cache_misses,
    }


def _publish_consolidate_baseline(record: dict) -> None:
    _publish_to_baseline(
        f"{record['config']} ({record['backend']})", record
    )


def _append_consolidate_row(path: str, record: dict) -> None:
    marker = "## Consolidation (make bench-consolidate)"
    header = (
        f"\n{marker}\n\n"
        "Batched drain-candidate evaluation (`service.consolidate`: one "
        "device dispatch for every candidate in a shape bucket) vs. the "
        "same masked bin-packs submitted sequentially through the "
        "service.\n\n"
        "| Date | Backend | Config | Batched p50 (ms) | Sequential p50 "
        "(ms) | Batched cand/s | Sequential cand/s | Speedup |\n"
        "|---|---|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['config']} "
        f"| {record['batched_p50_ms']} | {record['sequential_p50_ms']} "
        f"| {record['batched_cps']} | {record['sequential_cps']} "
        f"| {record['speedup']}x |\n"
    )
    _append_table_row(path, marker, header, row)


def _warm_and_check_consolidate(svc, inputs, args) -> int:
    """Warm both submission paths' compiles outside the timed region and
    assert their verdicts agree; returns the drainable count."""
    from karpenter_tpu.consolidation import drainable

    batched_out = svc.consolidate(inputs, buckets=args.buckets)
    sequential_out = [
        svc.solve(x, buckets=args.buckets) for x in inputs
    ]
    mismatch = sum(
        drainable(a) != drainable(b)
        for a, b in zip(batched_out, sequential_out)
    )
    if mismatch:
        raise AssertionError(
            f"{mismatch} verdict(s) differ between batched and "
            "sequential paths"
        )
    return sum(drainable(o) for o in batched_out)


def run_consolidate(args, metric: str, note: str) -> None:
    """Batched vs sequential candidate evaluation: the consolidation
    acceptance claim. Both paths run the IDENTICAL masked per-candidate
    bin-packs through the shared solve service; only the submission
    shape differs — one atomic `consolidate` batch (one dispatch per
    shape bucket) vs. one `solve` at a time (one dispatch each)."""
    import jax

    from karpenter_tpu.consolidation import (
        build_problems,
        cluster_view,
        drainable,
    )
    from karpenter_tpu.solver import SolverService

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    store = build_consolidation_cluster(
        args.candidates, args.pods, args.seed
    )
    view = cluster_view(store)
    solved, inputs, trivial = build_problems(
        view, [nv.name for nv in view.nodes]
    )
    print(
        f"candidates: {len(solved)} solved + {len(trivial)} empty",
        file=sys.stderr,
    )
    backend = args.backend
    svc = SolverService(window_s=0.002, max_batch=8, backend=backend)
    try:
        n_drainable = _warm_and_check_consolidate(svc, inputs, args)
        batched_times, sequential_times = [], []
        for _ in range(args.iters):
            t0 = time.perf_counter()
            svc.consolidate(inputs, buckets=args.buckets)
            batched_times.append((time.perf_counter() - t0) * 1e3)
        for _ in range(args.iters):
            t0 = time.perf_counter()
            for x in inputs:
                svc.solve(x, buckets=args.buckets)
            sequential_times.append((time.perf_counter() - t0) * 1e3)
        record = _consolidate_record(
            args, jax.default_backend(), batched_times,
            sequential_times, len(solved), n_drainable, svc,
        )
    finally:
        svc.close()
    record_evidence(
        batched_iter_ms=[round(t, 4) for t in batched_times],
        sequential_iter_ms=[round(t, 4) for t in sequential_times],
        consolidate=record,
        transport_floor=measure_transport_floor(),
    )
    print(
        f"batched p50={record['batched_p50_ms']}ms "
        f"({record['batched_cps']} cand/s) | sequential "
        f"p50={record['sequential_p50_ms']}ms "
        f"({record['sequential_cps']} cand/s) | "
        f"speedup={record['speedup']}x "
        f"drainable={record['drainable']}/{record['candidates']}",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_consolidate_baseline(record)
    if args.append_benchmarks:
        _append_consolidate_row(args.append_benchmarks, record)
    extra = (
        f"{record['batched_cps']} vs {record['sequential_cps']} "
        f"candidates/sec batched vs sequential "
        f"({record['speedup']}x); {record['drainable']}/"
        f"{record['candidates']} drainable"
    )
    emit(
        f"{metric} ({jax.default_backend()})",
        record["batched_p50_ms"],
        note=f"{note}; {extra}" if note else extra,
        against_baseline=False,
    )


def build_preempt_inputs(candidates: int, types: int, pods: int, seed: int):
    """A synthetic contended fleet for eviction planning: mostly-full
    node columns, priority-striped victim occupancy (sorted by
    (node, priority) — the kernel's contract), and high-priority
    candidate pods big enough that most placements need evictions."""
    from karpenter_tpu.ops.preempt import PreemptInputs

    rng = np.random.default_rng(seed)
    C, N, V, R = candidates, types, pods, 4
    node_free = rng.uniform(0.0, 2.0, (N, R)).astype(np.float32)
    node_tier = (rng.random(N) < 0.3).astype(np.int32)
    victim_node = np.sort(rng.integers(0, N, V)).astype(np.int32)
    victim_priority = np.zeros(V, np.int32)
    for n in range(N):
        seg = victim_node == n
        victim_priority[seg] = np.sort(rng.integers(0, 500, seg.sum()))
    return PreemptInputs(
        pod_requests=rng.uniform(1.0, 6.0, (C, R)).astype(np.float32),
        pod_priority=rng.integers(100, 1000, C).astype(np.int32),
        pod_valid=np.ones(C, bool),
        pod_node_forbidden=rng.random((C, N)) < 0.1,
        node_free=node_free,
        node_tier=node_tier,
        victim_requests=rng.uniform(0.1, 2.0, (V, R)).astype(
            np.float32
        ),
        victim_priority=victim_priority,
        victim_node=victim_node,
        victim_valid=np.ones(V, bool),
        victim_evictable=rng.random(V) < 0.95,
    )


def _single_candidate_inputs(inputs, c: int):
    """The same fleet, one candidate — what a per-candidate caller
    would submit (quantization scales are fleet-derived, so the plans
    match the batched rows bit for bit)."""
    import dataclasses

    return dataclasses.replace(
        inputs,
        pod_requests=inputs.pod_requests[c : c + 1],
        pod_priority=inputs.pod_priority[c : c + 1],
        pod_valid=inputs.pod_valid[c : c + 1],
        pod_node_forbidden=inputs.pod_node_forbidden[c : c + 1],
    )


def _warm_and_check_preempt(svc, inputs, args) -> int:
    """Warm both submission paths' compiles outside the timed region;
    assert batched plans == independent per-candidate plans == the
    numpy mirror, element for element. Returns the placed count."""
    from karpenter_tpu.ops.preempt import preempt_numpy

    batched = svc.preempt(inputs)
    mirror = preempt_numpy(inputs)
    for field in ("chosen_node", "evict_count", "evict_mask"):
        if not np.array_equal(
            np.asarray(getattr(batched, field)),
            np.asarray(getattr(mirror, field)),
        ):
            raise AssertionError(f"device/numpy mismatch on {field}")
    for c in range(args.candidates):
        single = svc.preempt(_single_candidate_inputs(inputs, c))
        if int(single.chosen_node[0]) != int(batched.chosen_node[c]):
            raise AssertionError(
                f"candidate {c}: batched plan != independent plan"
            )
    return int((np.asarray(batched.chosen_node) >= 0).sum())


def _preempt_record(args, backend, batched, sequential, placed: int,
                    svc) -> dict:
    batched_p50 = float(np.percentile(batched, 50))
    sequential_p50 = float(np.percentile(sequential, 50))
    return {
        "config": (
            f"{args.candidates} candidates x {args.types} node "
            f"columns x {args.pods} victims eviction planning"
        ),
        "backend": backend,
        "candidates": args.candidates,
        "placed": placed,
        "batched_p50_ms": round(batched_p50, 3),
        "sequential_p50_ms": round(sequential_p50, 3),
        "batched_cps": round(
            args.candidates * 1000.0 / batched_p50, 1
        ),
        "sequential_cps": round(
            args.candidates * 1000.0 / sequential_p50, 1
        ),
        "speedup": round(sequential_p50 / batched_p50, 2),
        "dispatches": svc.stats.preempt_dispatches,
        "compile_cache_misses": svc.stats.compile_cache_misses,
    }


def _append_preempt_row(path: str, record: dict) -> None:
    marker = "## Preemption (make bench-preempt)"
    header = (
        f"\n{marker}\n\n"
        "Batched eviction planning (`service.preempt`: every candidate "
        "pod's minimal-eviction placement in ONE device dispatch) vs. "
        "the same plans submitted one candidate at a time.\n\n"
        "| Date | Backend | Config | Batched p50 (ms) | Sequential p50 "
        "(ms) | Batched cand/s | Sequential cand/s | Speedup |\n"
        "|---|---|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['config']} "
        f"| {record['batched_p50_ms']} | {record['sequential_p50_ms']} "
        f"| {record['batched_cps']} | {record['sequential_cps']} "
        f"| {record['speedup']}x |\n"
    )
    _append_table_row(path, marker, header, row)


def run_preempt(args, metric: str, note: str) -> None:
    """Batched vs sequential eviction planning: the preemption
    acceptance claim (docs/preemption.md). Both paths run IDENTICAL
    per-candidate plans through the shared solve service; only the
    submission shape differs — all candidates in one PreemptInputs
    (one dispatch) vs. one single-candidate problem at a time."""
    import jax

    from karpenter_tpu.solver import SolverService

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    inputs = build_preempt_inputs(
        args.candidates, args.types, args.pods, args.seed
    )
    singles = [
        _single_candidate_inputs(inputs, c)
        for c in range(args.candidates)
    ]
    backend = args.backend
    svc = SolverService(window_s=0.002, max_batch=8, backend=backend)
    try:
        placed = _warm_and_check_preempt(svc, inputs, args)
        batched_times, sequential_times = [], []
        for _ in range(args.iters):
            t0 = time.perf_counter()
            svc.preempt(inputs)
            batched_times.append((time.perf_counter() - t0) * 1e3)
        for _ in range(args.iters):
            t0 = time.perf_counter()
            for single in singles:
                svc.preempt(single)
            sequential_times.append((time.perf_counter() - t0) * 1e3)
        record = _preempt_record(
            args, jax.default_backend(), batched_times,
            sequential_times, placed, svc,
        )
    finally:
        svc.close()
    record_evidence(
        batched_iter_ms=[round(t, 4) for t in batched_times],
        sequential_iter_ms=[round(t, 4) for t in sequential_times],
        preempt=record,
        transport_floor=measure_transport_floor(),
    )
    print(
        f"batched p50={record['batched_p50_ms']}ms "
        f"({record['batched_cps']} cand/s) | sequential "
        f"p50={record['sequential_p50_ms']}ms "
        f"({record['sequential_cps']} cand/s) | "
        f"speedup={record['speedup']}x "
        f"placed={record['placed']}/{record['candidates']}",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_to_baseline(
            f"{record['config']} ({record['backend']})", record
        )
    if args.append_benchmarks:
        _append_preempt_row(args.append_benchmarks, record)
    extra = (
        f"{record['batched_cps']} vs {record['sequential_cps']} "
        f"candidates/sec batched vs sequential "
        f"({record['speedup']}x); {record['placed']}/"
        f"{record['candidates']} placeable"
    )
    emit(
        f"{metric} ({jax.default_backend()})",
        record["batched_p50_ms"],
        note=f"{note}; {extra}" if note else extra,
        against_baseline=False,
    )


def build_forecast_inputs(series: int, history: int, seed: int):
    """A fleet of metric histories: mixed flat/ramping/seasonal series
    with gaps, half Holt-Winters and half robust-linear — the shape the
    BatchAutoscaler hands the service every tick."""
    from karpenter_tpu.forecast.models import ForecastInputs

    rng = np.random.RandomState(seed)
    S, T = series, history
    base = rng.uniform(5, 500, (S, 1)).astype(np.float32)
    slope = rng.uniform(-0.5, 2.0, (S, 1)).astype(np.float32)
    ticks = np.arange(T, dtype=np.float32)[None, :]
    seasonal = (
        rng.uniform(0, 30, (S, 1))
        * np.sin(ticks * 2 * np.pi / 12)
    ).astype(np.float32)
    noise = rng.normal(0, 3, (S, T)).astype(np.float32)
    values = (base + slope * ticks * 10.0 + seasonal + noise).astype(
        np.float32
    )
    valid = rng.rand(S, T) > 0.1
    times = ((ticks - (T - 1)) * 10.0).repeat(S, axis=0).astype(np.float32)
    horizon = rng.uniform(30, 120, S).astype(np.float32)
    weights = np.power(
        np.float32(0.5), (-times) / horizon[:, None]
    ).astype(np.float32)
    return ForecastInputs(
        values=values, valid=valid, times=times, weights=weights,
        horizon=horizon,
        step_s=np.full(S, 10.0, np.float32),
        model=(np.arange(S) % 2).astype(np.int32),
        season=rng.choice([0, 6, 12], S).astype(np.int32),
        alpha=np.full(S, 0.5, np.float32),
        beta=np.full(S, 0.1, np.float32),
        gamma=np.full(S, 0.3, np.float32),
    )


def _forecast_record(args, backend, batched, per_series) -> dict:
    batched_p50 = float(np.percentile(batched, 50))
    loop_p50 = float(np.percentile(per_series, 50))
    return {
        "config": f"{args.series} series x {args.history} samples "
                  "forecast",
        "backend": backend,
        "series": args.series,
        "history": args.history,
        "batched_p50_ms": round(batched_p50, 3),
        "per_series_p50_ms": round(loop_p50, 3),
        "batched_sps": round(args.series * 1000.0 / batched_p50, 1),
        "per_series_sps": round(args.series * 1000.0 / loop_p50, 1),
        "speedup": round(loop_p50 / batched_p50, 2),
    }


def _publish_forecast_baseline(record: dict) -> None:
    _publish_to_baseline(
        f"{record['config']} ({record['backend']})", record
    )


def _append_forecast_row(path: str, record: dict) -> None:
    marker = "## Forecast (make bench-forecast)"
    header = (
        f"\n{marker}\n\n"
        "Batched fleet forecast (every metric series in ONE device "
        "dispatch — the shape the BatchAutoscaler submits each tick) "
        "vs. the same series forecast one dispatch at a time.\n\n"
        "| Date | Backend | Config | Batched p50 (ms) | Per-series p50 "
        "(ms) | Batched series/s | Per-series series/s | Speedup |\n"
        "|---|---|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['config']} "
        f"| {record['batched_p50_ms']} | {record['per_series_p50_ms']} "
        f"| {record['batched_sps']} | {record['per_series_sps']} "
        f"| {record['speedup']}x |\n"
    )
    _append_table_row(path, marker, header, row)


def _measure_forecast(args, inputs, rows):
    """Timed batched vs per-series loops (compiles warmed outside)."""
    import jax

    from karpenter_tpu.forecast.models import forecast_jit

    jax.block_until_ready(forecast_jit(inputs))
    jax.block_until_ready(forecast_jit(rows[0]))
    batched_times, per_series_times = [], []
    for _ in range(args.iters):
        t0 = time.perf_counter()
        jax.block_until_ready(forecast_jit(inputs))
        batched_times.append((time.perf_counter() - t0) * 1e3)
    for _ in range(args.iters):
        t0 = time.perf_counter()
        for row in rows:
            jax.block_until_ready(forecast_jit(row))
        per_series_times.append((time.perf_counter() - t0) * 1e3)
    return batched_times, per_series_times


def run_forecast(args, metric: str, note: str) -> None:
    """Batched vs per-series forecasting: the predictive subsystem's
    one-dispatch claim (docs/forecasting.md). Both paths run the
    IDENTICAL jitted kernel on the identical histories; only the
    dispatch shape differs — one [S, T] program vs S [1, T] programs
    (the second compiled once and reused, so the gap is pure dispatch
    and launch overhead, not recompiles)."""
    import dataclasses

    import jax

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    inputs = build_forecast_inputs(args.series, args.history, args.seed)
    rows = [
        dataclasses.replace(
            inputs,
            **{
                f.name: np.asarray(getattr(inputs, f.name))[i: i + 1]
                for f in dataclasses.fields(inputs)
            },
        )
        for i in range(args.series)
    ]
    batched_times, per_series_times = _measure_forecast(
        args, inputs, rows
    )
    record = _forecast_record(
        args, jax.default_backend(), batched_times, per_series_times
    )
    record_evidence(
        batched_iter_ms=[round(t, 4) for t in batched_times],
        per_series_iter_ms=[round(t, 4) for t in per_series_times],
        forecast=record,
        transport_floor=measure_transport_floor(),
    )
    print(
        f"batched p50={record['batched_p50_ms']}ms "
        f"({record['batched_sps']} series/s) | per-series "
        f"p50={record['per_series_p50_ms']}ms "
        f"({record['per_series_sps']} series/s) | "
        f"speedup={record['speedup']}x",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_forecast_baseline(record)
    if args.append_benchmarks:
        _append_forecast_row(args.append_benchmarks, record)
    extra = (
        f"{record['batched_sps']} vs {record['per_series_sps']} "
        f"series/sec batched vs per-series ({record['speedup']}x)"
    )
    emit(
        f"{metric} ({jax.default_backend()})",
        record["batched_p50_ms"],
        note=f"{note}; {extra}" if note else extra,
        against_baseline=False,
    )


def build_cost_inputs(rows: int, metrics: int, seed: int):
    """A fleet of SLO-opted autoscaler rows: mixed demand regimes, a
    spread of unit costs and violation weights, some budget-capped and
    some forecast-sigma'd — the shape the CostEngine hands the service
    each tick (every row slo_valid: the bench measures the refine, not
    the opt-out)."""
    from karpenter_tpu.ops.cost import CostInputs

    rng = np.random.RandomState(seed)
    N, M = rows, metrics
    base = rng.randint(1, 200, N).astype(np.int32)
    return CostInputs(
        base_desired=base,
        min_replicas=np.maximum(base - 50, 0).astype(np.int32),
        max_replicas=(base + rng.randint(50, 500, N)).astype(np.int32),
        unit_cost=rng.choice([0.07, 0.19, 1.0, 4.8], N).astype(np.float32),
        slo_weight=rng.choice([0.0, 5.0, 50.0, 500.0], N).astype(
            np.float32
        ),
        max_hourly_cost=rng.choice([0.0, 25.0, 250.0], N).astype(
            np.float32
        ),
        slo_valid=np.ones(N, bool),
        slo_target=rng.uniform(0.5, 10, (N, M)).astype(np.float32),
        demand_mu=rng.uniform(0, 1000, (N, M)).astype(np.float32),
        demand_sigma=rng.choice([0.0, 5.0, 50.0], (N, M)).astype(
            np.float32
        ),
        demand_valid=rng.rand(N, M) > 0.1,
    )


def _cost_record(args, backend, batched, per_row) -> dict:
    batched_p50 = float(np.percentile(batched, 50))
    loop_p50 = float(np.percentile(per_row, 50))
    return {
        "config": f"{args.cost_rows} autoscalers x {args.cost_metrics} "
                  "metrics cost refine",
        "backend": backend,
        "rows": args.cost_rows,
        "metrics": args.cost_metrics,
        "batched_p50_ms": round(batched_p50, 3),
        "per_ha_p50_ms": round(loop_p50, 3),
        "batched_rps": round(args.cost_rows * 1000.0 / batched_p50, 1),
        "per_ha_rps": round(args.cost_rows * 1000.0 / loop_p50, 1),
        "speedup": round(loop_p50 / batched_p50, 2),
    }


def _append_cost_row(path: str, record: dict) -> None:
    marker = "## Cost refine (make bench-cost)"
    header = (
        f"\n{marker}\n\n"
        "Batched multi-objective cost/SLO refinement (every SLO-opted "
        "autoscaler's candidate ladder scored in ONE device dispatch — "
        "the shape the CostEngine submits each tick) vs. the same rows "
        "refined one HA at a time. XLA == numpy bit-parity on every "
        "output field is asserted before timing.\n\n"
        "| Date | Backend | Config | Batched p50 (ms) | Per-HA p50 "
        "(ms) | Batched rows/s | Per-HA rows/s | Speedup |\n"
        "|---|---|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['config']} "
        f"| {record['batched_p50_ms']} | {record['per_ha_p50_ms']} "
        f"| {record['batched_rps']} | {record['per_ha_rps']} "
        f"| {record['speedup']}x |\n"
    )
    _append_table_row(path, marker, header, row)


def run_cost(args, metric: str, note: str) -> None:  # lint: allow-complexity — bench arm: parity pin + two timed dispatch shapes inline
    """Batched vs per-HA multi-objective refinement: the cost
    subsystem's one-dispatch claim (docs/cost.md). Both paths run the
    IDENTICAL jitted kernel on identical rows; only the dispatch shape
    differs — one [N, K, M] program vs N [1, K, M] programs (the second
    compiled once and reused, so the gap is pure dispatch/launch
    overhead, not recompiles). The numpy mirror is asserted
    bit-identical on every output field before any timing."""
    import dataclasses

    import jax

    from karpenter_tpu.ops.cost import CostOutputs, cost_jit, cost_numpy

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    inputs = build_cost_inputs(args.cost_rows, args.cost_metrics, args.seed)
    rows = [
        dataclasses.replace(
            inputs,
            **{
                f.name: np.asarray(getattr(inputs, f.name))[i: i + 1]
                for f in dataclasses.fields(inputs)
            },
        )
        for i in range(args.cost_rows)
    ]
    # parity pin FIRST (the bench's acceptance gate): device == mirror,
    # bit for bit, on the exact workload about to be timed
    device_out = cost_jit(inputs)
    jax.block_until_ready(device_out)
    host_out = cost_numpy(inputs)
    for f in dataclasses.fields(CostOutputs):
        a = np.asarray(getattr(device_out, f.name))
        b = np.asarray(getattr(host_out, f.name))
        if not np.array_equal(a, b):
            raise AssertionError(
                f"cost kernel parity violated on {f.name}: "
                f"device != numpy mirror"
            )
    jax.block_until_ready(cost_jit(rows[0]))  # warm the per-HA shape

    batched_times, per_row_times = [], []
    for _ in range(args.iters):
        t0 = time.perf_counter()
        jax.block_until_ready(cost_jit(inputs))
        batched_times.append((time.perf_counter() - t0) * 1e3)
    for _ in range(args.iters):
        t0 = time.perf_counter()
        for row in rows:
            jax.block_until_ready(cost_jit(row))
        per_row_times.append((time.perf_counter() - t0) * 1e3)

    record = _cost_record(
        args, jax.default_backend(), batched_times, per_row_times
    )
    record_evidence(
        batched_iter_ms=[round(t, 4) for t in batched_times],
        per_ha_iter_ms=[round(t, 4) for t in per_row_times],
        cost=record,
        transport_floor=measure_transport_floor(),
    )
    print(
        f"batched p50={record['batched_p50_ms']}ms "
        f"({record['batched_rps']} rows/s) | per-HA "
        f"p50={record['per_ha_p50_ms']}ms "
        f"({record['per_ha_rps']} rows/s) | "
        f"speedup={record['speedup']}x",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_to_baseline(
            f"{record['config']} ({record['backend']})", record
        )
    if args.append_benchmarks:
        _append_cost_row(args.append_benchmarks, record)
    extra = (
        f"{record['batched_rps']} vs {record['per_ha_rps']} rows/sec "
        f"batched vs per-HA ({record['speedup']}x); numpy parity pinned"
    )
    emit(
        f"{metric} ({jax.default_backend()})",
        record["batched_p50_ms"],
        note=f"{note}; {extra}" if note else extra,
        against_baseline=False,
    )


def build_poolgroup_inputs(groups: int, pools: int, metrics: int,
                           seed: int):
    """A fleet of pool groups with every member pool live and every
    coupling SLACK (ratios/budget invalid, tier penalties zero): the
    one configuration where the joint program is provably the per-pool
    cost ladder bit for bit, so the timed comparison is the same math
    in two dispatch shapes — the bench measures the dispatch collapse,
    not a different algorithm. The masked constraint operands still
    run inside the joint program, so its timing is honest for the
    enforcing case too."""
    from karpenter_tpu.ops.poolgroup import RATIO_SLOTS, PoolGroupInputs

    rng = np.random.RandomState(seed)
    G, P, M = groups, pools, metrics
    base = rng.randint(1, 200, (G, P)).astype(np.int32)
    ratio_a = rng.randint(0, P, (G, RATIO_SLOTS)).astype(np.int32)
    return PoolGroupInputs(
        base_desired=base,
        min_replicas=np.maximum(base - 50, 0).astype(np.int32),
        max_replicas=(base + rng.randint(50, 500, (G, P))).astype(
            np.int32
        ),
        unit_cost=rng.choice([0.07, 0.19, 1.0, 4.8], (G, P)).astype(
            np.float32
        ),
        slo_weight=rng.choice([0.0, 5.0, 50.0, 500.0], (G, P)).astype(
            np.float32
        ),
        max_hourly_cost=rng.choice([0.0, 25.0, 250.0], (G, P)).astype(
            np.float32
        ),
        tier_penalty=np.zeros((G, P), np.float32),
        pool_valid=np.ones((G, P), bool),
        slo_target=rng.uniform(0.5, 10, (G, P, M)).astype(np.float32),
        demand_mu=rng.uniform(0, 1000, (G, P, M)).astype(np.float32),
        demand_sigma=rng.choice([0.0, 5.0, 50.0], (G, P, M)).astype(
            np.float32
        ),
        demand_valid=rng.rand(G, P, M) > 0.1,
        ratio_a=ratio_a,
        ratio_b=((ratio_a + 1) % P).astype(np.int32),
        ratio_min_num=np.zeros((G, RATIO_SLOTS), np.int32),
        ratio_min_den=np.ones((G, RATIO_SLOTS), np.int32),
        ratio_max_num=np.zeros((G, RATIO_SLOTS), np.int32),
        ratio_max_den=np.zeros((G, RATIO_SLOTS), np.int32),
        ratio_valid=np.zeros((G, RATIO_SLOTS), bool),
        group_budget=np.zeros(G, np.float32),
        group_valid=np.zeros(G, bool),
    )


def _poolgroup_record(args, backend, joint, per_pool) -> dict:
    joint_p50 = float(np.percentile(joint, 50))
    loop_p50 = float(np.percentile(per_pool, 50))
    n = args.poolgroup_groups * args.poolgroup_pools
    return {
        "config": f"{args.poolgroup_groups} pool groups x "
                  f"{args.poolgroup_pools} pools x "
                  f"{args.poolgroup_metrics} metrics joint allocation",
        "backend": backend,
        "groups": args.poolgroup_groups,
        "pools": args.poolgroup_pools,
        "metrics": args.poolgroup_metrics,
        "joint_p50_ms": round(joint_p50, 3),
        "per_pool_p50_ms": round(loop_p50, 3),
        "joint_pools_ps": round(n * 1000.0 / joint_p50, 1),
        "per_pool_pools_ps": round(n * 1000.0 / loop_p50, 1),
        "speedup": round(loop_p50 / joint_p50, 2),
        "dispatches_joint": 1,
        "dispatches_per_pool": n,
        "parity": "bitwise",
    }


def _append_poolgroup_row(path: str, record: dict) -> None:
    marker = "## Pool-group joint allocation (make bench-poolgroup)"
    header = (
        f"\n{marker}\n\n"
        "One batched joint pool-group dispatch (every group's "
        "cross-product candidate ladder scored together, constraint "
        "operands masked in-program) vs. the groups*pools per-pool "
        "cost dispatches the joint plane replaces. Before timing, XLA "
        "== numpy bit-parity is asserted on every output leaf AND the "
        "joint selection under slack constraints is asserted "
        "bit-identical to the per-pool cost ladder — same math, two "
        "dispatch shapes.\n\n"
        "| Date | Backend | Config | Joint p50 (ms) | Per-pool p50 "
        "(ms) | Dispatches | Speedup |\n"
        "|---|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['config']} "
        f"| {record['joint_p50_ms']} | {record['per_pool_p50_ms']} "
        f"| 1 vs {record['dispatches_per_pool']} "
        f"| {record['speedup']}x |\n"
    )
    _append_table_row(path, marker, header, row)


def run_poolgroup(args, metric: str, note: str) -> None:  # lint: allow-complexity — bench arm: two parity pins + two timed dispatch shapes inline
    """One batched joint pool-group dispatch vs the per-pool cost
    dispatches it replaces (docs/poolgroups.md). The workload keeps
    every coupling slack so the joint program's selection is provably
    the per-pool cost ladder bit for bit (the wire-compat property
    tests/test_poolgroup.py pins); the timed gap is then pure dispatch
    shape — one [G, P, ...] program vs G*P [1, ...] programs, the
    second compiled once and reused."""
    import dataclasses

    import jax

    from karpenter_tpu.ops.cost import CostInputs, cost_jit
    from karpenter_tpu.ops.poolgroup import (
        PoolGroupOutputs,
        poolgroup_jit,
        poolgroup_numpy,
    )

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    inputs = build_poolgroup_inputs(
        args.poolgroup_groups, args.poolgroup_pools,
        args.poolgroup_metrics, args.seed,
    )
    flat = CostInputs(
        base_desired=inputs.base_desired.reshape(-1),
        min_replicas=inputs.min_replicas.reshape(-1),
        max_replicas=inputs.max_replicas.reshape(-1),
        unit_cost=inputs.unit_cost.reshape(-1),
        slo_weight=inputs.slo_weight.reshape(-1),
        max_hourly_cost=inputs.max_hourly_cost.reshape(-1),
        slo_valid=inputs.pool_valid.reshape(-1),
        slo_target=inputs.slo_target.reshape(
            -1, inputs.slo_target.shape[-1]
        ),
        demand_mu=inputs.demand_mu.reshape(
            -1, inputs.demand_mu.shape[-1]
        ),
        demand_sigma=inputs.demand_sigma.reshape(
            -1, inputs.demand_sigma.shape[-1]
        ),
        demand_valid=inputs.demand_valid.reshape(
            -1, inputs.demand_valid.shape[-1]
        ),
    )
    n = args.poolgroup_groups * args.poolgroup_pools
    rows = [
        dataclasses.replace(
            flat,
            **{
                f.name: np.asarray(getattr(flat, f.name))[i: i + 1]
                for f in dataclasses.fields(flat)
            },
        )
        for i in range(n)
    ]
    # parity pin 1 (the bench's acceptance gate): joint device == numpy
    # mirror, bit for bit, on every output leaf of the timed workload
    joint_out = poolgroup_jit(inputs)
    jax.block_until_ready(joint_out)
    host_out = poolgroup_numpy(inputs)
    for f in dataclasses.fields(PoolGroupOutputs):
        a = np.asarray(getattr(joint_out, f.name))
        b = np.asarray(getattr(host_out, f.name))
        if not np.array_equal(a, b):
            raise AssertionError(
                f"poolgroup kernel parity violated on {f.name}: "
                f"device != numpy mirror"
            )
    # parity pin 2 (the replaces claim): under slack couplings the joint
    # selection IS the per-pool cost ladder — same math, so the timed
    # comparison below measures dispatch shape and nothing else
    flat_out = cost_jit(flat)
    jax.block_until_ready(flat_out)
    if not np.array_equal(
        np.asarray(joint_out.desired).reshape(-1),
        np.asarray(flat_out.desired),
    ):
        raise AssertionError(
            "joint selection != per-pool cost ladder under slack "
            "couplings — the dispatch comparison would be dishonest"
        )
    jax.block_until_ready(cost_jit(rows[0]))  # warm the per-pool shape

    joint_times, per_pool_times = [], []
    for _ in range(args.iters):
        t0 = time.perf_counter()
        jax.block_until_ready(poolgroup_jit(inputs))
        joint_times.append((time.perf_counter() - t0) * 1e3)
    for _ in range(args.iters):
        t0 = time.perf_counter()
        for row in rows:
            jax.block_until_ready(cost_jit(row))
        per_pool_times.append((time.perf_counter() - t0) * 1e3)

    record = _poolgroup_record(
        args, jax.default_backend(), joint_times, per_pool_times
    )
    record_evidence(
        joint_iter_ms=[round(t, 4) for t in joint_times],
        per_pool_iter_ms=[round(t, 4) for t in per_pool_times],
        poolgroup=record,
        transport_floor=measure_transport_floor(),
    )
    print(
        f"joint p50={record['joint_p50_ms']}ms "
        f"({record['joint_pools_ps']} pools/s, 1 dispatch) | per-pool "
        f"p50={record['per_pool_p50_ms']}ms "
        f"({record['per_pool_pools_ps']} pools/s, "
        f"{record['dispatches_per_pool']} dispatches) | "
        f"speedup={record['speedup']}x",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_to_baseline(
            f"{record['config']} ({record['backend']})", record
        )
    if args.append_benchmarks:
        _append_poolgroup_row(args.append_benchmarks, record)
    extra = (
        f"1 vs {record['dispatches_per_pool']} dispatches "
        f"({record['speedup']}x); numpy + cost-ladder parity pinned"
    )
    emit(
        f"{metric} ({jax.default_backend()})",
        record["joint_p50_ms"],
        note=f"{note}; {extra}" if note else extra,
        against_baseline=False,
    )


def run_decide(args, metric: str, note: str) -> None:
    """The reference computes ONE scalar HPA decision per object per 10 s
    tick (pkg/autoscaler/autoscaler.go:81-113). Here the whole fleet's
    decisions — per-metric recommendation, select policy, stabilization
    window, Count/Percent rate-limit policies, min/max bounds — run as one
    device call (ops/decision.decide_jit)."""
    import jax

    from karpenter_tpu.ops.decision import decide_jit
    from karpenter_tpu.parallel.mesh import example_decision_inputs

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    inputs = jax.device_put(
        example_decision_inputs(N=args.decide, M=4, seed=args.seed)
    )
    jax.block_until_ready(inputs)
    t0 = time.perf_counter()
    jax.block_until_ready(decide_jit(inputs))
    print(
        f"first call (compile+run): {(time.perf_counter() - t0) * 1e3:.1f} ms",
        file=sys.stderr,
    )
    times = []
    for _ in range(args.iters):
        t0 = time.perf_counter()
        jax.block_until_ready(decide_jit(inputs))
        times.append((time.perf_counter() - t0) * 1e3)
    record_evidence(
        iter_ms=[round(t, 4) for t in times],
        transport_floor=measure_transport_floor(),
    )
    p50 = float(np.percentile(times, 50))
    dps = args.decide * 1000.0 / p50 if p50 else 0.0
    print(
        f"p50={p50:.2f}ms p95={float(np.percentile(times, 95)):.2f}ms "
        f"autoscaler decisions/sec={dps:.0f}",
        file=sys.stderr,
    )
    extra = f"{dps:.0f} autoscaler decisions/sec"
    emit(
        f"{metric} ({jax.default_backend()})",
        p50,
        note=f"{note}; {extra}" if note else extra,
    )


def run_mesh(args, metric: str) -> None:
    """Sharded solve over an N-device pods x groups mesh — the scale story
    the reference concedes ('breaks down as the cluster scales',
    docs/designs/DESIGN.md): rows (pods) and columns (instance types) are
    sharded with NamedShardings and GSPMD partitions the whole program.
    When N real devices are present and healthy they are used; otherwise
    N virtual CPU devices stand in (same code path the driver's dryrun
    compiles) and the number is scale EVIDENCE for the sharded program,
    not a TPU perf claim. Outputs are asserted element-for-element equal
    to the single-device solve before timing."""
    # shared probe (retry+backoff, hang-safe): fall back to a virtual
    # CPU mesh if the real backend is unusable or smaller than the mesh
    count, reason = probe_real_devices(
        args.probe_timeout, args.probe_retries
    )
    if count < args.mesh:
        from karpenter_tpu.utils.backend import force_virtual_cpu

        print(
            f"real backend has {count} device(s)"
            + (f" ({reason})" if reason else "")
            + f", need {args.mesh}: using virtual CPU mesh",
            file=sys.stderr,
        )
        force_virtual_cpu(args.mesh)

    import jax

    from karpenter_tpu.ops.binpack import binpack
    from karpenter_tpu.parallel.mesh import build_mesh, sharded_binpack

    if len(jax.devices()) < args.mesh:
        emit(
            metric,
            None,
            error=f"only {len(jax.devices())} devices available",
        )
        return
    mesh = build_mesh(n_devices=args.mesh, slices=args.slices)
    print(f"mesh: {dict(mesh.shape)} on {jax.default_backend()}", file=sys.stderr)
    inputs = build_inputs(
        args.pods, args.types, args.taints, args.labels, args.seed,
        affinity=args.affinity,
    )

    single = jax.device_get(binpack(inputs, buckets=args.buckets))
    sharded = jax.device_get(
        sharded_binpack(mesh, inputs, buckets=args.buckets)
    )
    np.testing.assert_array_equal(sharded.assigned, single.assigned)
    np.testing.assert_array_equal(sharded.nodes_needed, single.nodes_needed)
    np.testing.assert_array_equal(sharded.lp_bound, single.lp_bound)
    assert int(sharded.unschedulable) == int(single.unschedulable)
    print("sharded outputs == single-device outputs", file=sys.stderr)

    times = []
    for _ in range(args.iters):
        t0 = time.perf_counter()
        out = sharded_binpack(mesh, inputs, buckets=args.buckets)
        jax.block_until_ready(out.nodes_needed)
        times.append((time.perf_counter() - t0) * 1e3)
    record_evidence(
        iter_ms=[round(t, 4) for t in times],
        mesh_shape=dict(mesh.shape),
        transport_floor=measure_transport_floor(),
    )
    p50 = float(np.percentile(times, 50))
    print(f"sharded p50={p50:.2f}ms over {args.iters} iters", file=sys.stderr)
    emit(f"{metric} ({jax.default_backend()})", p50)



def _shard_parity(out, ref, label: str, lp_tol: int = 1) -> None:
    """Pin the sharded-output contract: integer outputs EXACT, lp_bound
    within the ±1 reduction-order tolerance the numpy-parity contract
    already carves out (ops/numpy_binpack.py docstring — sharding the
    pod axis reorders the same f32 demand accumulation)."""
    for name in ("assigned", "assigned_count", "nodes_needed"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out, name)),
            np.asarray(getattr(ref, name)),
            err_msg=f"{label}: {name}",
        )
    assert int(out.unschedulable) == int(ref.unschedulable), label
    drift = np.abs(
        np.asarray(out.lp_bound, np.int64)
        - np.asarray(ref.lp_bound, np.int64)
    )
    assert int(drift.max(initial=0)) <= lp_tol, (
        f"{label}: lp_bound drift {int(drift.max())} > {lp_tol}"
    )


def _publish_shard_baseline(record: dict) -> None:
    _publish_to_baseline(
        f"{record['config']} sharded fleet solve ({record['backend']})",
        record,
    )


def _append_shard_row(path: str, record: dict) -> None:
    marker = "## Sharded fleet solve (make bench-shard)"
    header = (
        f"\n{marker}\n\n"
        "One fleet-scale bin-pack through the `SolverService` seam, "
        "routed by the sharded dispatch strategy onto a pods×groups "
        "mesh ([solver-service.md](solver-service.md) \"Sharded "
        "dispatch\"), per mesh device count. Outputs are pinned against "
        "the single-device and numpy paths before timing (integer "
        "fields exact, lp_bound ±1). Honest-reading note: on the "
        "host-emulated CPU mesh all virtual devices share one socket's "
        "cores and DRAM bandwidth — the single-device baseline is "
        "already multi-threaded, so the curve here is compressed "
        "relative to real multi-chip hardware, where each shard owns "
        "its cores/HBM and the pods axis is embarrassingly parallel up "
        "to one cross-shard reduction per aggregate.\n\n"
        "| Date | Backend | Config | Mesh | p50 by device count (ms) | "
        "Speedup @ max | Upload p50 @ max (ms) | numpy mirror (ms) |\n"
        "|---|---|---|---|---|---|---|---|\n"
    )
    per = record["per_device_p50_ms"]
    p50s = " / ".join(f"{n}: {per[n]}" for n in sorted(per, key=int))
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['config']} "
        f"| {record['mesh']} | {p50s} "
        f"| {record['speedup_at_max']}x @ {record['max_devices']} "
        f"| {record['upload_p50_ms_at_max']} "
        f"| {record['numpy_mirror_ms']} |\n"
    )
    _append_table_row(path, marker, header, row)


def _ensure_shard_backend(args, need: int, metric: str) -> bool:
    """Probe the real backend; fall back to a virtual CPU mesh when it
    is absent or smaller than the largest scaling point (run_mesh's
    posture). False = not enough devices even virtually (emitted)."""
    count, reason = probe_real_devices(
        args.probe_timeout, args.probe_retries
    )
    if count < need:
        from karpenter_tpu.utils.backend import force_virtual_cpu

        print(
            f"real backend has {count} device(s)"
            + (f" ({reason})" if reason else "")
            + f", need {need}: using virtual CPU mesh",
            file=sys.stderr,
        )
        force_virtual_cpu(need)
    import jax

    if len(jax.devices()) < need:
        emit(
            metric, None,
            error=f"only {len(jax.devices())} devices available",
        )
        return False
    return True


def _measure_shard_config(args, inputs, ref_np, n: int, timeout_s: float):
    """(p50_ms, upload_p50_ms, iter_ms) for one mesh device count: a
    fresh SolverService capped at n devices, warm + parity-checked
    against the numpy mirror before timing. n=1 cannot build a mesh and
    is the single-device baseline through the same seam."""
    from karpenter_tpu.metrics.registry import GaugeRegistry
    from karpenter_tpu.solver import SolverService

    svc = SolverService(
        registry=GaugeRegistry(),
        backend=args.backend,
        shard_devices=n,
        default_timeout_s=timeout_s,
    )
    try:
        out = svc.solve(inputs, buckets=args.buckets)  # warm/compile
        if n > 1:
            assert svc.stats.shard_dispatches >= 1, (
                f"{n}-device run did not route through the sharded "
                f"dispatch strategy: {svc.stats}"
            )
        else:
            assert svc.stats.shard_dispatches == 0, svc.stats
        _shard_parity(out, ref_np, f"{n}-device vs numpy")
        times = []
        for _ in range(args.iters):
            t0 = time.perf_counter()
            svc.solve(inputs, buckets=args.buckets)
            times.append((time.perf_counter() - t0) * 1e3)
        assert svc.stats.fallbacks == 0, (
            f"device path degraded during the measurement: {svc.stats}"
        )
        upload = svc.stage_percentiles().get("upload", {})
        return (
            round(float(np.percentile(times, 50)), 1),
            upload.get("p50_ms", 0.0),
            [round(t, 2) for t in times],
        )
    finally:
        svc.close()


def run_shard(args, metric: str) -> None:
    """The sharded-dispatch acceptance measurement (ROADMAP item 1):
    ONE fleet decision at --pods x --types through the production
    SolverService seam, on meshes of increasing device count. The
    service routes the request itself (the cell count crosses
    DEFAULT_SHARD_THRESHOLD; a 1-device run cannot build a mesh and is
    the single-device baseline through the SAME seam). When real
    devices are absent the virtual CPU mesh stands in — scale EVIDENCE
    for the sharded program, not a TPU perf claim, exactly like
    --mesh."""
    need = max(args.shard_scaling)
    if not _ensure_shard_backend(args, need, metric):
        return
    import jax

    from karpenter_tpu.ops.numpy_binpack import binpack_numpy
    from karpenter_tpu.parallel.mesh import factorize

    backend = jax.default_backend()
    print(
        f"backend={backend} devices={len(jax.devices())}",
        file=sys.stderr,
    )
    inputs = build_inputs(
        args.pods, args.types, args.taints, args.labels, args.seed,
        affinity=args.affinity,
    )
    # generous per-solve deadline: a 10^9-cell solve on emulated
    # hardware runs tens of seconds, and a deadline expiry would
    # silently swap the numpy fallback into the timing
    timeout_s = 1800.0

    t0 = time.perf_counter()
    ref_np = binpack_numpy(inputs, buckets=args.buckets)
    numpy_ms = (time.perf_counter() - t0) * 1e3
    print(f"numpy mirror: {numpy_ms:.0f} ms", file=sys.stderr)

    per_p50, per_upload, per_iters = {}, {}, {}
    for n in args.shard_scaling:
        per_p50[n], per_upload[n], per_iters[n] = _measure_shard_config(
            args, inputs, ref_np, n, timeout_s
        )
        print(
            f"{n}-device p50 {per_p50[n]:.1f} ms "
            f"(upload p50 {per_upload[n]:.2f} ms)",
            file=sys.stderr,
        )

    base = per_p50.get(1, per_p50[min(per_p50)])
    cells = args.pods * args.types
    record = {
        "config": f"{args.pods} pods x {args.types} types",
        "backend": backend,
        "mesh": "x".join(str(e) for e in factorize(need)),
        "max_devices": need,
        "per_device_p50_ms": {str(n): per_p50[n] for n in per_p50},
        "per_device_upload_ms": {
            str(n): per_upload[n] for n in per_upload
        },
        "speedup_at_max": round(base / max(per_p50[need], 1e-9), 2),
        "upload_p50_ms_at_max": per_upload[need],
        "cells_per_sec_at_max": round(
            cells / max(per_p50[need] / 1e3, 1e-9)
        ),
        "numpy_mirror_ms": round(numpy_ms, 1),
        "parity": "int outputs exact vs single-device+numpy; lp ±1",
    }
    record_evidence(
        shard=record,
        per_device_iter_ms={str(n): per_iters[n] for n in per_iters},
        transport_floor=measure_transport_floor(),
    )
    print(
        f"sharded fleet solve: {record['per_device_p50_ms']} ms "
        f"(speedup {record['speedup_at_max']}x @ {need} devices; "
        f"numpy mirror {record['numpy_mirror_ms']} ms)",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_shard_baseline(record)
    if args.append_benchmarks:
        _append_shard_row(args.append_benchmarks, record)
    extra = (
        f"device-count p50s (ms) {record['per_device_p50_ms']}; "
        f"speedup {record['speedup_at_max']}x @ {need} devices on a "
        f"shared-host emulated mesh; numpy mirror "
        f"{record['numpy_mirror_ms']} ms; sharded == single-device == "
        f"numpy (int exact, lp ±1)"
    )
    emit(
        f"{metric} ({backend})",
        per_p50[need],
        note=extra,
        against_baseline=False,
    )


def _e2e_anti_affinity(app: str):
    """Required hostname self-anti-affinity for --e2e --anti: the
    StatefulSet one-replica-per-node pattern, through the REAL spec
    parse -> columnar anti-shape intern -> _expand_anti_rows ->
    pod_exclusive operand path."""
    from karpenter_tpu.api.core import (
        Affinity,
        LabelSelector,
        PodAffinityTerm,
        PodAntiAffinity,
    )

    return Affinity(
        pod_anti_affinity=PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=[
                PodAffinityTerm(
                    label_selector=LabelSelector(
                        match_labels={"app": app}
                    ),
                    topology_key="kubernetes.io/hostname",
                )
            ]
        )
    )


def _e2e_affinity_shapes():
    """A few realistic affinity variants for --e2e --affinity: require
    ssd, forbid hdd, prefer ssd (weight 80)."""
    from karpenter_tpu.api.core import (
        Affinity,
        NodeAffinity,
        NodeSelector,
        NodeSelectorRequirement,
        NodeSelectorTerm,
        PreferredSchedulingTerm,
    )

    def required(operator, values):
        return Affinity(
            node_affinity=NodeAffinity(
                required_during_scheduling_ignored_during_execution=(
                    NodeSelector(
                        node_selector_terms=[
                            NodeSelectorTerm(
                                match_expressions=[
                                    NodeSelectorRequirement(
                                        key="disk",
                                        operator=operator,
                                        values=values,
                                    )
                                ]
                            )
                        ]
                    )
                )
            )
        )

    prefer_ssd = Affinity(
        node_affinity=NodeAffinity(
            preferred_during_scheduling_ignored_during_execution=[
                PreferredSchedulingTerm(
                    weight=80,
                    preference=NodeSelectorTerm(
                        match_expressions=[
                            NodeSelectorRequirement(
                                key="disk", operator="In", values=["ssd"]
                            )
                        ]
                    ),
                )
            ]
        )
    )
    return [
        required("In", ["ssd"]),
        required("NotIn", ["hdd"]),
        prefer_ssd,
    ]


def run_e2e(args, metric: str, note: str = "") -> None:  # lint: allow-complexity — honest e2e: every stage of the tick measured inline
    """Full control-plane tick at scale: one solve_pending call — node
    listing, group profiling, columnar cache snapshot, encode, transfer,
    device bin-pack, status + gauge writes — exactly the path a
    MetricsProducer reconcile runs (BASELINE.json 'p50 reconcile
    latency'). Store population cost is excluded: pods arrive via watch
    events over the fleet's lifetime."""
    import jax

    from karpenter_tpu.api.core import (
        Container,
        Node,
        NodeCondition,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from karpenter_tpu.api.metricsproducer import (
        MetricsProducer,
        MetricsProducerSpec,
        PendingCapacitySpec,
    )
    import functools

    from karpenter_tpu.metrics.producers.pendingcapacity import (
        register_gauges,
        solve_pending,
    )
    from karpenter_tpu.ops.binpack import solve
    from karpenter_tpu.metrics.registry import GaugeRegistry
    from karpenter_tpu.metrics.producers.pendingcapacity import (
        group_profile,
    )
    from karpenter_tpu.store import Store
    from karpenter_tpu.store.columnar import PendingFeed
    from karpenter_tpu.utils.quantity import Quantity

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    rng = np.random.default_rng(args.seed)
    store = Store()
    feed = PendingFeed(store, group_profile)
    cpu_choices = [Quantity.parse(q) for q in ("100m", "250m", "500m", "1", "2", "4")]
    mem_choices = [Quantity.parse(q) for q in ("128Mi", "512Mi", "1Gi", "4Gi")]

    # --affinity F: fraction F of pods carry required OR preferred node
    # affinity over the disk label the nodes alternate — exercising the
    # host shape evaluation + the mask/score device operands in the tick
    affinity_shapes = _e2e_affinity_shapes() if args.affinity else []

    def make_pod(name):
        # independent draws: the metric label promises each fraction
        # unconditionally, and a pod can legitimately carry BOTH node
        # affinity and pod anti-affinity
        affinity = None
        labels = {}
        constraints = []
        if affinity_shapes and rng.random() < args.affinity:
            affinity = affinity_shapes[
                int(rng.integers(0, len(affinity_shapes)))
            ]
        if args.anti and rng.random() < args.anti:
            # a handful of one-per-node workloads (distinct selectors =
            # distinct anti shapes, like production StatefulSets)
            app = f"svc{int(rng.integers(0, 8))}"
            labels = {"app": app}
            from karpenter_tpu.api.core import Affinity

            anti = _e2e_anti_affinity(app)
            affinity = Affinity(
                node_affinity=(
                    affinity.node_affinity if affinity else None
                ),
                pod_anti_affinity=anti.pod_anti_affinity,
            )
        if args.spread and rng.random() < args.spread:
            # a handful of zone-spread Deployments (distinct selectors =
            # distinct spread shapes + distinct census queries)
            from karpenter_tpu.api.core import TopologySpreadConstraint

            app = f"web{int(rng.integers(0, 8))}"
            labels = {**labels, "spread-app": app}
            constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key="topology.kubernetes.io/zone",
                    when_unsatisfiable="DoNotSchedule",
                    label_selector={
                        "matchLabels": {"spread-app": app}
                    },
                )
            ]
        return Pod(
            metadata=ObjectMeta(name=name, labels=labels),
            spec=PodSpec(
                containers=[
                    Container(
                        requests={
                            "cpu": rng.choice(cpu_choices),
                            "memory": rng.choice(mem_choices),
                        }
                    )
                ],
                affinity=affinity,
                topology_spread_constraints=constraints,
            ),
        )

    for i in range(args.pods):
        store.create(make_pod(f"p{i}"))
    nodes = []
    for g in range(args.types):
        cores = int(rng.choice([8, 16, 32, 64, 96]))
        node_labels = {
            "group": f"g{g}",
            "disk": "ssd" if g % 2 else "hdd",
        }
        if args.spread:
            # 16 zones across the groups: domains for the split + the
            # occupancy census
            node_labels["topology.kubernetes.io/zone"] = f"z{g % 16}"
        node = Node(
            metadata=ObjectMeta(
                name=f"n{g}",
                labels=node_labels,
            ),
            status=NodeStatus(
                allocatable={
                    "cpu": Quantity.parse(str(cores)),
                    "memory": Quantity.parse(f"{cores * 4}Gi"),
                },
                conditions=[NodeCondition(type="Ready", status="True")],
            ),
        )
        store.create(node)
        nodes.append(node)
    # --spread: a slab of BOUND pods (10% of the fleet, capped) feeds the
    # existing-pod occupancy census; a slice of it churns every measured
    # tick so the census epoch invalidates and the recompute is IN the
    # number, not amortized away by the memo
    def make_bound(name):
        app = f"web{int(rng.integers(0, 8))}"
        return Pod(
            metadata=ObjectMeta(
                name=name,
                labels={
                    "spread-app": app,
                    # per-pod-unique label (the StatefulSet pod-name
                    # pattern): fragments the census into one label
                    # group per pod, so the measured tick exercises the
                    # materialized-view path, not a shared-group lookup
                    "statefulset.kubernetes.io/pod-name": name,
                },
            ),
            spec=PodSpec(
                node_name=f"n{int(rng.integers(0, args.types))}",
                containers=[
                    Container(requests={"cpu": cpu_choices[0]})
                ],
            ),
        )

    bound_count = 0
    if args.spread:
        bound_count = min(max(args.pods // 10, 1), 10000)
        for i in range(bound_count):
            store.create(make_bound(f"b{i}"))

    producers = [
        store.create(
            MetricsProducer(
                metadata=ObjectMeta(name=f"mp{g}"),
                spec=MetricsProducerSpec(
                    pending_capacity=PendingCapacitySpec(
                        node_selector={"group": f"g{g}"}
                    )
                ),
            )
        )
        for g in range(args.types)
    ]
    registry = GaugeRegistry()
    register_gauges(registry)

    solver = functools.partial(
        solve, buckets=args.buckets, backend=args.backend
    )
    if args.host_only:
        # shape-correct no-op: everything around the device call still
        # runs (encode memo invalidation, status + gauge writes), so the
        # number is the honest host half of the churned tick
        from karpenter_tpu.ops.binpack import BinPackOutputs

        def solver(inputs, **_):  # noqa: ARG001
            groups = inputs.group_allocatable.shape[0]
            return BinPackOutputs(
                assigned=np.full(
                    inputs.pod_requests.shape[0], -1, np.int32
                ),
                assigned_count=np.zeros(groups, np.int32),
                nodes_needed=np.zeros(groups, np.int32),
                lp_bound=np.zeros(groups, np.int32),
                unschedulable=np.int32(0),
            )

    def tick():
        # the REAL production path, nothing hoisted: node listing + group
        # profiling + cache snapshot + encode + device solve + status and
        # gauge writes for every producer
        solve_pending(store, producers, registry, feed=feed, solver=solver)

    t0 = time.perf_counter()
    tick()
    print(
        f"first tick (compile+run): {(time.perf_counter() - t0) * 1e3:.1f} ms",
        file=sys.stderr,
    )

    # steady state: nothing changed between ticks, so the encode memo +
    # device-residency cache collapse the tick to (dispatch + one packed
    # output fetch)
    steady = []
    for _ in range(args.iters):
        t0 = time.perf_counter()
        tick()
        steady.append((time.perf_counter() - t0) * 1e3)
    s50 = float(np.percentile(steady, 50))
    print(
        f"steady-state tick p50={s50:.1f}ms "
        f"p95={float(np.percentile(steady, 95)):.1f}ms",
        file=sys.stderr,
    )

    # churned: replace pods through the store each tick (watch events feed
    # the incremental caches), so every measured tick includes cache
    # maintenance, full re-encode, and full input re-transfer — the honest
    # production number, reported as THE metric. Pod OBJECT construction is
    # the load generator's cost (a kubelet/scheduler analog), so the
    # replacement pods are pre-built; the timed region starts where the
    # controller's work starts: the store mutation and its watch fan-out.
    churn = args.churn if args.churn >= 0 else max(1, args.pods // 100)
    next_id = args.pods
    next_bound = bound_count
    # honest labeling: --churn 0 must stay a genuinely churn-free tick;
    # the window can never exceed the slab (victims must exist)
    bound_churn = (
        min(bound_count, max(1, churn // 10))
        if (bound_count and churn)
        else 0
    )
    times = []
    for it in range(args.iters):
        fresh = [make_pod(f"p{next_id + j}") for j in range(churn)]
        victims = [f"p{next_id - args.pods + j}" for j in range(churn)]
        next_id += churn
        fresh_bound = [
            make_bound(f"b{next_bound + j}") for j in range(bound_churn)
        ]
        bound_victims = [
            f"b{next_bound - bound_count + j}" for j in range(bound_churn)
        ]
        next_bound += bound_churn
        t0 = time.perf_counter()
        for victim, pod in zip(victims, fresh):
            store.delete("Pod", "default", victim)
            store.create(pod)
        for victim, pod in zip(bound_victims, fresh_bound):
            store.delete("Pod", "default", victim)
            store.create(pod)
        tick()
        times.append((time.perf_counter() - t0) * 1e3)
    record_evidence(
        steady_iter_ms=[round(t, 4) for t in steady],
        iter_ms=[round(t, 4) for t in times],
        churn=churn,
        transport_floor=(
            measure_transport_floor() if not args.host_only else None
        ),
    )
    p50 = float(np.percentile(times, 50))
    p95 = float(np.percentile(times, 95))
    print(
        f"e2e tick (churn={churn} pods/tick) p50={p50:.1f}ms p95={p95:.1f}ms",
        file=sys.stderr,
    )
    extra = f"churn={churn}/tick; steady-state p50={s50:.1f}ms"
    if args.host_only:
        metric += ", host half only"
        extra += "; device solve stubbed"
    emit(
        f"{metric} ({jax.default_backend()})",
        p50,
        note=f"{note}; {extra}" if note else extra,
        against_baseline=not args.host_only,
    )


def build_multitenant_batch(args, seed: int):
    """N tenant clusters' decide matrices for one lockstep tick — the
    same seeded world `--simulate --multitenant` steps
    (simulate.multitenant_fleet_inputs), so the bench times exactly the
    matrices the simulator replays."""
    from karpenter_tpu.simulate import (
        multitenant_cost_inputs,
        multitenant_fleet_inputs,
    )

    decide_batch = {}
    cost_batch = {}
    for i in range(args.tenants):
        tid = f"t{i:04d}"
        inputs = multitenant_fleet_inputs(
            i, args.tenant_rows, args.tenant_metrics, seed,
            tick=3, spec_replicas=np.full(args.tenant_rows, 2, np.int32),
            now=1_000_000.0,
        )
        decide_batch[tid] = inputs
        cost_batch[tid] = multitenant_cost_inputs(
            inputs, np.full(args.tenant_rows, 5, np.int32)
        )
    return decide_batch, cost_batch


def _multitenant_record(args, backend, batched, sequential) -> dict:
    batched_p50 = float(np.percentile(batched, 50))
    sequential_p50 = float(np.percentile(sequential, 50))
    decisions = args.tenants * args.tenant_rows
    return {
        "config": f"{args.tenants} tenants x {args.tenant_rows} "
                  "autoscalers multitenant",
        "backend": backend,
        "tenants": args.tenants,
        "rows_per_tenant": args.tenant_rows,
        "metrics_per_row": args.tenant_metrics,
        "batched_p50_ms": round(batched_p50, 3),
        "sequential_p50_ms": round(sequential_p50, 3),
        "batched_dps": round(decisions * 1000.0 / batched_p50, 1),
        "sequential_dps": round(decisions * 1000.0 / sequential_p50, 1),
        "speedup": round(sequential_p50 / batched_p50, 2),
    }


def _append_multitenant_row(path: str, record: dict) -> None:
    marker = "## Multi-tenant control plane (make bench-multitenant)"
    header = (
        f"\n{marker}\n\n"
        "One lockstep tick over N simulated tenant clusters: every "
        "tenant's decide + cost matrices concatenated into shared "
        "dispatches by the MultiTenantScheduler "
        "(docs/multitenancy.md) vs the same matrices dispatched one "
        "tenant at a time through the same SolverService seam. "
        "Cross-tenant slices == independent dispatches (device and "
        "numpy paths) is asserted before timing. Decisions/sec counts "
        "autoscaler rows decided+refined per wall second.\n\n"
        "| Date | Backend | Config | Batched tick p50 (ms) | "
        "Sequential tick p50 (ms) | Batched decisions/s | Sequential "
        "decisions/s | Speedup |\n"
        "|---|---|---|---|---|---|---|---|\n"
    )
    date = datetime.date.today().isoformat()
    row = (
        f"| {date} | {record['backend']} | {record['config']} "
        f"| {record['batched_p50_ms']} | {record['sequential_p50_ms']} "
        f"| {record['batched_dps']} | {record['sequential_dps']} "
        f"| {record['speedup']}x |\n"
    )
    _append_table_row(path, marker, header, row)


def _pin_multitenant_parity(scheduler, service, decide_batch, cost_batch,  # lint: allow-complexity — parity gate: one loop per family x path
                            backend: str) -> None:
    """The acceptance gate (docs/multitenancy.md): a subsample of
    tenants' concatenated-slice outputs must be bit-identical to their
    own independent dispatches — decide + cost on the requested device
    backend AND cost on the numpy mirror path."""
    import dataclasses

    from karpenter_tpu.ops.cost import CostOutputs, cost_numpy
    from karpenter_tpu.ops.decision import DecisionOutputs
    from karpenter_tpu.tenancy import concat_cost_inputs, slice_cost_outputs

    sample = sorted(decide_batch)[:: max(1, len(decide_batch) // 16)]
    decided = scheduler.decide_all(decide_batch)
    costed = scheduler.cost_all(cost_batch, backend=backend)
    for tid in sample:
        indep_d = service.decide(decide_batch[tid])
        for f in dataclasses.fields(DecisionOutputs):
            a = np.asarray(getattr(decided[tid], f.name))
            b = np.asarray(getattr(indep_d, f.name))
            if not np.array_equal(a, b):
                raise AssertionError(
                    f"decide concat parity violated for {tid}.{f.name}"
                )
        indep_c = service.cost(cost_batch[tid], backend=backend)
        for f in dataclasses.fields(CostOutputs):
            a = np.asarray(getattr(costed[tid], f.name))
            b = np.asarray(getattr(indep_c, f.name))
            if not np.array_equal(a, b):
                raise AssertionError(
                    f"cost concat parity violated for {tid}.{f.name} "
                    f"({backend})"
                )
    # numpy-mirror path: the concatenated host program's slices must
    # equal per-tenant host calls bit for bit too
    sample_batch = {tid: cost_batch[tid] for tid in sample}
    order = sorted(sample_batch)
    stacked = concat_cost_inputs([sample_batch[t] for t in order])
    host = cost_numpy(stacked)
    offset = 0
    for tid in order:
        n = int(np.asarray(sample_batch[tid].base_desired).shape[0])
        mine = slice_cost_outputs(host, offset, offset + n)
        offset += n
        indep = cost_numpy(sample_batch[tid])
        for f in dataclasses.fields(CostOutputs):
            a = np.asarray(getattr(mine, f.name))
            b = np.asarray(getattr(indep, f.name))
            if not np.array_equal(a, b):
                raise AssertionError(
                    f"cost concat parity violated for {tid}.{f.name} "
                    f"(numpy)"
                )


def run_multitenant(args, metric: str, note: str) -> None:
    """Aggregate decisions/sec at N tenants: the multi-tenant control
    plane's one-dispatch claim (docs/multitenancy.md). Both paths run
    the IDENTICAL kernels on identical per-tenant matrices through the
    same SolverService seam; only the dispatch shape differs — shared
    cross-tenant concatenated programs vs one decide + one cost
    dispatch per tenant. Parity is pinned before timing."""
    import jax

    from karpenter_tpu.metrics.registry import GaugeRegistry
    from karpenter_tpu.solver import SolverService
    from karpenter_tpu.tenancy import (
        MultiTenantScheduler,
        TenantRegistry,
        TenantSpec,
    )

    print(
        f"backend={jax.default_backend()} devices={jax.devices()}",
        file=sys.stderr,
    )
    decide_batch, cost_batch = build_multitenant_batch(args, args.seed)
    service = SolverService(
        backend=args.backend, registry=GaugeRegistry()
    )
    registry = TenantRegistry(
        service=service, registry=GaugeRegistry(),
        specs=[
            TenantSpec(id=tid, weight=1.0 + (i % 3))
            for i, tid in enumerate(sorted(decide_batch))
        ],
    )
    scheduler = MultiTenantScheduler(
        registry, service,
        max_rows_per_round=args.tenants * args.tenant_rows,
    )
    try:
        # parity pin FIRST (also warms every compiled shape both paths
        # will time)
        _pin_multitenant_parity(
            scheduler, service, decide_batch, cost_batch, args.backend
        )
        print(
            "parity pinned: cross-tenant slices == independent "
            "dispatches (device + numpy)",
            file=sys.stderr,
        )

        batched_times, sequential_times = [], []
        for _ in range(args.iters):
            t0 = time.perf_counter()
            scheduler.decide_all(decide_batch)
            scheduler.cost_all(cost_batch, backend=args.backend)
            batched_times.append((time.perf_counter() - t0) * 1e3)
        for _ in range(args.iters):
            t0 = time.perf_counter()
            for tid in decide_batch:
                service.decide(decide_batch[tid])
                service.cost(cost_batch[tid], backend=args.backend)
            sequential_times.append((time.perf_counter() - t0) * 1e3)
    finally:
        service.close()

    record = _multitenant_record(
        args, jax.default_backend(), batched_times, sequential_times
    )
    record_evidence(
        batched_iter_ms=[round(t, 4) for t in batched_times],
        sequential_iter_ms=[round(t, 4) for t in sequential_times],
        multitenant=record,
        transport_floor=measure_transport_floor(),
    )
    print(
        f"batched tick p50={record['batched_p50_ms']}ms "
        f"({record['batched_dps']} decisions/s) | sequential "
        f"p50={record['sequential_p50_ms']}ms "
        f"({record['sequential_dps']} decisions/s) | "
        f"speedup={record['speedup']}x",
        file=sys.stderr,
    )
    if args.publish_baseline:
        _publish_to_baseline(
            f"{record['config']} ({record['backend']})", record
        )
    if args.append_benchmarks:
        _append_multitenant_row(args.append_benchmarks, record)
    extra = (
        f"{record['batched_dps']} vs {record['sequential_dps']} "
        f"decisions/sec batched vs sequential "
        f"({record['speedup']}x); concat==independent parity pinned "
        f"(device + numpy)"
    )
    emit(
        f"{metric} ({jax.default_backend()})",
        record["batched_p50_ms"],
        note=f"{note}; {extra}" if note else extra,
        against_baseline=False,
    )


if __name__ == "__main__":
    main()
