"""Instance-type cost model: per-group hourly cost as columnar data.

The reference (and every layer of this repo before the cost subsystem)
is cost-blind. This module is the pricing half of docs/cost.md: a small
built-in on-demand catalog keyed by the standard
`node.kubernetes.io/instance-type` label, a spot/preemptible tier
multiplier composing with the SAME capacity-tier labels the packing
kernels steer on (api/core.capacity_tier_of — PR 6's group_tier), and
two explicit override annotations for fleets whose pricing the catalog
cannot know:

  cost.karpenter.sh/hourly-cost     exact per-node $/hour (wins)
  cost.karpenter.sh/instance-type   catalog key when the label is absent
                                    (ScalableNodeGroups carry no node
                                    labels)

`group_costs` is the encoder face: one vectorized pass over the
pendingCapacity group profiles produces the fleet's per-group cost
column (f32[G]), which the simulate report prices scale-up signals with;
`unit_cost` is the decide face, pricing a HorizontalAutoscaler's scale
target for the multi-objective kernel (ops/cost.py).

Prices are illustrative defaults, not billing data — the contract is
RELATIVE cost (spot < on-demand, big nodes > small nodes) driving the
multi-objective trade; operators with real prices override per group.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

from karpenter_tpu.api.core import capacity_tier_of

HOURLY_COST_ANNOTATION = "cost.karpenter.sh/hourly-cost"
INSTANCE_TYPE_ANNOTATION = "cost.karpenter.sh/instance-type"
INSTANCE_TYPE_LABEL = "node.kubernetes.io/instance-type"

# Representative on-demand $/hour anchors — enough catalog to make the
# relative trade real across the provider families this repo models
# (AWS ASG/EKS, GKE/TPU pools); unlisted types price at default_hourly.
DEFAULT_CATALOG: Dict[str, float] = {
    # general-purpose x86
    "m5.large": 0.096, "m5.xlarge": 0.192, "m5.2xlarge": 0.384,
    "n2-standard-4": 0.194, "n2-standard-8": 0.389,
    "e2-standard-4": 0.134,
    # accelerator hosts (per-host, pod-slice pools scale by topology)
    "ct5lp-hightpu-4t": 4.80,  # v5e-4 host
    "ct5lp-hightpu-8t": 9.60,  # v5e-8 host
    "p3.2xlarge": 3.06,
    "g5.xlarge": 1.006,
}


@dataclass
class CostModel:
    """Pricing policy (module docstring). One per runtime (or per
    tenant — tenancy/registry.py); the simulate replays mutate
    `spot_multiplier` mid-run to model a spot-price step."""

    catalog: Dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_CATALOG)
    )
    # price for a node whose type the catalog doesn't know — nonzero so
    # cost stays a live objective on label-less test/dev fleets
    default_hourly: float = 1.0
    # spot/preemptible tier price as a fraction of on-demand (the
    # historical ~65% discount); composes with capacity_tier_of
    spot_multiplier: float = 0.35
    # pluggable feed (cost/pricing.py, --pricing-file): consulted
    # BEFORE the built-in catalog, and its spotMultiplier (when the
    # feed carries one) outranks the knob above. None = catalog only.
    pricing: Optional[object] = None

    def on_demand(self, instance_type: Optional[str]) -> float:
        if instance_type:
            if self.pricing is not None:
                price = self.pricing.price(instance_type)
                if price is not None:
                    return float(price)
            price = self.catalog.get(instance_type)
            if price is not None:
                return float(price)
        return float(self.default_hourly)

    def effective_spot_multiplier(self) -> float:
        """The spot tier in force: the pricing feed's override when it
        carries one, else the configured knob."""
        if self.pricing is not None:
            override = self.pricing.spot_multiplier()
            if override is not None:
                return float(override)
        return float(self.spot_multiplier)

    def node_cost(self, labels) -> float:
        """Hourly cost of one node from its label set (the group-profile
        face): catalog price by instance-type label, spot tier applied
        by the same capacity-tier labels the packing kernels read."""
        get = labels.get if isinstance(labels, dict) else dict(labels).get
        price = self.on_demand(get(INSTANCE_TYPE_LABEL))
        if capacity_tier_of(labels) > 0:
            price *= self.effective_spot_multiplier()
        return price

    def group_costs(self, profiles) -> np.ndarray:
        """Columnar per-group hourly node cost, f32[G], aligned with the
        encoder's group axis (profiles are the (allocatable, labels,
        taints) triples every solve path already carries)."""
        return np.asarray(
            [self.node_cost(labels) for _alloc, labels, _t in profiles],
            np.float32,
        )

    def unit_cost(self, resource) -> float:
        """Hourly cost per replica of a scale target (the decide face).
        Annotation override wins; then the catalog via the
        instance-type annotation; spot tier from spec.preemptible OR
        spot-labeled metadata (ScalableNodeGroup carries the tier as
        spec, nodes as labels — both price the same)."""
        if resource is None:
            return float(self.default_hourly)
        meta = getattr(resource, "metadata", None)
        annotations = dict(getattr(meta, "annotations", None) or {})
        override = annotations.get(HOURLY_COST_ANNOTATION)
        if override is not None:
            try:
                return max(0.0, float(override))
            except ValueError:
                pass  # unparseable override: fall through to the catalog
        price = self.on_demand(annotations.get(INSTANCE_TYPE_ANNOTATION))
        spec = getattr(resource, "spec", None)
        preemptible = bool(getattr(spec, "preemptible", False))
        labels = dict(getattr(meta, "labels", None) or {})
        if preemptible or capacity_tier_of(labels) > 0:
            price *= self.effective_spot_multiplier()
        return price
