"""WarmPoolEngine: forecast-risk-sized pre-provisioned headroom.

The BLITZSCALE observation (PAPERS.md) is that end-to-end provisioning
lead time — not solve latency — dominates how fast capacity actually
arrives, and the way to attack it is capacity that already exists when
demand lands. A ScalableNodeGroup opting in via spec.warmPool keeps

    warm = clip(risk_headroom, minWarm, maxWarm)

spare nodes on top of its desired replicas, where risk_headroom is the
cost subsystem's one-sigma demand surplus for the HAs targeting the
group (CostEngine.headroom — the forecast distribution expressed in
replicas; 0 with no signal, so minWarm is the standalone floor).

The warm target rides the ScalableNodeGroup controller's ORDINARY
actuation door: the controller asks `warm_for(resource)` during its
reconcile and actuates spec.replicas + warm through the same fenced,
journaled, breaker-guarded provider write everything else uses — warm
capacity is never a side-channel resize. Sizing failures degrade to
minWarm (never-block: a broken risk signal must not stall actuation).

Metrics: karpenter_warmpool_{replicas,risk_replicas} gauges per group.
"""

from __future__ import annotations

from typing import Callable, Optional

from karpenter_tpu.utils.log import logger

SUBSYSTEM = "warmpool"


class WarmPoolEngine:
    """`headroom_source` is (namespace, group_name) -> int replicas of
    forecast-risk headroom (CostEngine.headroom in production)."""

    def __init__(
        self,
        headroom_source: Optional[Callable[[str, str], int]] = None,
        registry=None,
    ):
        self.headroom_source = headroom_source
        self._g_warm = self._g_risk = None
        if registry is not None:
            self._g_warm = registry.register(SUBSYSTEM, "replicas")
            self._g_risk = registry.register(SUBSYSTEM, "risk_replicas")

    def warm_for(self, resource) -> int:
        """Warm replicas to hold for this group right now: 0 without
        spec.warmPool (byte-identical controller behavior), else the
        risk-sized clip. Never raises."""
        spec = getattr(resource.spec, "warm_pool", None)
        if spec is None or spec.max_warm <= 0:
            return 0
        ns = resource.metadata.namespace
        name = resource.metadata.name
        risk = 0
        if self.headroom_source is not None:
            try:
                risk = max(0, int(self.headroom_source(ns, name)))
            except Exception as error:  # noqa: BLE001 — never-block sizing
                logger().warning(
                    "warm-pool risk signal failed for %s/%s (%s: %s); "
                    "holding minWarm", ns, name,
                    type(error).__name__, error,
                )
                risk = 0
        warm = min(max(risk, spec.min_warm), spec.max_warm)
        if self._g_warm is not None:
            self._g_warm.set(name, ns, float(warm))
            self._g_risk.set(name, ns, float(risk))
        return warm

    def on_deleted(self, resource) -> None:
        """Drop a deleted group's gauge series."""
        if self._g_warm is not None:
            self._g_warm.remove(
                resource.metadata.name, resource.metadata.namespace
            )
            self._g_risk.remove(
                resource.metadata.name, resource.metadata.namespace
            )
