"""Pluggable pricing feeds for the cost model.

PR 10 shipped the cost subsystem with an ILLUSTRATIVE built-in catalog
(cost/model.py DEFAULT_CATALOG) and named "real pricing feeds" as the
follow-up. This module is that seam: a `PricingSource` answers
"on-demand $/hour for this instance type" (and optionally overrides the
spot multiplier), and the CostModel consults its source before falling
back to the built-in catalog and the default price.

Two sources ship:

  * StaticPricingSource — a plain dict (the built-in catalog wrapped;
    also the test seam).
  * FilePricingSource — a JSON/YAML file, RELOADED ON MTIME CHANGE:
    operators point --pricing-file at a file a cron/sidecar refreshes
    from their billing export, and price changes land on the next tick
    with no restart. A broken or vanished file NEVER takes pricing
    down: the last good catalog keeps serving (never-block, the same
    posture every cost-path failure takes — docs/cost.md).

File format — either a bare {instance-type: $/hour} mapping or:

    {
      "catalog": {"m5.large": 0.096, "ct5lp-hightpu-4t": 4.8},
      "spotMultiplier": 0.31          # optional tier override
    }

Per-tenant feeds come through the tenant registry
(tenancy/registry.py): each TenantSpec.pricing_file builds its own
FilePricingSource, so a thousand tenants can price against a thousand
different negotiated rate cards while sharing one process.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

from karpenter_tpu.utils.log import logger


class PricingSource:
    """The feed interface: price() returns on-demand $/hour for an
    instance type, or None when this source doesn't know it (the model
    then falls back to its built-in catalog and default price);
    spot_multiplier() returns the tier override or None."""

    def price(self, instance_type: str) -> Optional[float]:
        raise NotImplementedError

    def spot_multiplier(self) -> Optional[float]:
        return None


class StaticPricingSource(PricingSource):
    def __init__(
        self,
        catalog: Dict[str, float],
        spot_multiplier: Optional[float] = None,
    ):
        self._catalog = dict(catalog)
        self._spot = spot_multiplier

    def price(self, instance_type: str) -> Optional[float]:
        value = self._catalog.get(instance_type)
        return None if value is None else float(value)

    def spot_multiplier(self) -> Optional[float]:
        return self._spot


_RECHECK_INTERVAL_S = 1.0  # mtime-poll throttle (see FilePricingSource)


class FilePricingSource(PricingSource):
    """Mtime-reloading file feed (module docstring). The mtime check is
    THROTTLED to once per _RECHECK_INTERVAL_S: pricing a whole fleet
    calls price()/spot_multiplier() per node, and a stat syscall per
    node would put filesystem latency on the reconcile hot path for a
    file that changes at cron cadence. Staleness stays bounded by one
    second — well under a tick."""

    def __init__(self, path: str):
        import time as _time

        self.path = path
        self._clock = _time.monotonic
        self._next_check = 0.0
        self._lock = threading.Lock()
        self._mtime: Optional[float] = None
        self._catalog: Dict[str, float] = {}
        self._spot: Optional[float] = None
        self._refresh()

    def _refresh(self) -> None:
        now = self._clock()
        if now < self._next_check:
            return
        self._next_check = now + _RECHECK_INTERVAL_S
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError as error:
            if self._mtime is not None:
                return  # keep serving the last good catalog
            raise ValueError(
                f"--pricing-file {self.path}: {error}"
            ) from error
        with self._lock:
            if self._mtime is not None and mtime == self._mtime:
                return
            try:
                catalog, spot = _load_pricing_file(self.path)
            except Exception as error:  # noqa: BLE001 — never-block feed
                if self._mtime is None:
                    raise  # a first load must fail loudly, not price $0
                logger().warning(
                    "pricing file %s reload failed (%s: %s); keeping the "
                    "last good catalog",
                    self.path, type(error).__name__, error,
                )
                self._mtime = mtime  # don't re-parse a bad file per tick
                return
            self._catalog = catalog
            self._spot = spot
            self._mtime = mtime

    def price(self, instance_type: str) -> Optional[float]:
        self._refresh()
        with self._lock:
            value = self._catalog.get(instance_type)
        return None if value is None else float(value)

    def spot_multiplier(self) -> Optional[float]:
        self._refresh()
        with self._lock:
            return self._spot


def _load_pricing_file(path: str):
    """(catalog, spot_multiplier | None) from a JSON/YAML pricing file."""
    from karpenter_tpu.utils.configfile import load_json_or_yaml

    doc = load_json_or_yaml(path)
    if not isinstance(doc, dict):
        raise ValueError(
            f"pricing file {path}: expected a mapping, got "
            f"{type(doc).__name__}"
        )
    spot = doc.get("spotMultiplier")
    raw = doc.get("catalog", doc)
    if not isinstance(raw, dict):
        raise ValueError(f"pricing file {path}: 'catalog' must be a mapping")
    catalog: Dict[str, float] = {}
    for key, value in raw.items():
        if key == "spotMultiplier":
            continue
        price = float(value)
        if price < 0:
            raise ValueError(
                f"pricing file {path}: negative price for {key!r}"
            )
        catalog[str(key)] = price
    if spot is not None:
        spot = float(spot)
        if not 0 < spot <= 1:
            raise ValueError(
                f"pricing file {path}: spotMultiplier must be in (0, 1], "
                f"got {spot}"
            )
    return catalog, spot


def pricing_source_for(path: Optional[str]) -> Optional[PricingSource]:
    """The Options/--pricing-file seam: a FilePricingSource when a path
    is configured, else None (the model's built-in catalog serves)."""
    return FilePricingSource(path) if path else None
