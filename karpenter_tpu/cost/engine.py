"""CostEngine: host-side orchestration of the multi-objective refine.

Rides the BatchAutoscaler's per-tick pass (docs/cost.md): after the
reactive/forecast-blended fleet decide lands, `adjust()` builds ONE
CostInputs matrix for every SLO-opted HorizontalAutoscaler — unit cost
from the scale target through the CostModel, per-metric demand
distribution from the FleetForecaster (observed value with sigma 0 when
no forecast), SLO targets from spec.behavior.slo — and submits it as a
single batched dispatch through the `cost_fn` seam (SolverService.cost
in production: backend-health FSM, `cost.score` fault point, numpy
mirror as the requested-CPU backend).

Contracts:

  * NEVER-BLOCK — adjust() never raises. Any failure (a cost-kernel
    fault past the service, a poisoned spec, a missing scale target)
    logs, counts karpenter_cost_blind_total, and returns the base
    outputs untouched: the tick proceeds COST-BLIND, exactly as if the
    subsystem didn't exist. Unlike the forecast path there is no host
    re-score on failure — the refinement is advisory, and the safe
    degradation is the unrefined decision, not host CPU spent
    re-scoring every tick through an outage.
  * ZERO-OVERHEAD OPT-OUT — a fleet with no spec.behavior.slo returns
    the SAME outputs object with no arrays built and no dispatch.
  * WARM-POOL SIGNAL — each pass refreshes its rows' per-HA headroom
    contributions (the kernel's one-sigma demand surplus; headroom()
    maxes them per scale target), which WarmPoolEngine sizes
    spec.warmPool from. A row that drops its SLO spec loses its
    contribution on the next pass, and prune() retires a DELETED HA's
    immediately, so a group's warm pool decays to minWarm instead of
    pinning stale risk forever.
  * BEHAVIOR-BOUNDED — the candidate ladder is clamped to the decide
    kernel's per-tick movement bounds (DecisionOutputs
    up_ceiling/down_floor), so the refinement converges over ticks at
    the rate the operator's scaleUp/scaleDown rules allow instead of
    outrunning them.

Metrics: karpenter_cost_{expected_hourly,violation_risk} gauges per HA
and karpenter_cost_{adjusted,blind}_total counters.
"""

from __future__ import annotations

import math
from dataclasses import replace
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.cost.model import CostModel
from karpenter_tpu.ops import cost as CK
from karpenter_tpu.ops import decision as D
from karpenter_tpu.utils.log import logger

SUBSYSTEM = "cost"


class CostEngine:
    """One per runtime (see module docstring).

    `cost_fn` is the device seam: any (CostInputs) -> CostOutputs
    callable — SolverService.cost in production (runtime.py wiring),
    the jitted kernel directly when standalone."""

    def __init__(
        self,
        store=None,
        cost_fn=None,
        model: Optional[CostModel] = None,
        forecaster=None,
        registry=None,
    ):
        self.store = store
        self.cost_fn = cost_fn if cost_fn is not None else CK.cost_jit
        self.model = model if model is not None else CostModel()
        self.forecaster = forecaster
        # (ns, ha-name) -> ((ns, scale-target name), one-sigma headroom
        # replicas): per-HA contributions, so a batch pass refreshes its
        # OWN rows' entries, a row that drops its SLO spec loses its
        # entry, and prune() retires a DELETED HA's entry even though no
        # pass will ever see it again (stale-target decay — module
        # docstring)
        self._contrib: Dict[
            Tuple[str, str], Tuple[Tuple[str, str], int]
        ] = {}
        self._g_hourly = self._g_risk = None
        self._c_adjusted = self._c_blind = None
        if registry is not None:
            self._g_hourly = registry.register(SUBSYSTEM, "expected_hourly")
            self._g_risk = registry.register(SUBSYSTEM, "violation_risk")
            self._c_adjusted = registry.register(
                SUBSYSTEM, "adjusted_total", kind="counter"
            )
            self._c_blind = registry.register(
                SUBSYSTEM, "blind_total", kind="counter"
            )

    # -- warm-pool face ----------------------------------------------------

    def headroom(self, namespace: str, name: str) -> int:
        """One-sigma demand replicas beyond the chosen desired, maxed
        over the HAs targeting this group — WarmPoolEngine's risk
        input."""
        key = (namespace, name)
        return max(
            (h for group, h in self._contrib.values() if group == key),
            default=0,
        )

    def prune(self, namespace: str, name: str) -> None:
        """Forget a deleted HorizontalAutoscaler (HA controller
        on_deleted hook): gauges AND its headroom contribution — a
        deleted HA never appears in another pass, so without this its
        group would hold risk-sized warm capacity forever."""
        self._contrib.pop((namespace, name), None)
        if self._g_hourly is not None:
            self._g_hourly.remove(name, namespace)
            self._g_risk.remove(name, namespace)

    def _retire(self, namespace: str, name: str) -> None:
        """A row stopped opting in (dropped spec.behavior.slo): drop its
        headroom contribution AND its gauge series — a frozen
        pre-opt-out karpenter_cost_* value would mislead dashboards
        exactly like stale headroom would mis-size warm pools."""
        if self._contrib.pop((namespace, name), None) is None:
            return
        if self._g_hourly is not None:
            self._g_hourly.remove(name, namespace)
            self._g_risk.remove(name, namespace)

    # -- the per-tick pass -------------------------------------------------

    def adjust(self, rows: List, outputs: D.DecisionOutputs, exclude=None):
        """The BatchAutoscaler's post-decide call: refine the fleet's
        desired counts in one batched dispatch. Returns `outputs`
        unchanged (the SAME object) when no row opts in; never raises
        (module docstring never-block contract). `exclude` drops row
        indices whose counts another refiner owns this tick (PoolGroup
        members — docs/poolgroups.md): they skip the independent ladder
        entirely, and _apply's retire diff drops their cost series the
        moment they join a group."""
        slo_rows = [
            i for i, row in enumerate(rows)
            if getattr(row.ha.spec.behavior, "slo", None) is not None
            and not getattr(row, "custom", False)
            and (exclude is None or i not in exclude)
        ]
        if not slo_rows:
            for row in rows:
                self._retire(*_ha_key(row.ha))
            return outputs
        try:
            inputs = self._build_inputs(rows, slo_rows, outputs)
            out = self.cost_fn(inputs)
            return self._apply(rows, slo_rows, outputs, out)
        except Exception as error:  # noqa: BLE001 — never-block contract
            logger().warning(
                "cost refinement failed (%s: %s); this tick scales "
                "cost-blind", type(error).__name__, error,
            )
            self._annotate_blind(slo_rows)
            for i in slo_rows:
                ns, name = _ha_key(rows[i].ha)
                if self._c_blind is not None:
                    self._c_blind.inc(name, ns)
            return outputs

    def fused_operands(self, rows: List, n: int, m: int, exclude=None):
        """Host half of the fused tick's cost stage (ops/fusedtick.py):
        the _build_inputs surface SPLIT at the demand seam. Spec bounds
        (ha_min/ha_max), pricing, and SLO targets assemble as before,
        but the movement-bound clamp and the _demand() selection move
        IN-DEVICE: the kernel clamps against the decide stage's fresh
        up_ceiling/down_floor and overlays the in-device distribution
        refresh over the PRIOR distribution read here — reproducing the
        chained path's post-refresh read bit for bit. distribution() is
        consulted under exactly _demand()'s gates (per-replica capacity
        declared AND observed finite), so its expiry side effects match
        the chained tick too. Returns (slo_rows, operands dict), or
        None when no row opts in (adjust()'s retire semantics apply) or
        the assembly fails (the cost-blind posture, already stamped).
        `exclude` mirrors adjust()'s: rows a PoolGroup owns this tick
        skip the independent ladder."""
        slo_rows = [
            i for i, row in enumerate(rows)
            if getattr(row.ha.spec.behavior, "slo", None) is not None
            and not getattr(row, "custom", False)
            and (exclude is None or i not in exclude)
        ]
        if not slo_rows:
            for row in rows:
                self._retire(*_ha_key(row.ha))
            return None
        try:
            return slo_rows, self._fused_operand_arrays(rows, slo_rows, n, m)
        except Exception as error:  # noqa: BLE001 — never-block contract
            logger().warning(
                "cost operand assembly failed (%s: %s); this tick "
                "scales cost-blind", type(error).__name__, error,
            )
            self._annotate_blind(slo_rows)
            for i in slo_rows:
                ns, name = _ha_key(rows[i].ha)
                if self._c_blind is not None:
                    self._c_blind.inc(name, ns)
            return None

    def _fused_operand_arrays(
        self, rows: List, slo_rows: List[int], n: int, m: int
    ) -> dict:
        ha_min = np.zeros(n, np.int32)
        ha_max = np.zeros(n, np.int32)
        unit_cost = np.zeros(n, np.float32)
        slo_weight = np.zeros(n, np.float32)
        max_hourly = np.zeros(n, np.float32)
        slo_valid = np.zeros(n, bool)
        slo_target = np.ones((n, m), np.float32)
        observed_arr = np.zeros((n, m), np.float32)
        demand_base_valid = np.zeros((n, m), bool)
        prior_point = np.zeros((n, m), np.float32)
        prior_sigma2 = np.zeros((n, m), np.float32)
        prior_valid = np.zeros((n, m), bool)
        for i in slo_rows:
            row = rows[i]
            slo = row.ha.spec.behavior.slo
            ns, name = _ha_key(row.ha)
            ha_min[i] = row.ha.spec.min_replicas
            ha_max[i] = row.ha.spec.max_replicas
            unit_cost[i] = self._unit_cost(row.ha)
            slo_weight[i] = slo.violation_cost_weight
            max_hourly[i] = slo.max_hourly_cost
            slo_valid[i] = True
            for j, (_spec, target, observed) in enumerate(row.observed):
                per_replica = slo.target_for(j)
                if not per_replica:
                    per_replica = target.target_value()
                if not per_replica or per_replica <= 0:
                    continue  # no capacity notion: metric carries no risk
                slo_target[i, j] = per_replica
                observed_arr[i, j] = observed
                if not math.isfinite(observed):
                    continue  # _demand()'s early return: no dist read
                demand_base_valid[i, j] = True
                if self.forecaster is None:
                    continue
                dist = self.forecaster.distribution(ns, name, j)
                if dist is not None:
                    prior_point[i, j] = dist[0]
                    prior_sigma2[i, j] = dist[1]
                    prior_valid[i, j] = True
        return {
            "ha_min": ha_min,
            "ha_max": ha_max,
            "unit_cost": unit_cost,
            "slo_weight": slo_weight,
            "max_hourly_cost": max_hourly,
            "slo_valid": slo_valid,
            "slo_target": slo_target,
            "observed": observed_arr,
            "demand_base_valid": demand_base_valid,
            "prior_point": prior_point,
            "prior_sigma2": prior_sigma2,
            "prior_valid": prior_valid,
        }

    def fused_commit(
        self, rows: List, slo_rows: List[int],
        outputs: D.DecisionOutputs, out: CK.CostOutputs,
    ) -> D.DecisionOutputs:
        """Bookkeeping for a fused tick's cost stage: exactly adjust()'s
        post-dispatch half — ledger provenance, gauge/contribution
        refresh, the desired overlay — given the CostOutputs the fused
        program returned. Same never-block posture as adjust()."""
        try:
            return self._apply(rows, slo_rows, outputs, out)
        except Exception as error:  # noqa: BLE001 — never-block contract
            logger().warning(
                "cost refinement failed (%s: %s); this tick scales "
                "cost-blind", type(error).__name__, error,
            )
            self._annotate_blind(slo_rows)
            for i in slo_rows:
                ns, name = _ha_key(rows[i].ha)
                if self._c_blind is not None:
                    self._c_blind.inc(name, ns)
            return outputs

    @staticmethod
    def _annotate_blind(slo_rows: List[int]) -> None:
        """Provenance: a cost-blind tick is itself an answer to 'why is
        the count what it is' — stamp the opted-in rows so the ledger
        record names the degradation instead of looking unrefined."""
        from karpenter_tpu.observability import default_ledger

        batch = default_ledger().current()  # None when disabled
        if batch is not None:
            rows = [i for i in slo_rows if i < batch.n]
            if rows:
                batch.annotate_rows(rows, slo_opted=True, cost_blind=True)

    def _unit_cost(self, ha) -> float:
        """Hourly cost per replica of this HA's scale target: the
        target resource (a ScalableNodeGroup's annotations/tier) priced
        through the CostModel; targets the store can't resolve price at
        the model default."""
        target = None
        ref = ha.spec.scale_target_ref
        if self.store is not None and ref.kind and ref.name:
            try:
                target = self.store.try_get(
                    ref.kind, ha.metadata.namespace, ref.name
                )
            except Exception:  # noqa: BLE001 — unknown kinds price default
                target = None
        return self.model.unit_cost(target)

    def _demand(self, row, j: int, observed: float):
        """(mu, sigma, valid) for one metric: the forecast distribution
        when the forecaster has one (demand can only be raised by the
        forecast — max(observed, point), the same monotone-up posture
        the blend takes), else the observed value with sigma 0."""
        if not math.isfinite(observed):
            return 0.0, 0.0, False
        mu, sigma = observed, 0.0
        if self.forecaster is not None:
            ns, name = _ha_key(row.ha)
            dist = self.forecaster.distribution(ns, name, j)
            if dist is not None:
                point, sigma2 = dist
                if math.isfinite(point):
                    mu = max(observed, point)
                if math.isfinite(sigma2) and sigma2 > 0:
                    sigma = math.sqrt(sigma2)
        return mu, sigma, True

    def _build_inputs(
        self, rows: List, slo_rows: List[int], outputs: D.DecisionOutputs
    ) -> CK.CostInputs:
        """One padded CostInputs matrix aligned row for row with the
        decide outputs (same pad_to bucket), slo_valid only on the
        opted-in rows so everything else passes through bit-identically."""
        base = np.asarray(outputs.desired, np.int32)
        n = base.shape[0]  # the decide pass's padded bucket
        m = max(1, max(len(r.values) for r in rows))
        min_replicas = np.zeros(n, np.int32)
        max_replicas = np.zeros(n, np.int32)
        unit_cost = np.zeros(n, np.float32)
        slo_weight = np.zeros(n, np.float32)
        max_hourly = np.zeros(n, np.float32)
        slo_valid = np.zeros(n, bool)
        slo_target = np.ones((n, m), np.float32)
        demand_mu = np.zeros((n, m), np.float32)
        demand_sigma = np.zeros((n, m), np.float32)
        demand_valid = np.zeros((n, m), bool)
        up_ceiling = np.asarray(outputs.up_ceiling, np.int32)
        down_floor = np.asarray(outputs.down_floor, np.int32)
        for i in slo_rows:
            row = rows[i]
            slo = row.ha.spec.behavior.slo
            ha_min = row.ha.spec.min_replicas
            ha_max = row.ha.spec.max_replicas
            # the candidate ladder honors the SAME per-tick movement
            # bounds the decide kernel enforced — stabilization windows
            # and scaleUp/scaleDown rate policies (DecisionOutputs
            # up_ceiling/down_floor) — so an SLO raise or budget trim
            # cannot outrun the operator's declared behavior; [min, max]
            # outranks the rate bound, exactly as in the decide clamp
            # order
            min_replicas[i] = max(ha_min, min(int(down_floor[i]), ha_max))
            max_replicas[i] = min(ha_max, max(int(up_ceiling[i]), ha_min))
            unit_cost[i] = self._unit_cost(row.ha)
            slo_weight[i] = slo.violation_cost_weight
            max_hourly[i] = slo.max_hourly_cost
            slo_valid[i] = True
            for j, (_spec, target, observed) in enumerate(row.observed):
                # per-metric SLO targets (spec.behavior.slo.metrics)
                # outrank the spec-wide targetValue; the kernel's max
                # over the metric axis keeps risk WORST-CASE across
                # however many of them the row declares
                per_replica = slo.target_for(j)
                if not per_replica:
                    per_replica = target.target_value()
                if not per_replica or per_replica <= 0:
                    continue  # no capacity notion: metric carries no risk
                mu, sigma, ok = self._demand(row, j, observed)
                slo_target[i, j] = per_replica
                demand_mu[i, j] = mu
                demand_sigma[i, j] = sigma
                demand_valid[i, j] = ok
        return CK.CostInputs(
            base_desired=base,
            min_replicas=min_replicas,
            max_replicas=max_replicas,
            unit_cost=unit_cost,
            slo_weight=slo_weight,
            max_hourly_cost=max_hourly,
            slo_valid=slo_valid,
            slo_target=slo_target,
            demand_mu=demand_mu,
            demand_sigma=demand_sigma,
            demand_valid=demand_valid,
        )

    def _apply(
        self, rows: List, slo_rows: List[int],
        outputs: D.DecisionOutputs, out: CK.CostOutputs,
    ) -> D.DecisionOutputs:
        desired = np.asarray(out.desired, np.int32)
        hourly = np.asarray(out.expected_hourly, np.float32)
        risk = np.asarray(out.violation_risk, np.float32)
        headroom = np.asarray(out.headroom, np.int32)
        self._annotate_ledger(rows, slo_rows, outputs, out)
        # every row in THIS batch re-establishes (or loses) its
        # contribution and gauges; rows outside the batch keep theirs
        # untouched
        slo_keys = {_ha_key(rows[i].ha) for i in slo_rows}
        for row in rows:
            if _ha_key(row.ha) not in slo_keys:
                self._retire(*_ha_key(row.ha))
        for i in slo_rows:
            ha = rows[i].ha
            ns, name = _ha_key(ha)
            if self._g_hourly is not None:
                self._g_hourly.set(name, ns, float(hourly[i]))
                self._g_risk.set(name, ns, float(risk[i]))
            if self._c_adjusted is not None:
                self._c_adjusted.inc(name, ns)
            ref = ha.spec.scale_target_ref
            self._contrib[(ns, name)] = ((ns, ref.name), int(headroom[i]))
        return replace(outputs, desired=desired)

    def _annotate_ledger(  # lint: allow-complexity — provenance assembly: one guard per clamp direction
        self, rows: List, slo_rows: List[int],
        outputs: D.DecisionOutputs, out: CK.CostOutputs,
    ) -> None:
        """Provenance slice (observability/provenance.py): the cost
        stage stamps the chosen ladder candidate with its risk/cost
        score and WHICH bound clamped it — the hard budget
        (cost_limited) or the decide kernel's per-tick movement bound
        (the candidate landed exactly on an up_ceiling/down_floor that
        is tighter than the spec's own [min, max]) — plus the one-sigma
        warm-pool headroom the candidate implies. One attribute read
        when the ledger is off."""
        from karpenter_tpu.observability import default_ledger

        batch = default_ledger().current()  # None when disabled
        if batch is None:
            return
        idx = [i for i in slo_rows if i < batch.n]
        if not idx:
            return
        desired = np.asarray(out.desired, np.int64)
        base = np.asarray(outputs.desired, np.int64)
        hourly = np.asarray(out.expected_hourly, np.float32)
        risk = np.asarray(out.violation_risk, np.float32)
        up_ceiling = np.asarray(outputs.up_ceiling, np.int64)
        down_floor = np.asarray(outputs.down_floor, np.int64)
        n = batch.n
        movement = np.zeros(len(base), bool)
        score = np.zeros(len(base), np.float32)
        for i in idx:
            slo = rows[i].ha.spec.behavior.slo
            ha_min = rows[i].ha.spec.min_replicas
            ha_max = rows[i].ha.spec.max_replicas
            # the movement bound clamped iff the candidate sits ON the
            # rate-limited ceiling/floor AND that bound is tighter than
            # the spec bound it would otherwise have hit
            movement[i] = bool(
                (
                    desired[i] > base[i]
                    and up_ceiling[i] < ha_max
                    and desired[i] == min(
                        ha_max, max(int(up_ceiling[i]), ha_min)
                    )
                )
                or (
                    desired[i] < base[i]
                    and down_floor[i] > ha_min
                    and desired[i] == max(
                        ha_min, min(int(down_floor[i]), ha_max)
                    )
                )
            )
            # the kernel's objective at the chosen candidate:
            # violationCostWeight x risk + n x unitHourlyCost
            score[i] = (
                float(slo.violation_cost_weight) * float(risk[i])
                + float(hourly[i])
            )
        batch.annotate_rows(
            idx,
            slo_opted=True,
            cost_candidate=desired[:n].astype(np.int32),
            cost_risk=risk[:n],
            cost_hourly=hourly[:n],
            cost_score=score[:n],
            budget_clamped=np.asarray(out.cost_limited, bool)[:n],
            movement_clamped=movement[:n],
            warm_headroom=np.asarray(out.headroom, np.int32)[:n],
        )


def _ha_key(ha) -> Tuple[str, str]:
    return (ha.metadata.namespace, ha.metadata.name)
