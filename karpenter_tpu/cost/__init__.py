"""Cost- and SLO-aware scaling subsystem (docs/cost.md).

Three pieces, composed by the runtime:

  * CostModel (cost/model.py) — per-instance-type hourly pricing with
    spot-tier composition and annotation overrides; `group_costs` is
    the columnar encoder face, `unit_cost` the decide face.
  * CostEngine (cost/engine.py) — the per-tick batched multi-objective
    refinement of the fleet decide (ops/cost.py kernel through the
    SolverService.cost seam), never-block, zero-overhead when no HA
    carries spec.behavior.slo.
  * WarmPoolEngine (cost/warmpool.py) — forecast-risk-sized
    pre-provisioned headroom for spec.warmPool groups, actuated through
    the ScalableNodeGroup controller's fenced door.
  * PricingSource (cost/pricing.py) — pluggable pricing feeds: the
    mtime-reloading --pricing-file feed (and per-tenant feeds via the
    tenant registry) consulted before the built-in catalog.
"""

from karpenter_tpu.cost.engine import CostEngine
from karpenter_tpu.cost.model import (
    DEFAULT_CATALOG,
    HOURLY_COST_ANNOTATION,
    INSTANCE_TYPE_ANNOTATION,
    INSTANCE_TYPE_LABEL,
    CostModel,
)
from karpenter_tpu.cost.pricing import (
    FilePricingSource,
    PricingSource,
    StaticPricingSource,
    pricing_source_for,
)
from karpenter_tpu.cost.warmpool import WarmPoolEngine

__all__ = [
    "CostEngine",
    "CostModel",
    "DEFAULT_CATALOG",
    "FilePricingSource",
    "HOURLY_COST_ANNOTATION",
    "INSTANCE_TYPE_ANNOTATION",
    "INSTANCE_TYPE_LABEL",
    "PricingSource",
    "StaticPricingSource",
    "WarmPoolEngine",
    "pricing_source_for",
]
