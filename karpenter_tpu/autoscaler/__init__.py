from karpenter_tpu.autoscaler.autoscaler import AutoscalerFactory, BatchAutoscaler

__all__ = ["AutoscalerFactory", "BatchAutoscaler"]
