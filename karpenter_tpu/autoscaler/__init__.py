from karpenter_tpu.autoscaler.autoscaler import AutoscalerFactory, BatchAutoscaler

__all__ = ["AutoscalerFactory", "BatchAutoscaler"]

# arm the api layer's validation hooks at package import (webhook.py does
# the same): admission must reject unknown algorithm annotations in every
# process shape, including standalone mode where nothing else would import
# the algorithms package before the first reconcile
import karpenter_tpu.autoscaler.algorithms  # noqa: E402,F401
