"""Host-side autoscaler orchestration around the batched decision kernel.

reference: pkg/autoscaler/autoscaler.go:81-237 — per autoscaler: fetch
metrics, fetch scale target, compute desired replicas, apply transient and
bounded limits, update the scale target, set conditions.

The TPU redesign: instead of one scalar pipeline per object per tick, the
BatchAutoscaler snapshots EVERY HorizontalAutoscaler into structure-of-arrays
(padded to a compile bucket) and evaluates them in ONE device call
(ops/decision.decide_jit). Host code does only I/O: metric reads, scale
reads/writes, condition messages. Per-object failures (bad metric, missing
scale target) exclude that row from the batch without failing the others.
"""

from __future__ import annotations

import datetime
import math
import time as _time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from karpenter_tpu.api import conditions as cond
from karpenter_tpu.api.horizontalautoscaler import (
    AVERAGE_VALUE,
    HorizontalAutoscaler,
    MetricStatus,
    MetricValueStatus,
    PERCENT_SCALING_POLICY,
    PrometheusMetricStatus,
    UTILIZATION,
    VALUE,
)
from karpenter_tpu.observability import solver_trace
from karpenter_tpu.ops import decision as D
from karpenter_tpu.store import NotFoundError, Store

_TYPE_CODES = {
    VALUE: D.TYPE_VALUE,
    AVERAGE_VALUE: D.TYPE_AVERAGE_VALUE,
    UTILIZATION: D.TYPE_UTILIZATION,
}
_POLICY_CODES = {
    None: D.POLICY_MAX,
    "Max": D.POLICY_MAX,
    "Min": D.POLICY_MIN,
    "Disabled": D.POLICY_DISABLED,
}


@dataclass
class _Row:
    ha: HorizontalAutoscaler
    scale: object
    values: List[float]
    targets: List[float]
    types: List[int]
    # raw observations + spec target types, kept for status.currentMetrics
    # even when a custom algorithm replaces `values` with recommendations
    observed: List = field(default_factory=list)
    error: Optional[Exception] = None
    # a custom Algorithm replaced `values` with recommendations: the
    # forecaster must not blend predicted RAW metric values into them
    custom: bool = False
    # metric indices whose live query failed and reused the last history
    # sample (age-bounded) — excluded from new history appends
    stale_metrics: set = field(default_factory=set)


class BatchAutoscaler:
    """Evaluates all HorizontalAutoscalers as one device call per tick.

    `decider` is the decision half of the Algorithm seam: any
    (DecisionInputs) -> DecisionOutputs callable — the in-process jitted
    kernel (default) or a sidecar SolverClient.decide, making the control
    plane DEVICE-free under the gRPC process split (jax stays imported —
    ops/decision builds the jitted kernel at import — but no backend is
    initialized and no device math runs here; the bin-pack half is the
    `solver=` seam in producers/pendingcapacity.py).
    """

    def __init__(
        self, metrics_client_factory, store: Store, clock=_time.time,
        decider=None, forecaster=None, cost_engine=None, tenant=None,
        fused_tick_fn=None, pool_engine=None,
    ):
        self.metrics = metrics_client_factory
        self.store = store
        self.clock = clock
        # this control plane's own tenant id (--tenant-id), stamped on
        # provenance-ledger records so a shared /debug/decisions reader
        # can tell fleets apart; "" single-tenant
        self.tenant = tenant or ""
        self.decider = decider if decider is not None else D.decide_jit
        # predictive-scaling seam (forecast/, docs/forecasting.md): a
        # FleetForecaster owning metric history, the batched forecast
        # dispatch, and online skill gating. None = reactive-only.
        self.forecaster = forecaster
        # cost/SLO refinement seam (cost/, docs/cost.md): a CostEngine
        # refining the fleet decide in one batched dispatch — desired
        # counts only; conditions keep reporting the behavior-pipeline
        # view. None (or an SLO-free fleet) = cost-blind, bit-identical
        # decisions.
        self.cost_engine = cost_engine
        # fused steady-state tick (--fused-tick, ops/fusedtick.py): the
        # SolverService.fused_tick seam running forecast → decide →
        # cost as ONE device program. None = the chained per-stage
        # wire (bit-identical outputs; tests/test_fusedtick.py pins it).
        self.fused_tick_fn = fused_tick_fn
        # joint pool-group allocation seam (--poolgroups, poolgroups/,
        # docs/poolgroups.md): a PoolGroupEngine resolving PoolGroup
        # membership per tick, EXCLUDING member rows from the cost
        # engine's independent ladders and refining them in one joint
        # dispatch instead. None (or a group-free fleet) = the
        # uncoordinated wire, byte-identical.
        self.pool_engine = pool_engine
        # Times enter the kernel as f32 seconds relative to this epoch so a
        # long-lived process never loses sub-second precision to f32.
        self.epoch = clock()
        # per-engine memo of custom Algorithm instances (see
        # _snapshot_row): stateful algorithms keep their windows here
        self._algorithm_instances: Dict[str, object] = {}

    # -- snapshot ---------------------------------------------------------

    def _snapshot_row(self, ha: HorizontalAutoscaler) -> _Row:
        from karpenter_tpu.autoscaler import algorithms

        row = _Row(ha=ha, scale=None, values=[], targets=[], types=[])
        try:
            ref = ha.spec.scale_target_ref
            # the ref's apiVersion rides along so ARBITRARY scalable kinds
            # (a Deployment, any scale-marker CRD) resolve via discovery,
            # not a hard-coded kind table (reference: autoscaler.go:196-237
            # parseGroupResource + ScalesGetter)
            row.scale = self.store.get_scale(
                ref.kind, ha.metadata.namespace, ref.name,
                api_version=ref.api_version,
            )
            # spec-driven algorithm selection (the seam the reference left
            # as a TODO, algorithm.go:37-39): default rows encode raw
            # metrics for the kernel's native Proportional math; a custom
            # algorithm computes per-metric recommendations on host, which
            # enter the batch as AverageValue/target-1 metrics (the kernel
            # passes them through exactly) so select policy, stabilization,
            # rate-limit policies, and bounds still apply ON DEVICE
            name = algorithms.algorithm_name(ha)
            custom = None
            if name != algorithms.DEFAULT_ALGORITHM:
                # instances are memoized PER ENGINE, not per process:
                # stateful algorithms (trend windows) must survive
                # across reconciles but never leak across runtimes or
                # share clocks with another engine's fake time
                custom = self._algorithm_instances.get(name)
                if custom is None:
                    custom = algorithms.for_spec(ha)
                    self._algorithm_instances[name] = custom
            row.custom = custom is not None
            for j, metric_spec in enumerate(ha.spec.metrics):
                observed = self._observe_metric(ha, j, metric_spec, row)
                target = metric_spec.get_target()
                row.observed.append((metric_spec, target, observed.value))
                if custom is not None:
                    metric = algorithms.Metric(
                        value=observed.value,
                        target_type=target.type,
                        target_value=target.target_value(),
                        name=getattr(observed, "name", ""),
                        # labels distinguish two specs over the same
                        # metric name — stateful algorithms (trend) key
                        # windows on them, or a sawtooth of interleaved
                        # series would fit garbage slopes
                        labels=dict(
                            getattr(observed, "labels", {}) or {}
                        ),
                        # stateful algorithms key history on the OWNING
                        # autoscaler and order it by this clock
                        owner=(
                            ha.metadata.namespace,
                            ha.metadata.name,
                        ),
                        at=self.clock(),
                    )
                    row.values.append(
                        float(
                            custom.get_desired_replicas(
                                metric, row.scale.status_replicas
                            )
                        )
                    )
                    row.targets.append(1.0)
                    row.types.append(D.TYPE_AVERAGE_VALUE)
                else:
                    row.values.append(observed.value)
                    row.targets.append(target.target_value())
                    row.types.append(
                        _TYPE_CODES.get(target.type, D.TYPE_UNKNOWN)
                    )
        except NotFoundError as e:
            # a missing scale target is RETRYABLE: the target may be
            # created any moment, and its creation fires no watch event
            # on the HA — deactivation would strand the autoscaler
            # (engine ladder: docs/resilience.md). Lazy import: the
            # controllers package imports this module.
            from karpenter_tpu.controllers.errors import RetryableError

            row.error = RetryableError(str(e), code="ScaleTargetNotFound")
            row.error.__cause__ = e
        except Exception as e:  # noqa: BLE001 - row-isolated failure
            row.error = e
        return row

    def _observe_metric(self, ha, j: int, metric_spec, row: _Row):
        """One metric read, with the stale-sample bridge: a failed query
        reuses the newest history sample when it is young enough
        (forecaster.stale_max_age_s), so a transient exporter blip
        degrades ONE input instead of dropping the whole row from the
        batch. Older-than-bound history re-raises — an autoscaler must
        not keep scaling on a signal that has been dark for minutes."""
        # lazy import (the controllers package imports this module)
        from karpenter_tpu.metrics.clients import MetricQueryError

        try:
            return self.metrics.for_metric(metric_spec).get_current_value(
                metric_spec
            )
        except MetricQueryError:
            if self.forecaster is None:
                raise
            value = self.forecaster.stale_value(ha, j, self.clock())
            if value is None:
                raise
            row.stale_metrics.add(j)
            from karpenter_tpu.metrics.types import Metric as MetricValue

            return MetricValue(
                name=(
                    metric_spec.prometheus.query
                    if metric_spec.prometheus is not None
                    else ""
                ),
                value=value,
            )

    # -- batch reconcile --------------------------------------------------

    def reconcile_batch(
        self, has: List[HorizontalAutoscaler]
    ) -> Dict[tuple, Optional[Exception]]:
        """Returns {(namespace, name): error or None}; mutates each HA's status."""
        key = lambda ha: (ha.metadata.namespace, ha.metadata.name)
        results: Dict[tuple, Optional[Exception]] = {}
        rows = [self._snapshot_row(ha) for ha in has]
        live = [r for r in rows if r.error is None]
        for row in rows:
            if row.error is not None:
                results[key(row.ha)] = row.error

        if live:
            outputs = self._evaluate_live(live)
            now = self.clock()
            for i, row in enumerate(live):
                self._apply(row, outputs, i, now)
                results[key(row.ha)] = None
        return results

    def _evaluate_live(self, live: List[_Row]) -> D.DecisionOutputs:
        """Forecast -> decide -> cost-refine the live rows, with the
        provenance ledger batch (when enabled) annotated at each stage
        and committed once the final counts are known."""
        ledger_batch = self._begin_ledger(live)
        # PoolGroup membership resolves ONCE per tick (store list +
        # name matching); None = no group participates and every path
        # below is byte-identical to the pre-subsystem wire
        pg_plan = None
        if self.pool_engine is not None:
            pg_plan = self.pool_engine.plan(live)
        if self.fused_tick_fn is not None:
            outputs = self._evaluate_fused(live, ledger_batch, pg_plan)
        else:
            # the forecast pass: ingest this tick's observations into
            # the history store and predict every eligible series in ONE
            # batched dispatch; {} (no spec, warming up, skill-gated, or
            # ANY failure) keeps the tick purely reactive
            forecasts: Dict[tuple, float] = {}
            if self.forecaster is not None:
                forecasts = self.forecaster.forecast_rows(
                    live, self.clock()
                )
            outputs = self._decide(live, forecasts)
            if ledger_batch is not None:
                n = len(live)
                ledger_batch.annotate(
                    base_desired=np.asarray(outputs.desired)[:n],
                    final_desired=np.asarray(outputs.desired)[:n],
                )
            if self.cost_engine is not None:
                # the multi-objective pass (docs/cost.md): ONE batched
                # refine of the whole fleet's desired counts; any
                # failure returns the base outputs (never-block) and
                # an SLO-free fleet returns the SAME object untouched.
                # PoolGroup members skip the independent ladder — the
                # joint pass below owns their counts this tick.
                outputs = self.cost_engine.adjust(
                    live, outputs,
                    exclude=pg_plan.grouped if pg_plan is not None else None,
                )
                if ledger_batch is not None:
                    ledger_batch.annotate(
                        final_desired=np.asarray(
                            outputs.desired
                        )[:len(live)],
                    )
            if pg_plan is not None:
                # the joint allocation (docs/poolgroups.md): every
                # group's K^P candidate ladder in ONE batched dispatch,
                # desired overlaid at the member rows; never-block —
                # failure leaves the uncoordinated counts standing
                outputs = self.pool_engine.refine(live, pg_plan, outputs)
                if ledger_batch is not None:
                    ledger_batch.annotate(
                        final_desired=np.asarray(
                            outputs.desired
                        )[:len(live)],
                    )
        if ledger_batch is not None:
            from karpenter_tpu.observability import default_ledger

            default_ledger().commit(ledger_batch)
        return outputs

    def _evaluate_fused(self, live: List[_Row], ledger_batch, pg_plan=None):  # lint: allow-complexity — four optional stages x plan/commit halves around ONE dispatch; splitting would scatter each stage's paired halves
        """The fused steady-state tick (--fused-tick, ops/fusedtick.py):
        forecast → decide → cost as ONE SolverService.fused_tick call,
        with each engine's host bookkeeping split into plan/commit
        halves around the single dispatch. Every seam keeps its own
        never-block posture — fused_plan/fused_operands return None
        instead of raising (the stage is then simply absent, exactly
        the chained path's degradation), and the service ladder covers
        device-side failures (fused → chained per-stage → numpy) — so
        the fixed point matches the chained wire bit for bit
        (tests/test_fusedtick.py)."""
        from karpenter_tpu.ops import fusedtick as FT

        now = self.clock()
        plan = None
        if self.forecaster is not None:
            plan = self.forecaster.fused_plan(live, now)
        inputs = self._decision_inputs(live, None)
        kw = {}
        if plan is not None:
            _eligible, finputs, row_map, col_map, need, blend = plan
            kw.update(
                forecast=finputs,
                series_row=row_map,
                series_col=col_map,
                series_need=need,
                series_blend=blend,
            )
        cost_plan = None
        if self.cost_engine is not None:
            cost_plan = self.cost_engine.fused_operands(
                live,
                int(inputs.spec_replicas.shape[0]),
                int(inputs.metric_value.shape[1]),
                exclude=pg_plan.grouped if pg_plan is not None else None,
            )
            if cost_plan is not None:
                kw.update(cost_plan[1])
        pg_ops = None
        if pg_plan is not None:
            pg_ops = self.pool_engine.fused_operands(
                live, pg_plan,
                int(inputs.spec_replicas.shape[0]),
                int(inputs.metric_value.shape[1]),
            )
            if pg_ops is not None:
                kw["poolgroup"] = pg_ops
        with solver_trace("autoscaler.fused_tick"):
            out = self.fused_tick_fn(
                FT.FusedTickInputs(decision=inputs, **kw)
            )
        if plan is not None and out.forecast is not None:
            self.forecaster.fused_commit(plan[0], out.forecast, live, now)
        outputs = out.decision
        if ledger_batch is not None:
            n = len(live)
            ledger_batch.annotate(
                base_desired=np.asarray(outputs.desired)[:n],
                final_desired=np.asarray(outputs.desired)[:n],
            )
        if cost_plan is not None and out.cost is not None:
            outputs = self.cost_engine.fused_commit(
                live, cost_plan[0], outputs, out.cost
            )
            if ledger_batch is not None:
                ledger_batch.annotate(
                    final_desired=np.asarray(
                        outputs.desired
                    )[:len(live)],
                )
        if pg_ops is not None and out.poolgroup is not None:
            outputs = self.pool_engine.fused_commit(
                live, pg_plan, outputs, out.poolgroup
            )
            if ledger_batch is not None:
                ledger_batch.annotate(
                    final_desired=np.asarray(
                        outputs.desired
                    )[:len(live)],
                )
        return outputs

    def _begin_ledger(self, live: List[_Row]):
        """Open the tick's provenance batch (observability/provenance):
        one record per live HorizontalAutoscaler, annotated in place by
        the forecast pass, the cost refinement, and the solver decide
        as the batch flows through them. None (one attribute read) when
        the ledger is disabled — the default posture."""
        from karpenter_tpu.observability import default_ledger
        from karpenter_tpu.observability.provenance import OBSERVED_WIDTH

        ledger = default_ledger()
        if not ledger.enabled:
            return None
        n = len(live)
        observed = np.zeros((n, OBSERVED_WIDTH), np.float32)
        observed_n = np.zeros(n, np.int16)
        for i, row in enumerate(live):
            m = min(len(row.values), OBSERVED_WIDTH)
            observed[i, :m] = row.values[:m]
            observed_n[i] = len(row.values)
        return ledger.begin(
            "ha",
            n,
            autosolver=True,
            tenant=self.tenant,
            namespace=[r.ha.metadata.namespace for r in live],
            name=[r.ha.metadata.name for r in live],
            group=[r.ha.spec.scale_target_ref.name for r in live],
            observed=observed,
            observed_n=observed_n,
            prev_replicas=np.asarray(
                [r.scale.status_replicas for r in live], np.int32
            ),
        )

    def _decide(
        self, rows: List[_Row], forecasts: Optional[Dict[tuple, float]] = None
    ) -> D.DecisionOutputs:
        inputs = self._decision_inputs(rows, forecasts)
        with solver_trace("autoscaler.decide"):
            return self.decider(inputs)

    def _decision_inputs(
        self, rows: List[_Row], forecasts: Optional[Dict[tuple, float]] = None
    ) -> D.DecisionInputs:
        n = D.pad_to(len(rows))
        m = max(1, max(len(r.values) for r in rows))

        def pad2(getter, fill, dtype):
            arr = np.full((n, m), fill, dtype)
            for i, r in enumerate(rows):
                vals = getter(r)
                arr[i, : len(vals)] = vals
            return arr

        valid = np.zeros((n, m), bool)
        for i, r in enumerate(rows):
            valid[i, : len(r.values)] = True

        def col(fn, fill, dtype):
            arr = np.full(n, fill, dtype)
            for i, r in enumerate(rows):
                arr[i] = fn(i, r)
            return arr

        # one (up, down) rules resolution per row, reused by all four columns
        resolved_rules = [
            (
                r.ha.spec.behavior.scale_up_rules(),
                r.ha.spec.behavior.scale_down_rules(),
            )
            for r in rows
        ]

        # Count/Percent policy slots: K padded to a power of two — the row
        # axis is already padded (pad_to above) to keep decide_jit's
        # compiled shape stable, and the K axis must not undo that by
        # retracing when one autoscaler gains a second policy
        widest = max(
            [1]
            + [
                len(rules.policies or [])
                for pair in resolved_rules
                for rules in pair
            ]
        )
        k = 1 << (widest - 1).bit_length() if widest > 1 else 1

        def policy_slots(direction: int):
            ptype = np.zeros((n, k), np.int32)
            pvalue = np.zeros((n, k), np.int32)
            pperiod = np.ones((n, k), np.int32)
            pvalid = np.zeros((n, k), bool)
            for i in range(len(rows)):
                for j, policy in enumerate(
                    resolved_rules[i][direction].policies or []
                ):
                    ptype[i, j] = (
                        D.POLICY_TYPE_PERCENT
                        if policy.type == PERCENT_SCALING_POLICY
                        else D.POLICY_TYPE_COUNT
                    )
                    pvalue[i, j] = policy.value
                    pperiod[i, j] = policy.period_seconds
                    pvalid[i, j] = True
            # plain numpy: the local jitted kernel converts on entry; the
            # remote decider serializes host bytes (no device work here)
            return (ptype, pvalue, pperiod, pvalid)

        up_ptype, up_pvalue, up_pperiod, up_pvalid = policy_slots(0)
        down_ptype, down_pvalue, down_pperiod, down_pvalid = policy_slots(1)

        # proactive blend operands: predicted metric values slot into
        # the same [N, M] layout; absent forecasts leave the fields None
        # so a reactive-only fleet keeps the pre-forecast program
        forecast_value = forecast_valid = None
        if forecasts:
            forecast_value = np.zeros((n, m), np.float32)
            forecast_valid = np.zeros((n, m), bool)
            for (i, j), predicted in forecasts.items():
                forecast_value[i, j] = predicted
                forecast_valid[i, j] = True

        now = np.float32(self.clock() - self.epoch)
        inputs = D.DecisionInputs(
            metric_value=pad2(lambda r: r.values, 0.0, np.float32),
            target_value=pad2(lambda r: r.targets, 0.0, np.float32),
            target_type=pad2(lambda r: r.types, D.TYPE_UNKNOWN, np.int32),
            metric_valid=valid,
            spec_replicas=col(lambda i, r: r.scale.spec_replicas or 0, 0, np.int32),
            status_replicas=col(lambda i, r: r.scale.status_replicas, 0, np.int32),
            min_replicas=col(lambda i, r: r.ha.spec.min_replicas, 0, np.int32),
            max_replicas=col(lambda i, r: r.ha.spec.max_replicas, 0, np.int32),
            up_window=col(
                lambda i, r: resolved_rules[i][0].stabilization_window_seconds,
                0,
                np.int32,
            ),
            down_window=col(
                lambda i, r: resolved_rules[i][1].stabilization_window_seconds,
                0,
                np.int32,
            ),
            up_policy=col(
                lambda i, r: _POLICY_CODES.get(
                    resolved_rules[i][0].select_policy, D.POLICY_MAX
                ),
                D.POLICY_MAX,
                np.int32,
            ),
            down_policy=col(
                lambda i, r: _POLICY_CODES.get(
                    resolved_rules[i][1].select_policy, D.POLICY_MAX
                ),
                D.POLICY_MAX,
                np.int32,
            ),
            last_scale_time=col(
                lambda i, r: (r.ha.status.last_scale_time or 0.0) - self.epoch,
                0.0,
                np.float32,
            ),
            has_last_scale=col(
                lambda i, r: r.ha.status.last_scale_time is not None,
                False,
                bool,
            ),
            now=np.float32(now),
            up_ptype=up_ptype,
            up_pvalue=up_pvalue,
            up_pperiod=up_pperiod,
            up_pvalid=up_pvalid,
            down_ptype=down_ptype,
            down_pvalue=down_pvalue,
            down_pperiod=down_pperiod,
            down_pvalid=down_pvalid,
            forecast_value=forecast_value,
            forecast_valid=forecast_valid,
        )
        return inputs

    def _mark_forecast_condition(self, ha, mgr) -> None:
        """Predictive posture on status (docs/forecasting.md): True
        while forecasts blend into scale-up, False (with the structured
        reason) while degraded to reactive-only — warming up, skill
        below the floor, or the forecast path failing. A spec that
        opted back OUT drops the condition entirely: a frozen last
        value would keep reporting a posture nothing computes."""
        if ha.spec.behavior.forecast is not None and self.forecaster is not None:
            active, reason, message = self.forecaster.verdict(
                ha.metadata.namespace, ha.metadata.name
            )
            if active:
                mgr.mark_true(cond.FORECASTING)
            else:
                mgr.mark_false(cond.FORECASTING, reason, message)
        else:
            ha.status.conditions[:] = [
                c for c in ha.status.conditions
                if c.type != cond.FORECASTING
            ]

    def _apply(self, row: _Row, out: D.DecisionOutputs, i: int, now: float):
        """Write back one row's decision (reference: autoscaler.go:81-113,
        155-194 for the condition semantics)."""
        ha, scale = row.ha, row.scale
        mgr = ha.status_conditions()
        desired = int(out.desired[i])
        recommendation = int(out.recommendation[i])
        able = bool(out.able_to_scale[i])
        unbounded = bool(out.scaling_unbounded[i])
        rate_limited = bool(out.rate_limited[i])

        ha.status.current_replicas = scale.status_replicas

        # last-read state of every configured metric: the reference MODELS
        # status.currentMetrics (horizontalautoscaler_status.go:36-39) but
        # never populates it — here every reconcile records what it saw,
        # slotted by the spec's own target type
        ha.status.current_metrics = [
            _metric_status(metric_spec, target, value)
            for metric_spec, target, value in row.observed
        ]

        if able:
            # a partial policy clamp still scales (just by less than
            # recommended), so AbleToScale stays true; the clamp itself is
            # visible through desired < recommendation in status
            mgr.mark_true(cond.ABLE_TO_SCALE)
        else:
            able_at = self.epoch + float(out.able_at[i])
            stamp = datetime.datetime.fromtimestamp(
                able_at, datetime.timezone.utc
            ).strftime("%Y-%m-%dT%H:%M:%SZ")
            held_by = (
                "scaling policy budget spent"
                if rate_limited
                else "within stabilization window"
            )
            mgr.mark_false(
                cond.ABLE_TO_SCALE,
                "",
                f"{held_by}, able to scale at {stamp}",
            )

        if unbounded:
            mgr.mark_true(cond.SCALING_UNBOUNDED)
        else:
            # the kernel's post-window/policy pre-[min,max] value: exactly
            # what the bounds clamp saw (NOT the raw recommendation, which
            # a partial policy clamp may already have reduced)
            limited = int(out.limited[i])
            mgr.mark_false(
                cond.SCALING_UNBOUNDED,
                "",
                f"recommendation {limited} limited by bounds "
                f"[{ha.spec.min_replicas}, {ha.spec.max_replicas}]",
            )

        self._mark_forecast_condition(ha, mgr)

        if scale.spec_replicas is not None and desired == scale.spec_replicas:
            return
        scale.spec_replicas = desired
        self.store.update_scale(
            ha.spec.scale_target_ref.kind, scale,
            api_version=ha.spec.scale_target_ref.api_version,
        )
        ha.status.desired_replicas = desired
        ha.status.last_scale_time = now


def _metric_status(metric_spec, target, value: float):
    current = MetricValueStatus()
    # a NaN/inf observation is legitimate (e.g. reserved-capacity over an
    # empty node group, the reference's NaN case) — record NO value rather
    # than poisoning the status document (json.dumps emits the non-standard
    # NaN literal, which a real apiserver rejects, killing the whole
    # status patch)
    if not math.isfinite(value):
        pass
    elif target.type == UTILIZATION:
        current.average_utilization = int(round(value * 100))
    elif target.type == AVERAGE_VALUE:
        current.average_value = value
    else:
        current.value = value
    query = (
        metric_spec.prometheus.query
        if metric_spec.prometheus is not None
        else ""
    )
    return MetricStatus(
        prometheus=PrometheusMetricStatus(query=query, current=current)
    )


class AutoscalerFactory:
    """reference: autoscaler.go:38-69 — kept for per-object call sites; the
    controller uses the batch path."""

    def __init__(
        self, metrics_client_factory, store: Store, clock=_time.time,
        decider=None, forecaster=None, cost_engine=None,
    ):
        self.batch = BatchAutoscaler(
            metrics_client_factory, store, clock, decider=decider,
            forecaster=forecaster, cost_engine=cost_engine,
        )

    def reconcile(self, ha: HorizontalAutoscaler) -> None:
        error = self.batch.reconcile_batch([ha])[
            (ha.metadata.namespace, ha.metadata.name)
        ]
        if error is not None:
            raise error
