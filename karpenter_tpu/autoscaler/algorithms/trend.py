"""Trend algorithm: scale ahead of ramping load.

reference anchor: pkg/autoscaler/algorithms/algorithm.go:37-39 leaves
algorithm selection as a TODO with Proportional hardcoded; this is the
second registered algorithm that seam exists for (select with the
`autoscaling.karpenter.sh/algorithm: trend` annotation). The reference
has no predictive capability at all — its loop reacts to the current
instant vector only, so a steadily ramping queue is always chased from
behind by (poll interval + stabilization) of lag.

Method: keep a per-(autoscaler, metric) sliding window of observed
values, fit a least-squares line, and run Proportional's HPA math on
the value PROJECTED `horizon` seconds ahead. Two safety properties:

- never scales down ahead of the data: the projected value is
  max(current, projection), so a falling trend behaves exactly like
  plain Proportional (down-scaling stays governed by the stabilization
  window and rate policies, which apply on device after this
  recommendation like every custom algorithm's);
- degrades to plain Proportional whenever the window holds fewer than
  two samples or spans less than a second (a fresh autoscaler, a
  paused metric, clock skew) — never extrapolates from noise.

State: one shared instance holds every window (keyed by the OWNING
autoscaler + metric identity, so two autoscalers watching the same
query never share a trend); windows prune by age on every observation
and the key set prunes lazily, so a deleted autoscaler's history ages
out instead of leaking.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, Tuple

from karpenter_tpu.autoscaler.algorithms.proportional import Proportional

# class-level defaults; register_algorithm("my-trend", lambda:
# Trend(window=..., horizon=...)) for other operating points
DEFAULT_WINDOW_SECONDS = 300.0
DEFAULT_HORIZON_SECONDS = 60.0
# lazy key pruning: when the key census exceeds this, drop windows whose
# newest sample is older than a window (deleted/renamed autoscalers)
_PRUNE_THRESHOLD = 1024


class Trend:
    def __init__(
        self,
        window: float = DEFAULT_WINDOW_SECONDS,
        horizon: float = DEFAULT_HORIZON_SECONDS,
    ):
        self.window = float(window)
        self.horizon = float(horizon)
        self._proportional = Proportional()
        self._series: Dict[tuple, Deque[Tuple[float, float]]] = {}

    def _key(self, metric) -> tuple:
        return (
            getattr(metric, "owner", ()),
            metric.name,
            tuple(sorted(metric.labels.items())),
        )

    def _observe(self, metric) -> Deque[Tuple[float, float]]:
        at = float(getattr(metric, "at", 0.0))
        series = self._series.setdefault(self._key(metric), deque())
        if series and at < series[-1][0]:
            # clock went backwards (restart with an older fake clock,
            # NTP step): a poisoned window must not extrapolate
            series.clear()
        series.append((at, float(metric.value)))
        while series and series[0][0] < at - self.window:
            series.popleft()
        if len(self._series) > _PRUNE_THRESHOLD:
            stale = [
                key
                for key, s in self._series.items()
                if not s or s[-1][0] < at - self.window
            ]
            for key in stale:
                del self._series[key]
        return series

    def _projected(self, series) -> float:
        """Least-squares slope over the window, projected `horizon`
        ahead of the NEWEST sample; the caller floors the result at the
        current value."""
        n = len(series)
        t0 = series[0][0]
        ts = [t - t0 for t, _ in series]
        vs = [v for _, v in series]
        mean_t = sum(ts) / n
        mean_v = sum(vs) / n
        var_t = sum((t - mean_t) ** 2 for t in ts)
        if var_t < 1.0:  # window too narrow to carry a slope
            return vs[-1]
        slope = (
            sum((t - mean_t) * (v - mean_v) for t, v in zip(ts, vs))
            / var_t
        )
        return vs[-1] + slope * self.horizon

    def get_desired_replicas(self, metric, replicas: int) -> int:
        series = self._observe(metric)
        value = float(metric.value)
        if len(series) >= 2:
            # never project BELOW the data: a falling trend scales like
            # plain Proportional; only a rising one scales ahead
            value = max(value, self._projected(series))
        if value == metric.value:
            return self._proportional.get_desired_replicas(
                metric, replicas
            )
        projected = dataclasses.replace(metric, value=value)
        return self._proportional.get_desired_replicas(
            projected, replicas
        )
