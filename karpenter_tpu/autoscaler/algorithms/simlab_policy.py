"""`simlab` algorithm: a frozen search-tuned SimLab policy, live.

The third registered algorithm the algorithm.go:37-39 seam exists for
(select with `autoscaling.karpenter.sh/algorithm: simlab`). The SimLab
policy plane (karpenter_tpu/simlab/policy.py, docs/simulator.md)
grid-searches a 3-knob decision surface — forecast blend floor, cost
shed weight, scale-down stabilization window — against batched
simulated rollouts; the winning vector freezes into this algorithm, so
what search scored is what the fleet runs.

Live translation of the knobs (the kernel's price/fault trails don't
exist on the metric path):

  blend floor   the observed value blends with a one-step linear
                projection (value + the last observed delta), floored
                by the knob: blend = max(value, floor * projection) —
                never BELOW the data, exactly the Trend discipline;
  stab window   a per-(autoscaler, metric) scale-down streak must age
                past the window before a smaller desired count is
                released (holds return the current replicas);
  cost weight   carried on the instance for introspection — live cost
                shedding already belongs to the cost ladder
                (docs/cost.md), which applies AFTER every algorithm's
                recommendation, so applying it here would double-shed.

NEVER-BLOCK (the acceptance contract): any failure inside the tuned
path — bad history, arithmetic on poisoned values, anything — degrades
THAT decision to the plain reactive tick (Proportional on the raw
metric). The tuned path is advisory; the reactive baseline is the
floor.

State: per-(autoscaler, metric) (last value, last at, streak), pruned
lazily past a census threshold like Trend's windows, so deleted
autoscalers age out instead of leaking.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from karpenter_tpu.autoscaler.algorithms.proportional import Proportional

# lazy key pruning, the Trend discipline: when the census exceeds this,
# drop keys whose newest sample is older than the staleness horizon
_PRUNE_THRESHOLD = 1024
_STALE_SECONDS = 300.0


class SimlabPolicy:
    def __init__(self, knobs=None):
        if knobs is None:
            # the shipped frozen winner (simlab/policy.py FROZEN_KNOBS);
            # register_algorithm("my-simlab", lambda:
            # SimlabPolicy(knobs=...)) pins a re-searched vector
            from karpenter_tpu.simlab.policy import FROZEN_KNOBS

            knobs = FROZEN_KNOBS
        self.blend_floor = float(knobs[0])
        self.cost_weight = float(knobs[1])  # introspection only (docstring)
        self.stab_window = float(knobs[2])
        self._proportional = Proportional()
        # key -> (last value, last at, scale-down streak)
        self._state: Dict[tuple, Tuple[float, float, float]] = {}

    def _key(self, metric) -> tuple:
        return (
            getattr(metric, "owner", ()),
            metric.name,
            tuple(sorted(metric.labels.items())),
        )

    def _blend(self, metric, prev: Optional[Tuple]) -> float:
        """max(value, floor * one-step projection): scale-ups see the
        projected ramp, scale-downs never drop below the data."""
        value = float(metric.value)
        if prev is None or self.blend_floor <= 0.0:
            return value
        projection = value + (value - prev[0])
        return max(value, self.blend_floor * projection)

    def _tuned(self, metric, replicas: int) -> int:
        key = self._key(metric)
        prev = self._state.get(key)
        at = float(getattr(metric, "at", 0.0))
        if prev is not None and at < prev[1]:
            prev = None  # clock went backwards: don't project from it
        blended = self._blend(metric, prev)
        if blended == metric.value:
            desired = self._proportional.get_desired_replicas(
                metric, replicas
            )
        else:
            desired = self._proportional.get_desired_replicas(
                dataclasses.replace(metric, value=blended), replicas
            )
        streak = (prev[2] + 1.0) if prev is not None else 1.0
        if desired >= replicas:
            streak = 0.0
        self._state[key] = (float(metric.value), at, streak)
        self._prune(at)
        if desired < replicas and streak <= self.stab_window:
            return replicas  # held: the streak is younger than the window
        return desired

    def _prune(self, at: float) -> None:
        if len(self._state) <= _PRUNE_THRESHOLD:
            return
        stale = [
            key
            for key, (_v, last_at, _s) in self._state.items()
            if last_at < at - _STALE_SECONDS
        ]
        for key in stale:
            del self._state[key]

    def get_desired_replicas(self, metric, replicas: int) -> int:
        try:
            return self._tuned(metric, replicas)
        except Exception:  # noqa: BLE001 — never-block: reactive floor
            try:
                return self._proportional.get_desired_replicas(
                    metric, replicas
                )
            except Exception:  # noqa: BLE001 — poisoned metric (NaN):
                return int(replicas)  # hold the fleet, never block
