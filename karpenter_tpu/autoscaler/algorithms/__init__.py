"""Autoscaling algorithms (reference: pkg/autoscaler/algorithms/algorithm.go:24-40).

The reference hardcodes Proportional and leaves spec-driven selection as a
TODO (algorithm.go:37-39). Here the seam is REAL: algorithms register by
name, a HorizontalAutoscaler selects one with the
`autoscaling.karpenter.sh/algorithm` annotation (annotation, not a spec
field, so the CRD schema stays reference-compatible), and unknown names are
rejected at admission.

TPU-first composition: the batched device kernel (karpenter_tpu.ops.decision)
natively implements Proportional's HPA semantics for the whole fleet in one
call. A row that selects a CUSTOM algorithm still rides the same batch —
the algorithm computes per-metric replica recommendations on host, and the
snapshot encodes them as AverageValue metrics with target 1 (the kernel's
AverageValue rule is ceil(value/target), so the recommendation passes
through exactly) — select policy, stabilization windows, Count/Percent
rate-limit policies, and min/max bounds then apply uniformly ON DEVICE for
default and custom rows alike.

The scalar Proportional here also serves as the golden oracle for kernel
tests.
"""

from dataclasses import dataclass, field
from typing import Callable, Dict

from karpenter_tpu.autoscaler.algorithms.proportional import Proportional

# annotation on the HorizontalAutoscaler selecting the algorithm by name
ALGORITHM_ANNOTATION = "autoscaling.karpenter.sh/algorithm"
DEFAULT_ALGORITHM = "proportional"


@dataclass
class Metric:
    """Observed value + target (reference: algorithm.go:29-34).

    `owner` (the observing autoscaler's (namespace, name)) and `at`
    (observation time) extend the reference shape so STATEFUL
    algorithms (trend windows) can key and order their history; both
    default empty for plain stateless use."""

    value: float = 0.0
    target_type: str = ""
    target_value: float = 0.0
    name: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    owner: tuple = ()
    at: float = 0.0


_registry: Dict[str, Callable[[], object]] = {}


def register_algorithm(name: str, factory: Callable[[], object]) -> None:
    """Register an Algorithm factory; instances must provide
    get_desired_replicas(metric, replicas) -> int (algorithm.go:24-26)."""
    _registry[name] = factory


def known_algorithms() -> list:
    return sorted(_registry)


def algorithm_name(ha) -> str:
    """The algorithm a HorizontalAutoscaler selects (default proportional)."""
    return (
        ha.metadata.annotations.get(ALGORITHM_ANNOTATION, DEFAULT_ALGORITHM)
        if getattr(ha, "metadata", None) is not None
        else DEFAULT_ALGORITHM
    )


def _resolve(name: str) -> Callable[[], object]:
    """ONE unknown-name error for both admission and reconcile paths."""
    factory = _registry.get(name)
    if factory is None:
        raise ValueError(
            f"unknown algorithm {name!r} in annotation "
            f"{ALGORITHM_ANNOTATION}; known: {', '.join(known_algorithms())}"
        )
    return factory


def validate_algorithm(ha) -> None:
    """Admission-time check: an unknown algorithm name must be rejected
    when the object is written, not discovered at reconcile time."""
    _resolve(algorithm_name(ha))


def for_spec(ha_or_none=None):
    """Resolve the Algorithm instance for a HorizontalAutoscaler.

    reference: algorithm.go:36-40 hardcodes Proportional "until we
    implement a means to select via the spec"; this implements it.
    """
    name = (
        algorithm_name(ha_or_none)
        if ha_or_none is not None
        else DEFAULT_ALGORITHM
    )
    return _resolve(name)()


register_algorithm(DEFAULT_ALGORITHM, Proportional)

# trend: the factory returns FRESH instances; the autoscaler engine
# memoizes one per name (autoscaler.py _algorithm_instances), so trend
# windows survive across reconciles without a process-wide global that
# would leak history (and fake clocks) across runtimes
from karpenter_tpu.autoscaler.algorithms.trend import Trend  # noqa: E402

register_algorithm("trend", Trend)

# simlab: the frozen search-tuned SimLab policy (docs/simulator.md)
# behind the never-block contract — any tuned-path failure degrades
# that decision to the plain reactive tick; same fresh-instance /
# engine-memoized lifecycle as trend
from karpenter_tpu.autoscaler.algorithms.simlab_policy import (  # noqa: E402
    SimlabPolicy,
)

register_algorithm("simlab", SimlabPolicy)

# admission wiring: the api layer exposes a hook registry (it cannot import
# this package — that would invert the layering); importing the algorithms
# package is what arms the annotation check, and every control-plane entry
# point does (runtime -> autoscaler -> algorithms)
from karpenter_tpu.api.horizontalautoscaler import (  # noqa: E402
    register_validation_hook,
)

register_validation_hook(validate_algorithm)

__all__ = [
    "ALGORITHM_ANNOTATION",
    "DEFAULT_ALGORITHM",
    "Metric",
    "Proportional",
    "algorithm_name",
    "for_spec",
    "known_algorithms",
    "register_algorithm",
    "validate_algorithm",
]
