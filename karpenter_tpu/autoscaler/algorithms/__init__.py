"""Autoscaling algorithms (reference: pkg/autoscaler/algorithms/algorithm.go:24-40).

The Algorithm seam is where the reference intended pluggable decision
backends; in the TPU build the default backend is the batched device kernel
(karpenter_tpu.ops.decision) and the scalar Proportional here serves as the
per-object fallback and the golden oracle for kernel tests.
"""

from dataclasses import dataclass, field
from typing import Dict

from karpenter_tpu.autoscaler.algorithms.proportional import Proportional


@dataclass
class Metric:
    """Observed value + target (reference: algorithm.go:29-34)."""

    value: float = 0.0
    target_type: str = ""
    target_value: float = 0.0
    name: str = ""
    labels: Dict[str, str] = field(default_factory=dict)


def for_spec(spec) -> Proportional:
    """reference: algorithm.go:36-40 (hardcoded Proportional for now)."""
    return Proportional()


__all__ = ["Metric", "Proportional", "for_spec"]
