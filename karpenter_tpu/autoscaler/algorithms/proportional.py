"""Scalar proportional algorithm — HPA-style ratio scaling.

reference: pkg/autoscaler/algorithms/proportional.go:30-47. This is the
float64 host implementation; it is the oracle the batched device kernel
(karpenter_tpu/ops/decision.py) is golden-tested against.
"""

from __future__ import annotations

import math

from karpenter_tpu.api.horizontalautoscaler import AVERAGE_VALUE, UTILIZATION, VALUE
from karpenter_tpu.utils.log import logger


class Proportional:
    def get_desired_replicas(self, metric, replicas: int) -> int:
        ratio = metric.value / metric.target_value if metric.target_value else 0.0
        proportional = float(replicas) * ratio
        if metric.target_type == VALUE:
            # proportional, cannot scale to zero
            return int(max(1, math.ceil(proportional)))
        if metric.target_type == AVERAGE_VALUE:
            # proportional average, divided by number of replicas; can reach 0
            return int(math.ceil(ratio))
        if metric.target_type == UTILIZATION:
            # proportional percentage, multiplied by 100, cannot scale to zero
            return int(max(1, math.ceil(proportional * 100)))
        logger().error("Unexpected TargetType %s", metric.target_type)
        return replicas
