"""Solver sidecar: the control-plane / device-solver process split.

BASELINE.json's north star names the shape: the control plane "ships the
batch to a [control-plane]→gRPC→JAX sidecar" — the reference's analogous
process boundaries are the Prometheus HTTP hop (pkg/metrics/clients/
prometheus.go:35-55) and the scale-subresource RPC
(pkg/autoscaler/autoscaler.go:196-221). Running the solver out of process
keeps TPU ownership in exactly one place (one process holds the chip; N
control-plane replicas can share it) and makes the solver independently
restartable — the stateless-resume posture of SURVEY.md §5.

The wire contract is documented in proto/solver.proto; messages are a
self-describing array framing (codec.py) rather than generated protobuf
classes, because this environment has no grpc codegen plugin — the gRPC
transport, service/method names, and semantics match the proto exactly, so
swapping in generated stubs later changes no behavior.
"""

from karpenter_tpu.sidecar.client import SolverClient
from karpenter_tpu.sidecar.server import SolverServer

__all__ = ["SolverClient", "SolverServer"]
