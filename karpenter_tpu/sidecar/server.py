"""gRPC solver sidecar server: owns the TPU, serves Solve/Decide/Health.

The service/method names and message semantics are proto/solver.proto;
handlers are registered generically (no generated stubs, see
sidecar/__init__.py). Solve routes the pending-pods bin-pack through the
process-shared solve service (solver/service.py): concurrent Solve RPCs
from the gRPC thread pool coalesce into one batched device call, shapes
are bucketed through the shared compile cache, and a sick device path
degrades to the numpy backend instead of erroring every caller. Decide
runs the batched HPA decision kernel (ops/decision.decide_jit). Both are
stateless: all inputs arrive in the request, matching the reference's
checkpoint/resume posture (all durable state in the store; SURVEY.md §5).
"""

from __future__ import annotations

import json
import os
from concurrent import futures
from typing import Optional

import numpy as np

from karpenter_tpu.observability import solver_trace
from karpenter_tpu.sidecar import codec

SERVICE = "karpenter.solver.v1.Solver"

# tenant-scoped RPCs (docs/multitenancy.md): a multi-tenant control
# plane stamps its tenant id into this gRPC metadata key; the server
# attributes solver traffic per tenant on /metrics
# (karpenter_tenant_rpcs_total{name=<tenant>}).
TENANT_METADATA_KEY = "x-karpenter-tenant"


def _solve(request: bytes) -> bytes:
    from karpenter_tpu.ops.binpack import BinPackInputs
    from karpenter_tpu.solver import default_service

    # optional tensors (pod_weight) may be absent from the wire; the codec
    # fills dataclass defaults and rejects missing-required/extra tensors
    inputs, meta = codec.unpack_dataclass(BinPackInputs, request)
    buckets = int(meta.get("buckets", 32))
    backend = meta.get("backend", "auto")
    with solver_trace("sidecar.solve"):
        # the shared service owns device access: concurrent RPCs from the
        # gRPC worker pool coalesce into one dispatch, and outputs come
        # back as host numpy ready for the wire
        out = default_service().solve(
            inputs, buckets=buckets, backend=backend
        )
    return codec.pack_dataclass(out)


def _decide(request: bytes) -> bytes:
    import jax

    from karpenter_tpu.ops.decision import DecisionInputs, decide_jit

    inputs, _ = codec.unpack_dataclass(DecisionInputs, request)
    with solver_trace("sidecar.decide"):
        out = decide_jit(jax.device_put(inputs))
        jax.block_until_ready(out)
    return codec.pack_dataclass(out)


def _health(request: bytes) -> bytes:
    import jax

    return codec.pack(
        {"ok": np.asarray(True)},
        meta={
            "backend": jax.default_backend(),
            "device_count": jax.device_count(),
        },
    )


class SolverServer:
    """port=0 binds an ephemeral port; `port` holds the bound port after
    start()."""

    def __init__(self, port: int = 9090, host: str = "0.0.0.0",
                 max_workers: int = 4, registry=None):
        from karpenter_tpu.metrics.registry import default_registry

        self.host = host
        self.port = port
        self.max_workers = max_workers
        self._server = None
        registry = registry if registry is not None else default_registry()
        # per-tenant RPC attribution (docs/multitenancy.md): counted by
        # the tenant id the client stamped into TENANT_METADATA_KEY;
        # single-tenant clients (no metadata) count nothing. The label
        # value is CLIENT-SUPPLIED, so it is sanitized and the distinct
        # series are CAPPED — an adversarial or misconfigured fleet
        # stamping unbounded ids must not grow /metrics without bound;
        # past the cap, traffic counts under the "_overflow" series.
        self._c_tenant_rpcs = registry.register(
            "tenant", "rpcs_total", kind="counter"
        )
        self._tenant_labels: set = set()

    # distinct tenant label values one server will track; chosen well
    # above any sane tenant fleet per sidecar, far below scrape pain
    MAX_TENANT_SERIES = 1024

    def _tenant_label(self, value: str):
        """Sanitized, cardinality-capped label for a client-supplied
        tenant id: printable, bounded length, no label-breaking
        characters (the exposition escaper handles quoting, this bounds
        SIZE); ids beyond the series cap collapse to "_overflow"."""
        value = str(value)[:64]
        if not value or not value.isprintable():
            return None
        if value in self._tenant_labels:
            return value
        if len(self._tenant_labels) >= self.MAX_TENANT_SERIES:
            return "_overflow"
        self._tenant_labels.add(value)
        return value

    def _count_tenant(self, context) -> None:
        try:
            for key, value in context.invocation_metadata() or ():
                if key == TENANT_METADATA_KEY and value:
                    label = self._tenant_label(value)
                    if label is not None:
                        self._c_tenant_rpcs.inc(label, "-")
                    return
        except Exception:  # noqa: BLE001 — attribution must never fail an RPC
            pass

    def start(self) -> int:
        import grpc

        def wrap(fn):
            def handler(request: bytes, context) -> bytes:
                self._count_tenant(context)
                try:
                    return fn(request)
                except Exception as e:  # noqa: BLE001 — errors go to the
                    # client as INTERNAL with the message, not a dead channel
                    context.abort(
                        grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}"
                    )

            return grpc.unary_unary_rpc_method_handler(
                handler,
                request_deserializer=None,  # raw bytes both ways
                response_serializer=None,
            )

        handlers = grpc.method_handlers_generic_handler(
            SERVICE,
            {
                "Solve": wrap(_solve),
                "Decide": wrap(_decide),
                "Health": wrap(_health),
            },
        )
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self.max_workers)
        )
        self._server.add_generic_rpc_handlers((handlers,))
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        if self.port == 0:
            raise RuntimeError(f"could not bind {self.host}")
        self._server.start()
        return self.port

    def wait(self) -> None:
        self._server.wait_for_termination()

    def stop(self, grace: Optional[float] = 1.0) -> None:
        if self._server is not None:
            self._server.stop(grace).wait()
            self._server = None


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description="karpenter-tpu solver sidecar")
    ap.add_argument("--port", type=int, default=9090)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument(
        "--warmup-pods",
        type=int,
        default=0,
        help="pre-compile the bin-pack at this pod count before serving",
    )
    ap.add_argument(
        "--multihost",
        action="store_true",
        help="join a multi-host jax.distributed deployment before serving "
        "(topology from TPU pod metadata or JAX_COORDINATOR_ADDRESS/"
        "JAX_NUM_PROCESSES/JAX_PROCESS_ID; see parallel/multihost.py)",
    )
    ap.add_argument(
        "--compile-cache-dir",
        default=os.environ.get("KARPENTER_COMPILE_CACHE", ""),
        help="persistent XLA compilation cache directory (also env "
        "KARPENTER_COMPILE_CACHE): a restarted sidecar reloads compiled "
        "solver programs instead of paying the 20-40s TPU compile again; "
        "point it at an emptyDir/PVC in the pod spec. Empty = disabled.",
    )
    args = ap.parse_args(argv)

    from karpenter_tpu.utils.backend import configure_compile_cache

    configure_compile_cache(args.compile_cache_dir)

    joined = False
    if args.multihost:
        # the join must precede ANY backend touch (jax.distributed
        # refuses after XLA initializes), so it runs before the probe.
        # A configured-but-broken topology raises and kills the process
        # — correct: N independent solvers would double-solve the fleet.
        from karpenter_tpu.parallel.multihost import initialize_multihost

        joined = initialize_multihost()
        if not joined:
            import sys

            print(
                "multihost: no topology configured; serving single-host",
                file=sys.stderr,
            )

    if not joined:
        # the sidecar exists to own the TPU, but a hung accelerator
        # tunnel must degrade to CPU service (logged loudly), not a
        # frozen gRPC server. A JOINED multihost member never takes this
        # fallback: contributing CPU devices to a TPU fleet's global
        # device set (or silently leaving the fleet) corrupts the mesh —
        # a member whose accelerator is broken should crash and be
        # rescheduled, not limp
        from karpenter_tpu.utils.backend import ensure_usable_backend

        note = ensure_usable_backend()
        if note:
            import sys

            print(f"sidecar backend: {note}", file=sys.stderr)

    if args.warmup_pods:
        import jax

        from karpenter_tpu.ops.binpack import BinPackInputs, solve

        p = args.warmup_pods
        inputs = BinPackInputs(
            pod_requests=np.ones((p, 3), np.float32),
            pod_valid=np.ones((p,), bool),
            pod_intolerant=np.zeros((p, 64), bool),
            pod_required=np.zeros((p, 64), bool),
            group_allocatable=np.ones((300, 3), np.float32),
            group_taints=np.zeros((300, 64), bool),
            group_labels=np.ones((300, 64), bool),
        )
        jax.block_until_ready(solve(jax.device_put(inputs)))

    server = SolverServer(port=args.port, host=args.host)
    port = server.start()
    print(json.dumps({"serving": f"{args.host}:{port}", "service": SERVICE}))
    server.wait()


if __name__ == "__main__":
    main()
