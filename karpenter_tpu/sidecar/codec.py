"""Self-describing array framing for the solver sidecar wire format.

Layout: 8-byte little-endian header length, JSON header, then the raw
C-order little-endian array buffers concatenated in header order. The
header is a list of [name, dtype, shape] triples plus an optional "meta"
dict (backend info, error strings). Arrays round-trip zero-copy on decode
(numpy views over the message buffer).

This is the byte-level stand-in for proto/solver.proto's TensorBatch (see
sidecar/__init__.py for why no generated stubs).
"""

from __future__ import annotations

import dataclasses
import json
import struct
from typing import Any, Dict, Optional, Tuple

import numpy as np

_LEN = struct.Struct("<Q")


def pack(
    arrays: Dict[str, np.ndarray], meta: Optional[Dict[str, Any]] = None
) -> bytes:
    entries = []
    buffers = []
    for name, arr in arrays.items():
        arr = np.asarray(arr)
        shape = list(arr.shape)  # before ascontiguousarray: it promotes 0-d to 1-d
        arr = np.ascontiguousarray(arr)
        if arr.dtype.byteorder == ">":  # wire format is little-endian
            arr = arr.astype(arr.dtype.newbyteorder("<"))
        entries.append([name, arr.dtype.str, shape])
        buffers.append(arr.tobytes())
    header = json.dumps({"tensors": entries, "meta": meta or {}}).encode()
    return b"".join([_LEN.pack(len(header)), header] + buffers)


def unpack(data: bytes) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    (header_len,) = _LEN.unpack_from(data, 0)
    header = json.loads(data[8 : 8 + header_len])
    offset = 8 + header_len
    arrays: Dict[str, np.ndarray] = {}
    for name, dtype_str, shape in header["tensors"]:
        dtype = np.dtype(dtype_str)
        count = int(np.prod(shape)) if shape else 1
        nbytes = dtype.itemsize * count
        arrays[name] = np.frombuffer(
            data, dtype=dtype, count=count, offset=offset
        ).reshape(tuple(shape))
        offset += nbytes
    return arrays, header.get("meta", {})


def pack_dataclass(obj, meta: Optional[Dict[str, Any]] = None) -> bytes:
    """Any registered array-dataclass (BinPackInputs, DecisionInputs, ...)
    -> wire bytes, one tensor per field. None-valued optional fields (e.g.
    BinPackInputs.pod_weight) are simply absent from the wire."""
    arrays = {
        f.name: np.asarray(getattr(obj, f.name))
        for f in dataclasses.fields(obj)
        if getattr(obj, f.name) is not None
    }
    return pack(arrays, meta)


def unpack_dataclass(cls, data: bytes):
    """Wire bytes -> cls hydrated with numpy arrays. Field-name match is
    exact for required fields; fields with a dataclass default may be
    absent (they take the default — how optional tensors like pod_weight
    stay wire-compatible across versions). Extra tensors are an error,
    same strictness as the YAML codec."""
    arrays, meta = unpack(data)
    required = {
        f.name
        for f in dataclasses.fields(cls)
        if f.default is dataclasses.MISSING
        and f.default_factory is dataclasses.MISSING
    }
    names = {f.name for f in dataclasses.fields(cls)}
    if not (required <= set(arrays) <= names):
        raise ValueError(
            f"tensor set mismatch for {cls.__name__}: "
            f"got {sorted(arrays)}, want {sorted(required)} <= got <= "
            f"{sorted(names)}"
        )
    return cls(**arrays), meta
