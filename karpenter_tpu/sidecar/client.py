"""gRPC solver client: the control-plane side of the sidecar split.

Drop-in for the in-process solver at the Algorithm seam (the reference's
pluggable-Algorithm boundary, pkg/autoscaler/algorithms/algorithm.go:24-40):
`SolverClient.solve` has the same (inputs, buckets) -> BinPackOutputs
contract as ops/binpack.solve, so metrics/producers/pendingcapacity.py
routes through it when the runtime is configured with a solver URI.

Resilience posture (docs/resilience.md): every RPC carries a DEADLINE
(`timeout_seconds`, default 30 s — never an unbounded wait on a dead
server) and transient failures (UNAVAILABLE — server restarting, channel
reconnecting — and DEADLINE_EXCEEDED) get ONE retry after a short
jittered sleep, decorrelating concurrent callers hitting the same
restart. Anything still failing surfaces to the caller, where the solve
service's numpy fallback (solver/service.py) takes over — the client
never retries indefinitely, because the layer above already owns
degradation.
"""

from __future__ import annotations

import random
import time as _time
from typing import Any, Dict, Optional, Tuple

from karpenter_tpu.faults import FaultInjected, inject
from karpenter_tpu.sidecar import codec
from karpenter_tpu.sidecar.server import SERVICE, TENANT_METADATA_KEY
from karpenter_tpu.utils.log import logger

DEFAULT_TIMEOUT_S = 30.0
# one retry, after uniform(0, retry_jitter_s) — enough to ride out a
# sidecar restart without amplifying load against a genuinely dead one
DEFAULT_RETRIES = 1
DEFAULT_RETRY_JITTER_S = 0.25


def _retryable_rpc_error(err: BaseException) -> bool:
    import grpc

    if isinstance(err, FaultInjected):
        return err.retryable  # injected transport faults ride the retry
    if not isinstance(err, grpc.RpcError):
        return False
    code = err.code() if callable(getattr(err, "code", None)) else None
    return code in (
        grpc.StatusCode.UNAVAILABLE,
        grpc.StatusCode.DEADLINE_EXCEEDED,
    )


class SolverClient:
    def __init__(
        self,
        target: str,
        timeout_seconds: float = DEFAULT_TIMEOUT_S,
        retries: int = DEFAULT_RETRIES,
        retry_jitter_s: float = DEFAULT_RETRY_JITTER_S,
        seed: int = 0,
        tenant: Optional[str] = None,
    ):
        import grpc

        self.target = target
        self.timeout = (
            timeout_seconds if timeout_seconds else DEFAULT_TIMEOUT_S
        )
        self.retries = retries
        self.retry_jitter_s = retry_jitter_s
        self._rng = random.Random(seed)
        # tenant-scoped RPCs (docs/multitenancy.md): the tenant id rides
        # every call as gRPC metadata, so a multi-tenant sidecar can
        # attribute solver traffic per tenant (server-side the label is
        # sanitized and series-capped — the value crosses a trust
        # boundary). None = single-tenant wire, byte-identical to
        # previous releases.
        self.tenant = tenant
        self._metadata = (
            ((TENANT_METADATA_KEY, tenant),) if tenant else None
        )
        self._channel = grpc.insecure_channel(target)
        self._solve = self._channel.unary_unary(f"/{SERVICE}/Solve")
        self._decide = self._channel.unary_unary(f"/{SERVICE}/Decide")
        self._health = self._channel.unary_unary(f"/{SERVICE}/Health")

    def _call(self, rpc, request, timeout: Optional[float] = None):
        """One RPC under the default deadline, with one jittered retry on
        transient transport failure. `sidecar.rpc` is the fault-injection
        point (faults/registry.py)."""
        deadline = timeout if timeout else self.timeout
        attempts = 1 + max(0, self.retries)
        # the tenant metadata kwarg is only passed when a tenant is
        # configured: the single-tenant call signature stays exactly
        # rpc(request, timeout=...) — wire- and test-double-compatible
        kwargs = (
            {"metadata": self._metadata} if self._metadata else {}
        )
        for attempt in range(attempts):
            try:
                inject("sidecar.rpc")
                return rpc(request, timeout=deadline, **kwargs)
            except Exception as e:  # noqa: BLE001 — classified below
                if attempt + 1 >= attempts or not _retryable_rpc_error(e):
                    raise
                delay = self._rng.uniform(0.0, self.retry_jitter_s)
                logger().warning(
                    "sidecar RPC failed (%s); retrying once in %.3fs",
                    e, delay,
                )
                _time.sleep(delay)

    def solve(self, inputs, buckets: int = 32, backend: str = "auto"):
        """BinPackInputs -> BinPackOutputs via the sidecar (numpy-backed)."""
        from karpenter_tpu.ops.binpack import BinPackOutputs

        request = codec.pack_dataclass(
            inputs, meta={"buckets": buckets, "backend": backend}
        )
        response = self._call(self._solve, request)
        out, _ = codec.unpack_dataclass(BinPackOutputs, response)
        return out

    def decide(self, inputs):
        """DecisionInputs -> DecisionOutputs via the sidecar."""
        from karpenter_tpu.ops.decision import DecisionOutputs

        response = self._call(self._decide, codec.pack_dataclass(inputs))
        out, _ = codec.unpack_dataclass(DecisionOutputs, response)
        return out

    def health(self) -> Tuple[bool, Dict[str, Any]]:
        arrays, meta = codec.unpack(
            self._call(self._health, codec.pack({}))
        )
        return bool(arrays["ok"]), meta

    def close(self) -> None:
        self._channel.close()

    def __enter__(self) -> "SolverClient":
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        self.close()
        return None
