"""gRPC solver client: the control-plane side of the sidecar split.

Drop-in for the in-process solver at the Algorithm seam (the reference's
pluggable-Algorithm boundary, pkg/autoscaler/algorithms/algorithm.go:24-40):
`SolverClient.solve` has the same (inputs, buckets) -> BinPackOutputs
contract as ops/binpack.solve, so metrics/producers/pendingcapacity.py
routes through it when the runtime is configured with a solver URI.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from karpenter_tpu.sidecar import codec
from karpenter_tpu.sidecar.server import SERVICE


class SolverClient:
    def __init__(self, target: str, timeout_seconds: float = 30.0):
        import grpc

        self.target = target
        self.timeout = timeout_seconds
        self._channel = grpc.insecure_channel(target)
        self._solve = self._channel.unary_unary(f"/{SERVICE}/Solve")
        self._decide = self._channel.unary_unary(f"/{SERVICE}/Decide")
        self._health = self._channel.unary_unary(f"/{SERVICE}/Health")

    def solve(self, inputs, buckets: int = 32, backend: str = "auto"):
        """BinPackInputs -> BinPackOutputs via the sidecar (numpy-backed)."""
        from karpenter_tpu.ops.binpack import BinPackOutputs

        request = codec.pack_dataclass(
            inputs, meta={"buckets": buckets, "backend": backend}
        )
        response = self._solve(request, timeout=self.timeout)
        out, _ = codec.unpack_dataclass(BinPackOutputs, response)
        return out

    def decide(self, inputs):
        """DecisionInputs -> DecisionOutputs via the sidecar."""
        from karpenter_tpu.ops.decision import DecisionOutputs

        response = self._decide(
            codec.pack_dataclass(inputs), timeout=self.timeout
        )
        out, _ = codec.unpack_dataclass(DecisionOutputs, response)
        return out

    def health(self) -> Tuple[bool, Dict[str, Any]]:
        arrays, meta = codec.unpack(
            self._health(codec.pack({}), timeout=self.timeout)
        )
        return bool(arrays["ok"]), meta

    def close(self) -> None:
        self._channel.close()

    def __enter__(self) -> "SolverClient":
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        self.close()
        return None
