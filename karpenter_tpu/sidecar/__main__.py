"""`python -m karpenter_tpu.sidecar` — run the solver sidecar."""

from karpenter_tpu.sidecar.server import main

main()
