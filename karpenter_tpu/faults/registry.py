"""Seeded, deterministic fault-injection registry.

The reference survives provider flakiness with an error taxonomy
(pkg/controllers/errors.go) but never EXERCISES those paths: nothing in
its test suite injects a throttled ASG mid-reconcile or a hung solver.
This registry makes failure a first-class test input. Production code is
instrumented with named injection points (`inject("cloud.set_replicas")`
— one global read + None check when no registry is installed, so the
hot path pays nothing), and a chaos suite installs plans against them:

  * error   — raise a configured exception (RetryableError by default,
              so the controller taxonomy is exercised end to end)
  * latency — sleep before proceeding (slow backend)
  * hang    — block until the registry releases (dead backend; the
              solver watchdog is expected to trip first)
  * flaky   — fail the first N matching attempts, then pass forever
              (times=N on an error plan)
  * crash   — raise ProcessCrash, a BaseException the tick's blanket
              `except Exception` handlers can NOT swallow: the injected
              analog of SIGKILL. The kill-and-restart chaos suite
              (tests/test_restart_chaos.py) catches it at harness level,
              abandons the incarnation, and reboots from the journal.

Determinism: every plan owns its own `random.Random` stream seeded from
(registry seed, plan index), so a plan's fire/skip sequence depends only
on its own attempt order — not on interleaving with other points — and
a chaos run replays exactly under a fixed seed.

Points instrumented across the stack (docs/resilience.md):

  solver.dispatch     device path of the shared solve service
  forecast.predict    device path of the batched forecast seam
  preempt.plan        device path of the eviction-planning seam
  cost.score          device path of the multi-objective cost/SLO
                      refinement (SolverService.cost) — failures make
                      the tick COST-BLIND, not mirror-served
                      (docs/cost.md degradation contract)
  fused.tick          the fused steady-state megakernel
                      (SolverService.fused_tick) — failures fall back
                      to the chained per-stage path, then numpy, and
                      feed the FSM (docs/solver-service.md "Fused
                      tick")
  poolgroup.solve     device path of the joint pool-group allocation
                      (SolverService.poolgroup) — failures degrade to
                      INDEPENDENT per-pool ladders for the tick
                      (ratios advisory, never-block) and feed the FSM
                      (docs/poolgroups.md)
  encoder.encode      snapshot -> solver-operand encode
  cloud.get_replicas  provider replica observation
  cloud.set_replicas  provider actuation
  metrics.query       metrics-client instant queries
  sidecar.rpc         gRPC solver client calls
  store.patch_status  controller status writes
  process.crash.*     kill points for the restart chaos suite — target a
                      site exactly, or the whole family via the glob:
                      .drain (consolidation actuation), .evict
                      (preemption mid-eviction-batch), .journal (the
                      recovery StateJournal, which flushes a REAL torn
                      half-record before dying)
  lease.acquire.*     lease acquisition CAS, per elector identity —
  lease.renew.*       error plans here are a partitioned/deposed
                      replica that cannot reach the lease store
                      (replication.chaos partition_plans builds the
                      pair)
  replica.crash.*     kill point at the top of a replica's tick
                      (ReplicatedControlPlane.on_tick) — a crash plan
                      here is that replica dying between lease rounds
                      (replication.chaos crash_plan; the failover
                      world's leader kill)

Registries also export `karpenter_faults_{attempts,injected}_total`
{name=<point>} when given a GaugeRegistry, so a chaos run's injection
volume is visible on the same /metrics surface as the resilience
counters it provokes.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time as _time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karpenter_tpu.controllers.errors import RetryableError

SUBSYSTEM = "faults"

MODES = ("error", "latency", "hang", "flaky", "crash")


class FaultInjected(RetryableError):
    """The default injected error: retryable, coded, and typed so tests
    can tell an injected failure from an organic one."""


class ProcessCrash(BaseException):
    """The injected SIGKILL analog (mode "crash"). Deliberately NOT an
    Exception: the reconcile engine's blanket `except Exception` must
    not be able to absorb a simulated process death — it propagates out
    of the tick to the test harness, which abandons the incarnation and
    restarts from the journal."""


@dataclass
class FaultPlan:
    """One fault plan against one injection point (or a `prefix.*` glob).

    `times` bounds TOTAL firings (None = unlimited); mode "flaky" is an
    error plan whose firings are the FIRST `times` matching attempts —
    after N failures the point succeeds forever (the classic transient
    outage shape).
    """

    point: str
    mode: str = "error"
    probability: float = 1.0
    times: Optional[int] = None
    latency_s: float = 0.0
    retryable: bool = True
    code: str = "FaultInjected"
    message: str = ""
    # runtime state (owned by the registry)
    attempts: int = 0
    fired: int = 0
    _rng: random.Random = field(default_factory=random.Random, repr=False)

    def _exhausted(self) -> bool:
        return self.times is not None and self.fired >= self.times

    def _decide(self) -> bool:
        """Whether this attempt fires. Called under the registry lock;
        the plan-local RNG stream makes the sequence a pure function of
        this plan's attempt order."""
        if self._exhausted():
            return False
        if self.mode == "flaky":
            return True  # fail-first-N is deterministic by definition
        if self.probability >= 1.0:
            return True
        return self._rng.random() < self.probability

    def matches(self, point: str) -> bool:
        if self.point.endswith("*"):
            return point.startswith(self.point[:-1])
        return self.point == point


class FaultRegistry:
    """Installable set of fault plans + per-point counters.

    Use as a context manager (`with FaultRegistry(seed=7) as reg: ...`)
    or via faults.install()/uninstall(). Exiting releases any in-flight
    hangs so a failing test never wedges the suite.
    """

    def __init__(self, seed: int = 0, registry=None):
        self.seed = seed
        self._plans: List[FaultPlan] = []
        self._lock = threading.Lock()
        self._release = threading.Event()
        self.attempts: Dict[str, int] = {}
        self.injected: Dict[str, int] = {}
        self._c_attempts = self._c_injected = None
        if registry is not None:
            self._c_attempts = registry.register(
                SUBSYSTEM, "attempts_total", kind="counter"
            )
            self._c_injected = registry.register(
                SUBSYSTEM, "injected_total", kind="counter"
            )

    # -- plan building ----------------------------------------------------

    def plan(self, point: str, **kwargs) -> FaultPlan:
        """Add a plan; its RNG stream is seeded from (registry seed,
        plan index) so runs replay deterministically."""
        plan = FaultPlan(point=point, **kwargs)
        if plan.mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {plan.mode!r}")
        with self._lock:
            # int-combined (seed, plan-index) stream id: tuple seeding is
            # deprecated, and the plan index keeps sibling plans'
            # sequences independent under one registry seed
            plan._rng = random.Random(
                (self.seed * 1_000_003) ^ len(self._plans)
            )
            self._plans.append(plan)
        return plan

    def clear(self) -> None:
        """Drop all plans and release any in-flight hangs (the 'faults
        cleared' transition of a chaos scenario)."""
        with self._lock:
            self._plans = []
        self._release.set()
        self._release = threading.Event()

    def plans(self) -> List[FaultPlan]:
        with self._lock:
            return list(self._plans)

    # -- firing -----------------------------------------------------------

    def fire(self, point: str) -> None:
        """Evaluate all plans against one attempt at `point` — called
        from inject() on the instrumented code path."""
        with self._lock:
            self.attempts[point] = self.attempts.get(point, 0) + 1
            if self._c_attempts is not None:
                self._c_attempts.inc(point, "-")
            plan = None
            for candidate in self._plans:
                if not candidate.matches(point):
                    continue
                # EVERY matching plan records the attempt (and consumes
                # its RNG stream) so a plan's fire/skip sequence is a
                # pure function of the point's attempt order, not of
                # which other plan fired first
                candidate.attempts += 1
                fires = candidate._decide()
                if plan is None and fires:
                    plan = candidate
            if plan is None:
                return
            plan.fired += 1
            self.injected[point] = self.injected.get(point, 0) + 1
            if self._c_injected is not None:
                self._c_injected.inc(point, "-")
            release = self._release
        # flight-recorder breadcrumb BEFORE executing (a crash plan
        # raises out of _execute): the chaos timeline shows what was
        # injected where, backlinked to the reconcile trace it hit
        from karpenter_tpu.observability import default_flight_recorder

        default_flight_recorder().record(
            "fault_injected", point=point, mode=plan.mode,
        )
        self._execute(plan, point, release)

    def _execute(
        self, plan: FaultPlan, point: str, release: threading.Event
    ) -> None:
        """Carry out a fired plan OUTSIDE the lock (latency/hang must
        not serialize unrelated points)."""
        if plan.mode == "latency":
            _time.sleep(plan.latency_s)
            return
        if plan.mode == "crash":
            raise ProcessCrash(f"injected process crash at {point}")
        if plan.mode == "hang":
            # block until the registry releases (clear()/uninstall/exit),
            # then surface as a retryable error: the stalled caller's
            # frame unwinds through the same degradation path a real
            # backend recovery would, instead of resuming as if nothing
            # happened with state the watchdog already reassigned.
            release.wait()
            raise FaultInjected(
                f"hang released at {point}", code="FaultHangReleased"
            )
        raise FaultInjected(
            plan.message or f"injected fault at {point}",
            code=plan.code,
            retryable=plan.retryable,
        )

    def release_hangs(self) -> None:
        self._release.set()
        self._release = threading.Event()

    def __enter__(self) -> "FaultRegistry":
        install(self)
        return self

    def __exit__(self, *exc) -> None:
        uninstall(self)


# -- module-level installation ------------------------------------------------

_active: Optional[FaultRegistry] = None


def install(registry: FaultRegistry) -> FaultRegistry:
    global _active
    _active = registry
    return registry


def uninstall(registry: Optional[FaultRegistry] = None) -> None:
    """Deactivate (the given registry, or whatever is active) and release
    its hangs so no injected stall outlives the scenario."""
    global _active
    target = registry or _active
    _active = None
    if target is not None:
        target.release_hangs()


def active() -> Optional[FaultRegistry]:
    return _active


@contextlib.contextmanager
def injected_faults(seed: int = 0, registry=None):
    """`with injected_faults(seed=7) as reg:` — scoped install."""
    reg = FaultRegistry(seed=seed, registry=registry)
    install(reg)
    try:
        yield reg
    finally:
        uninstall(reg)


def inject(point: str) -> None:
    """The injection point production code calls. No registry installed
    (the production default) is one global read + None check."""
    registry = _active
    if registry is not None:
        registry.fire(point)
