"""Fault injection (registry.py) — the testable-failure subsystem.

Public surface: `inject(point)` for instrumented production code paths,
`FaultRegistry`/`FaultPlan` + install/uninstall/injected_faults for
chaos suites. See docs/resilience.md for the point catalog and plan
format.
"""

from karpenter_tpu.faults.registry import (
    FaultInjected,
    FaultPlan,
    FaultRegistry,
    ProcessCrash,
    active,
    inject,
    injected_faults,
    install,
    uninstall,
)

__all__ = [
    "FaultInjected",
    "FaultPlan",
    "FaultRegistry",
    "ProcessCrash",
    "active",
    "inject",
    "injected_faults",
    "install",
    "uninstall",
]
