"""PoolGroups: coordinated joint allocation for interdependent pools.

The declarative surface is api/poolgroup.py (the PoolGroup CRD), the
batched joint kernel is ops/poolgroup.py, the service seam is
SolverService.poolgroup, and this package's PoolGroupEngine is the
host-side orchestration riding the BatchAutoscaler tick — see
docs/poolgroups.md.
"""

from karpenter_tpu.poolgroups.engine import PoolGroupEngine

__all__ = ["PoolGroupEngine"]
