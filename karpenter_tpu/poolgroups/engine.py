"""PoolGroupEngine: host-side orchestration of the joint allocation.

Rides the BatchAutoscaler's per-tick pass AFTER the cost refinement
(docs/poolgroups.md): plan() resolves every PoolGroup in the store
against the live rows — each member name must resolve to exactly one
live, non-custom HorizontalAutoscaler in the group's namespace, and a
group with ANY unresolvable member sits the tick out whole (a joint
allocation of half a group is worse than none). The resolved member
rows are EXCLUDED from the CostEngine's independent per-pool ladders
(the `exclude` seam) and refined here instead: one PoolGroupInputs
matrix for the whole fleet's groups, submitted as a SINGLE batched
dispatch through the `poolgroup_fn` seam (SolverService.poolgroup in
production: backend-health FSM, `poolgroup.solve` fault point, numpy
mirror as the requested-CPU backend, the enforce=False independent
ladder as the degraded rung). That is the dispatch collapse the
subsystem exists for: G groups x P pools ride ONE program instead of
G x P independent cost rungs.

Contracts (the CostEngine discipline, one rank up):

  * NEVER-BLOCK — refine() never raises. Any failure (a poisoned spec,
    a kernel fault past the service ladder) logs, counts
    karpenter_poolgroup_degraded_total per group, and returns the base
    outputs untouched: the tick proceeds UNCOORDINATED, exactly as if
    the groups didn't exist.
  * ZERO-OVERHEAD OPT-OUT — a fleet with no PoolGroup objects returns
    plan() None after one store list; the autoscaler wire is then
    byte-identical to the pre-subsystem plane (pinned in
    tests/test_poolgroup.py).
  * BEHAVIOR-BOUNDED — the joint ladder is clamped per pool to the
    decide kernel's movement bounds intersected with the member's own
    spec tightening; coordination can never outrun a pool's declared
    scaleUp/scaleDown behavior.
  * WARM-POOL SIGNAL — member pools contribute one-sigma headroom
    exactly like cost rows do; headroom() is an additional source the
    runtime maxes into WarmPoolEngine's.

Metrics: karpenter_poolgroup_{expected_hourly,ratio_ok} gauges per
group and karpenter_poolgroup_{coordinated,degraded}_total counters;
series retire when a group is deleted or stops resolving.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.api import poolgroup as api_pg
from karpenter_tpu.api.poolgroup import PoolGroup
from karpenter_tpu.cost.model import CostModel
from karpenter_tpu.ops import decision as D
from karpenter_tpu.ops import poolgroup as PGK
from karpenter_tpu.utils.log import logger

SUBSYSTEM = "poolgroup"

# the api package re-declares the kernel's static limits so it never
# imports jax — drift would mean admission admits what the kernel
# cannot represent, so it is a hard import-time error here
assert api_pg.MAX_POOLS == PGK.MAX_POOLS, "api/ops MAX_POOLS drift"
assert api_pg.RATIO_BOUND == PGK.RATIO_BOUND, "api/ops RATIO_BOUND drift"
assert api_pg.RATIO_SLOTS == PGK.RATIO_SLOTS, "api/ops RATIO_SLOTS drift"

# group-axis compile buckets: padded like every other fleet axis so a
# steady fleet never recompiles when one group comes or goes
_GROUP_BUCKET_FLOOR = 4


def pad_group_count(groups: int) -> int:
    bucket = _GROUP_BUCKET_FLOOR
    while bucket < groups:
        bucket *= 2
    return bucket


@dataclass
class PoolGroupPlan:
    """One tick's resolved membership: the groups that participate and
    the fleet-row index of every member pool (position-aligned with
    group.spec.pools)."""

    groups: List[Tuple[PoolGroup, List[int]]]
    # union of member row indices — the CostEngine exclusion set
    grouped: frozenset


class PoolGroupEngine:
    """One per runtime (see module docstring).

    `poolgroup_fn` is the device seam: any (PoolGroupInputs) ->
    PoolGroupOutputs callable — SolverService.poolgroup in production
    (runtime.py wiring), the jitted kernel directly when standalone."""

    def __init__(
        self,
        store=None,
        poolgroup_fn=None,
        model: Optional[CostModel] = None,
        forecaster=None,
        registry=None,
    ):
        self.store = store
        self.poolgroup_fn = (
            poolgroup_fn if poolgroup_fn is not None else PGK.poolgroup_jit
        )
        self.model = model if model is not None else CostModel()
        self.forecaster = forecaster
        # (ns, ha-name) -> ((ns, scale-target name), one-sigma headroom
        # replicas) — the CostEngine contribution shape, so the warm
        # pool's max-over-sources works unchanged
        self._contrib: Dict[
            Tuple[str, str], Tuple[Tuple[str, str], int]
        ] = {}
        # (ns, group-name) keys currently holding gauge series — the
        # retirement diff set (group deleted / stopped resolving)
        self._live: set = set()
        self._g_hourly = self._g_ratio = None
        self._c_coordinated = self._c_degraded = None
        if registry is not None:
            self._g_hourly = registry.register(SUBSYSTEM, "expected_hourly")
            self._g_ratio = registry.register(SUBSYSTEM, "ratio_ok")
            self._c_coordinated = registry.register(
                SUBSYSTEM, "coordinated_total", kind="counter"
            )
            self._c_degraded = registry.register(
                SUBSYSTEM, "degraded_total", kind="counter"
            )

    # -- warm-pool face ----------------------------------------------------

    def headroom(self, namespace: str, name: str) -> int:
        """One-sigma demand replicas beyond the chosen desired, maxed
        over the member pools targeting this group — an additional
        WarmPoolEngine source (runtime maxes it with the cost
        engine's)."""
        key = (namespace, name)
        return max(
            (h for group, h in self._contrib.values() if group == key),
            default=0,
        )

    def prune(self, namespace: str, name: str) -> None:
        """Forget a deleted PoolGroup immediately (controller/delete
        hooks): its gauge series AND its members' headroom
        contributions — without this a deleted group would hold
        risk-sized warm capacity until the next refine pass diffs it
        away."""
        self._retire((namespace, name))

    def _retire(self, key: Tuple[str, str]) -> None:
        self._live.discard(key)
        ns, name = key
        if self._g_hourly is not None:
            self._g_hourly.remove(name, ns)
            self._g_ratio.remove(name, ns)

    def _sync_gauges(self, current: set) -> None:
        """Retire series for every group that held gauges last pass but
        does not participate now (deleted, invalid, or unresolvable)."""
        for key in list(self._live - current):
            self._retire(key)

    # -- membership resolution --------------------------------------------

    def plan(self, rows: List) -> Optional[PoolGroupPlan]:
        """Resolve the fleet's PoolGroups against this tick's live rows.
        Returns None when nothing participates (the zero-overhead
        opt-out: gauges of previously-live groups still retire). Never
        raises."""
        if self.store is None:
            return None
        try:
            groups = self.store.list(PoolGroup.KIND)
        except Exception:  # noqa: BLE001 — never-block contract
            groups = []
        if not groups:
            self._sync_gauges(set())
            if self._contrib:
                self._contrib.clear()
            return None
        index: Dict[Tuple[str, str], int] = {}
        for i, row in enumerate(rows):
            if getattr(row, "custom", False):
                continue  # a custom Algorithm owns this row's counts
            index[(row.ha.metadata.namespace, row.ha.metadata.name)] = i
        resolved: List[Tuple[PoolGroup, List[int]]] = []
        claimed: set = set()
        current: set = set()
        for group in groups:
            ns = group.metadata.namespace
            try:
                group.validate()
                idxs = [
                    index[(ns, member.name)]
                    for member in group.spec.pools
                ]
            except Exception as error:  # noqa: BLE001 — skip whole group
                logger().warning(
                    "pool group %s/%s sits this tick out (%s: %s)",
                    ns, group.metadata.name,
                    type(error).__name__, error,
                )
                continue
            if claimed & set(idxs):
                # an HA can belong to ONE group per tick; first listed
                # group wins, later claimants scale uncoordinated
                logger().warning(
                    "pool group %s/%s overlaps an earlier group's "
                    "members; it sits this tick out",
                    ns, group.metadata.name,
                )
                continue
            claimed |= set(idxs)
            resolved.append((group, idxs))
            current.add((ns, group.metadata.name))
        self._sync_gauges(current)
        if not resolved:
            self._contrib.clear()
            return None
        # drop contributions of HAs that left every group (the cost
        # engine's retire posture, keyed by membership instead of spec)
        member_keys = {
            (g.metadata.namespace, g.spec.pools[p].name)
            for g, _ in resolved
            for p in range(len(g.spec.pools))
        }
        for key in list(self._contrib):
            if key not in member_keys:
                self._contrib.pop(key, None)
        return PoolGroupPlan(
            groups=resolved,
            grouped=frozenset(claimed),
        )

    # -- the per-tick pass -------------------------------------------------

    def refine(
        self, rows: List, plan: PoolGroupPlan, outputs: D.DecisionOutputs
    ) -> D.DecisionOutputs:
        """The BatchAutoscaler's post-cost call: ONE batched joint
        dispatch for every group, desired counts overlaid at the member
        rows. Returns `outputs` untouched on any failure (never-block:
        the tick proceeds uncoordinated)."""
        try:
            inputs = self._build_inputs(rows, plan, outputs)
            out = self.poolgroup_fn(inputs)
            return self._apply(rows, plan, outputs, out)
        except Exception as error:  # noqa: BLE001 — never-block contract
            logger().warning(
                "joint pool-group allocation failed (%s: %s); this tick "
                "scales uncoordinated", type(error).__name__, error,
            )
            self._count_degraded(plan)
            return outputs

    def fused_operands(self, rows: List, plan: PoolGroupPlan, n: int, m: int):
        """Host half of the fused tick's poolgroup stage
        (ops/fusedtick.py PoolGroupOperands): spec bounds, pricing, and
        ratio operands assemble as in _build_inputs, but the base
        desired + movement clamps and the demand-distribution overlay
        move IN-DEVICE (gathered from the decide stage's fresh outputs
        at each pool's member_row). Returns the operand dataclass or
        None on failure (the uncoordinated posture, already counted)."""
        try:
            return self._fused_operand_struct(rows, plan, n, m)
        except Exception as error:  # noqa: BLE001 — never-block contract
            logger().warning(
                "pool-group operand assembly failed (%s: %s); this tick "
                "scales uncoordinated", type(error).__name__, error,
            )
            self._count_degraded(plan)
            return None

    def fused_commit(
        self, rows: List, plan: PoolGroupPlan,
        outputs: D.DecisionOutputs, out: PGK.PoolGroupOutputs,
    ) -> D.DecisionOutputs:
        """Bookkeeping for a fused tick's poolgroup stage: exactly
        refine()'s post-dispatch half, given the PoolGroupOutputs the
        fused program returned. Same never-block posture."""
        try:
            return self._apply(rows, plan, outputs, out)
        except Exception as error:  # noqa: BLE001 — never-block contract
            logger().warning(
                "joint pool-group allocation failed (%s: %s); this tick "
                "scales uncoordinated", type(error).__name__, error,
            )
            self._count_degraded(plan)
            return outputs

    def _count_degraded(self, plan: PoolGroupPlan) -> None:
        if self._c_degraded is None:
            return
        for group, _ in plan.groups:
            self._c_degraded.inc(
                group.metadata.name, group.metadata.namespace
            )

    # -- operand assembly --------------------------------------------------

    @staticmethod
    def _member_bounds(ha, member) -> Tuple[int, int]:
        """The member's effective spec bounds: the HA's own [min, max]
        TIGHTENED by the member's optional overrides (they can never
        widen); an empty intersection pins max = min — the HA's floor
        outranks the group's preference."""
        lo = ha.spec.min_replicas
        hi = ha.spec.max_replicas
        if member.min_replicas is not None:
            lo = max(lo, member.min_replicas)
        if member.max_replicas is not None:
            hi = min(hi, member.max_replicas)
        if hi < lo:
            hi = lo
        return lo, hi

    def _unit_cost(self, ha) -> float:
        """Hourly cost per replica of this pool's scale target (the
        CostEngine pricing path: annotations/tier through the
        CostModel; unresolvable targets price the model default)."""
        target = None
        ref = ha.spec.scale_target_ref
        if self.store is not None and ref.kind and ref.name:
            try:
                target = self.store.try_get(
                    ref.kind, ha.metadata.namespace, ref.name
                )
            except Exception:  # noqa: BLE001 — unknown kinds price default
                target = None
        return self.model.unit_cost(target)

    def _demand(self, row, j: int, observed: float):
        """(mu, sigma, valid) for one metric — the CostEngine's demand
        selection verbatim: forecast distribution when available
        (monotone-up max(observed, point)), else observed with sigma
        0."""
        if not math.isfinite(observed):
            return 0.0, 0.0, False
        mu, sigma = observed, 0.0
        if self.forecaster is not None:
            ns = row.ha.metadata.namespace
            name = row.ha.metadata.name
            dist = self.forecaster.distribution(ns, name, j)
            if dist is not None:
                point, sigma2 = dist
                if math.isfinite(point):
                    mu = max(observed, point)
                if math.isfinite(sigma2) and sigma2 > 0:
                    sigma = math.sqrt(sigma2)
        return mu, sigma, True

    @staticmethod
    def _target_for(row, slo, j: int) -> float:
        """Per-replica capacity for metric j: the SLO's per-metric
        override, else the metric spec's own target value — pools whose
        HA declares no SLO still carry demand (weight 0 keeps risk out
        of their score; headroom and ratios still see real demand)."""
        per_replica = 0.0
        if slo is not None:
            per_replica = slo.target_for(j) or 0.0
        if not per_replica:
            _spec, target, _observed = row.observed[j]
            per_replica = target.target_value() or 0.0
        return per_replica

    def _pool_scalars(self, group, idxs, rows, g, arrays) -> None:
        """Fill one group's per-pool scalar operands (shared between the
        standalone and fused assemblies)."""
        for p, i in enumerate(idxs):
            row = rows[i]
            member = group.spec.pools[p]
            slo = getattr(row.ha.spec.behavior, "slo", None)
            arrays["unit_cost"][g, p] = self._unit_cost(row.ha)
            arrays["tier_penalty"][g, p] = member.tier_penalty
            arrays["pool_valid"][g, p] = True
            if slo is not None:
                arrays["slo_weight"][g, p] = slo.violation_cost_weight
                arrays["max_hourly_cost"][g, p] = slo.max_hourly_cost

    def _ratio_operands(self, group, g, arrays) -> None:
        for r, ratio in enumerate(group.spec.ratios[: PGK.RATIO_SLOTS]):
            arrays["ratio_a"][g, r] = group.member_index(ratio.numerator)
            arrays["ratio_b"][g, r] = group.member_index(ratio.denominator)
            arrays["ratio_min_num"][g, r] = ratio.min_numerator
            arrays["ratio_min_den"][g, r] = ratio.min_denominator
            arrays["ratio_max_num"][g, r] = ratio.max_numerator
            arrays["ratio_max_den"][g, r] = ratio.max_denominator
            arrays["ratio_valid"][g, r] = True

    def _alloc(self, gb: int, pb: int, m: int) -> dict:
        return {
            "unit_cost": np.zeros((gb, pb), np.float32),
            "slo_weight": np.zeros((gb, pb), np.float32),
            "max_hourly_cost": np.zeros((gb, pb), np.float32),
            "tier_penalty": np.zeros((gb, pb), np.float32),
            "pool_valid": np.zeros((gb, pb), bool),
            "slo_target": np.ones((gb, pb, m), np.float32),
            "ratio_a": np.zeros((gb, PGK.RATIO_SLOTS), np.int32),
            "ratio_b": np.zeros((gb, PGK.RATIO_SLOTS), np.int32),
            "ratio_min_num": np.zeros((gb, PGK.RATIO_SLOTS), np.int32),
            "ratio_min_den": np.ones((gb, PGK.RATIO_SLOTS), np.int32),
            "ratio_max_num": np.zeros((gb, PGK.RATIO_SLOTS), np.int32),
            "ratio_max_den": np.zeros((gb, PGK.RATIO_SLOTS), np.int32),
            "ratio_valid": np.zeros((gb, PGK.RATIO_SLOTS), bool),
            "group_budget": np.zeros(gb, np.float32),
            "group_valid": np.zeros(gb, bool),
        }

    def _build_inputs(
        self, rows: List, plan: PoolGroupPlan, outputs: D.DecisionOutputs
    ) -> PGK.PoolGroupInputs:
        """One padded PoolGroupInputs matrix for the whole fleet's
        groups: per-pool operands exactly as the CostEngine would
        assemble them for that pool's row, movement bounds clamped to
        the decide kernel's fresh up_ceiling/down_floor, group
        constraints as exact-integer operands."""
        gb = pad_group_count(len(plan.groups))
        pb = PGK.pad_pool_count(
            max(len(idxs) for _, idxs in plan.groups)
        )
        m = max(
            1,
            max(
                len(rows[i].values)
                for _, idxs in plan.groups
                for i in idxs
            ),
        )
        a = self._alloc(gb, pb, m)
        base = np.zeros((gb, pb), np.int32)
        min_replicas = np.zeros((gb, pb), np.int32)
        max_replicas = np.zeros((gb, pb), np.int32)
        demand_mu = np.zeros((gb, pb, m), np.float32)
        demand_sigma = np.zeros((gb, pb, m), np.float32)
        demand_valid = np.zeros((gb, pb, m), bool)
        desired = np.asarray(outputs.desired, np.int32)
        up_ceiling = np.asarray(outputs.up_ceiling, np.int32)
        down_floor = np.asarray(outputs.down_floor, np.int32)
        for g, (group, idxs) in enumerate(plan.groups):
            self._pool_scalars(group, idxs, rows, g, a)
            self._ratio_operands(group, g, a)
            a["group_budget"][g] = group.spec.max_hourly_cost
            a["group_valid"][g] = True
            for p, i in enumerate(idxs):
                row = rows[i]
                slo = getattr(row.ha.spec.behavior, "slo", None)
                lo, hi = self._member_bounds(row.ha, group.spec.pools[p])
                base[g, p] = desired[i]
                # the cost clamp order one rank up: spec bounds outrank
                # the per-tick rate bound
                min_replicas[g, p] = max(lo, min(int(down_floor[i]), hi))
                max_replicas[g, p] = min(hi, max(int(up_ceiling[i]), lo))
                for j in range(len(row.observed)):
                    per_replica = self._target_for(row, slo, j)
                    if not per_replica or per_replica <= 0:
                        continue  # no capacity notion: no risk, no demand
                    _spec, _target, observed = row.observed[j]
                    mu, sigma, ok = self._demand(row, j, observed)
                    a["slo_target"][g, p, j] = per_replica
                    demand_mu[g, p, j] = mu
                    demand_sigma[g, p, j] = sigma
                    demand_valid[g, p, j] = ok
        return PGK.PoolGroupInputs(
            base_desired=base,
            min_replicas=min_replicas,
            max_replicas=max_replicas,
            unit_cost=a["unit_cost"],
            slo_weight=a["slo_weight"],
            max_hourly_cost=a["max_hourly_cost"],
            tier_penalty=a["tier_penalty"],
            pool_valid=a["pool_valid"],
            slo_target=a["slo_target"],
            demand_mu=demand_mu,
            demand_sigma=demand_sigma,
            demand_valid=demand_valid,
            ratio_a=a["ratio_a"],
            ratio_b=a["ratio_b"],
            ratio_min_num=a["ratio_min_num"],
            ratio_min_den=a["ratio_min_den"],
            ratio_max_num=a["ratio_max_num"],
            ratio_max_den=a["ratio_max_den"],
            ratio_valid=a["ratio_valid"],
            group_budget=a["group_budget"],
            group_valid=a["group_valid"],
        )

    def _fused_operand_struct(
        self, rows: List, plan: PoolGroupPlan, n: int, m: int
    ):
        from karpenter_tpu.ops import fusedtick as FT

        gb = pad_group_count(len(plan.groups))
        pb = PGK.pad_pool_count(
            max(len(idxs) for _, idxs in plan.groups)
        )
        a = self._alloc(gb, pb, m)
        member_row = np.zeros((gb, pb), np.int32)
        pg_min = np.zeros((gb, pb), np.int32)
        pg_max = np.zeros((gb, pb), np.int32)
        observed_arr = np.zeros((gb, pb, m), np.float32)
        demand_base_valid = np.zeros((gb, pb, m), bool)
        prior_point = np.zeros((gb, pb, m), np.float32)
        prior_sigma2 = np.zeros((gb, pb, m), np.float32)
        prior_valid = np.zeros((gb, pb, m), bool)
        for g, (group, idxs) in enumerate(plan.groups):
            self._pool_scalars(group, idxs, rows, g, a)
            self._ratio_operands(group, g, a)
            a["group_budget"][g] = group.spec.max_hourly_cost
            a["group_valid"][g] = True
            for p, i in enumerate(idxs):
                row = rows[i]
                slo = getattr(row.ha.spec.behavior, "slo", None)
                lo, hi = self._member_bounds(row.ha, group.spec.pools[p])
                member_row[g, p] = i
                pg_min[g, p] = lo
                pg_max[g, p] = hi
                for j in range(len(row.observed)):
                    per_replica = self._target_for(row, slo, j)
                    if not per_replica or per_replica <= 0:
                        continue
                    _spec, _target, observed = row.observed[j]
                    a["slo_target"][g, p, j] = per_replica
                    observed_arr[g, p, j] = observed
                    if not math.isfinite(observed):
                        continue  # _demand()'s early return: no dist read
                    demand_base_valid[g, p, j] = True
                    if self.forecaster is None:
                        continue
                    dist = self.forecaster.distribution(
                        row.ha.metadata.namespace,
                        row.ha.metadata.name,
                        j,
                    )
                    if dist is not None:
                        prior_point[g, p, j] = dist[0]
                        prior_sigma2[g, p, j] = dist[1]
                        prior_valid[g, p, j] = True
        return FT.PoolGroupOperands(
            member_row=member_row,
            pg_min=pg_min,
            pg_max=pg_max,
            unit_cost=a["unit_cost"],
            slo_weight=a["slo_weight"],
            max_hourly_cost=a["max_hourly_cost"],
            tier_penalty=a["tier_penalty"],
            pool_valid=a["pool_valid"],
            slo_target=a["slo_target"],
            observed=observed_arr,
            demand_base_valid=demand_base_valid,
            prior_point=prior_point,
            prior_sigma2=prior_sigma2,
            prior_valid=prior_valid,
            ratio_a=a["ratio_a"],
            ratio_b=a["ratio_b"],
            ratio_min_num=a["ratio_min_num"],
            ratio_min_den=a["ratio_min_den"],
            ratio_max_num=a["ratio_max_num"],
            ratio_max_den=a["ratio_max_den"],
            ratio_valid=a["ratio_valid"],
            group_budget=a["group_budget"],
            group_valid=a["group_valid"],
        )

    # -- post-dispatch half ------------------------------------------------

    def _apply(
        self, rows: List, plan: PoolGroupPlan,
        outputs: D.DecisionOutputs, out: PGK.PoolGroupOutputs,
    ) -> D.DecisionOutputs:
        from dataclasses import replace

        desired = np.asarray(outputs.desired, np.int32).copy()
        pg_desired = np.asarray(out.desired, np.int32)
        headroom = np.asarray(out.headroom, np.int32)
        ratio_ok = np.asarray(out.ratio_ok, bool)
        group_hourly = np.asarray(out.group_hourly, np.float32)
        self._annotate_ledger(plan, outputs, out)
        for g, (group, idxs) in enumerate(plan.groups):
            ns = group.metadata.namespace
            name = group.metadata.name
            for p, i in enumerate(idxs):
                desired[i] = pg_desired[g, p]
                ha = rows[i].ha
                ref = ha.spec.scale_target_ref
                self._contrib[(ns, ha.metadata.name)] = (
                    (ns, ref.name), int(headroom[g, p]),
                )
            if self._g_hourly is not None:
                self._g_hourly.set(name, ns, float(group_hourly[g]))
                self._g_ratio.set(name, ns, float(bool(ratio_ok[g])))
            if self._c_coordinated is not None and ratio_ok[g]:
                # counts COORDINATED ticks only: a tick served by the
                # degraded independent rung (or one whose band is out of
                # the ladder's reach) leaves the counter flat, so its
                # rate vs the tick rate IS the coordination SLI
                self._c_coordinated.inc(name, ns)
            self._live.add((ns, name))
            self._patch_status(group, bool(ratio_ok[g]), float(group_hourly[g]))
        return replace(outputs, desired=desired)

    def _patch_status(
        self, group: PoolGroup, coordinated: bool, hourly: float
    ) -> None:
        """status.coordinated / status.expectedHourly: the operator's
        kubectl-visible answer to 'is the band holding'. Best-effort —
        a status write failure must not fail the refine."""
        group.status.coordinated = coordinated
        group.status.expected_hourly = hourly
        if self.store is None:
            return
        try:
            self.store.patch_status(group)
        except Exception:  # noqa: BLE001 — status is advisory
            pass

    def _annotate_ledger(
        self, plan: PoolGroupPlan, outputs: D.DecisionOutputs,
        out: PGK.PoolGroupOutputs,
    ) -> None:
        """Provenance: member rows record that a JOINT allocation chose
        their count — and whether coordination moved them off the
        independent optimum (joint_repair). One attribute read when the
        ledger is off."""
        from karpenter_tpu.observability import default_ledger

        batch = default_ledger().current()  # None when disabled
        if batch is None:
            return
        repair = np.asarray(out.joint_repair, bool)
        for g, (_group, idxs) in enumerate(plan.groups):
            rows_in = [i for i in idxs if i < batch.n]
            if rows_in:
                batch.annotate_rows(
                    rows_in,
                    pool_grouped=True,
                    pool_joint_repair=bool(repair[g]),
                )
