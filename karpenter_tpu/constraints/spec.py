"""Declarative constraint-group specs (spec.constraints on the
pendingCapacity producer).

A ConstraintGroup names a set of pending pods (podSelector over pod
labels, first matching group wins) and declares how the batched solver
must place them:

- ``anti_affinity`` — no two members share a node (each member row takes
  a whole node: the pod_exclusive operand, the same conservative shape
  the hostname self-anti-affinity path uses)
- ``compact`` — members pack onto nodes of their own (compact-placement
  isolation class: the pod_pack_class operand; members never share a
  node with non-members, TPU-slice locality)
- ``spread`` — members balance across zones (the pod_spread_slot /
  group_domain / spread_cap operand trio; the compiler emits balanced
  per-domain quotas, skew <= 1 <= any legal maxSkew)
- ``reservation`` — members claim reserved capacity: they only place on
  groups labeled karpenter.sh/reservation=<name>, and unclaimed pods are
  fenced OFF every reserved group (the pod_claim / group_reservation
  operands)

Validation is strict at the API boundary (``validate()``), while the
compiler itself never raises on fleet state — a constraint that cannot
be satisfied yields infeasible rows (unschedulable counts), not errors.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karpenter_tpu.api.core import ZONE_LABEL


@dataclass(slots=True)
class SpreadSpec:
    """Topology-spread over the domains of ANY node label axis —
    topologyKey defaults to the zone label but accepts hostname, rack,
    or any custom key the fleet's groups carry. maxSkew >= 1 is
    accepted and always satisfied because the compiler emits BALANCED
    per-domain quotas (skew <= 1).

    One constraint set shares ONE topology key across all its spread
    groups (the solver ships a single group->domain operand;
    validate_constraints enforces the invariant at admission)."""

    topology_key: str = ZONE_LABEL
    max_skew: int = 1

    def validate(self) -> None:
        if not self.topology_key:
            raise ValueError("spread.topologyKey must be a non-empty label key")
        if self.max_skew < 1:
            raise ValueError("spread.maxSkew must be >= 1")


@dataclass(slots=True)
class ConstraintGroup:
    name: str = ""
    pod_selector: Dict[str, str] = field(default_factory=dict)
    anti_affinity: bool = False
    compact: bool = False
    spread: Optional[SpreadSpec] = None
    reservation: str = ""

    def validate(self) -> None:
        if not self.name:
            raise ValueError("constraint group requires a name")
        if not self.pod_selector:
            raise ValueError(
                f"constraint group {self.name!r} requires a podSelector"
            )
        if not (
            self.anti_affinity
            or self.compact
            or self.spread is not None
            or self.reservation
        ):
            raise ValueError(
                f"constraint group {self.name!r} declares no constraint "
                "(one of antiAffinity/compact/spread/reservation)"
            )
        if self.spread is not None:
            self.spread.validate()
        if self.anti_affinity and self.compact:
            # exclusive rows take whole nodes; compact isolation of
            # whole-node rows is vacuous and the combination reads as a
            # spec mistake
            raise ValueError(
                f"constraint group {self.name!r}: antiAffinity and "
                "compact are mutually exclusive"
            )


def validate_constraints(groups: List[ConstraintGroup]) -> None:
    seen = set()
    for group in groups:
        group.validate()
        if group.name in seen:
            raise ValueError(
                f"duplicate constraint group name {group.name!r}"
            )
        seen.add(group.name)
    keys = {g.spread.topology_key for g in groups if g.spread is not None}
    if len(keys) > 1:
        raise ValueError(
            "all spread groups in one constraint set must share a single "
            f"topologyKey, got {sorted(keys)}"
        )


def spread_topology_key(groups) -> str:
    """The single domain axis this constraint set spreads on (the
    validated invariant above); the zone label when nothing spreads."""
    for group in groups:
        if group.spread is not None:
            return group.spread.topology_key
    return ZONE_LABEL


def canonical_constraints(groups) -> tuple:
    """Hashable canonical form — the encode-memo / fingerprint identity
    of a constraint-group set (order-preserving: first-match-wins makes
    group order semantic)."""
    if not groups:
        return ()
    return tuple(
        (
            g.name,
            tuple(sorted(g.pod_selector.items())),
            bool(g.anti_affinity),
            bool(g.compact),
            (
                (g.spread.topology_key, int(g.spread.max_skew))
                if g.spread is not None
                else None
            ),
            g.reservation,
        )
        for g in groups
    )
