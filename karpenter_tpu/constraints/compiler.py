"""Constraint-group compiler: declarative specs -> exact-integer solver
operands (ops/binpack constraint plane).

The compiler is pure host-side numpy over the DEDUPLICATED weighted rows
the encoder already produces; everything it emits is integer-exact so
the XLA and numpy kernels stay bitwise-identical:

- membership: first matching group wins, evaluated once per DISTINCT pod
  label set (the columnar label_sets registry), gathered to rows
- reservation: claim ids over the reservation universe = spec claims
  union group karpenter.sh/reservation labels — reserved groups fence
  unclaimed pods even when nothing claims them
- compact placement: isolation class 1+k per compact group (class 0 is
  the shared class everything else packs in)
- spread: balanced per-zone quotas q+1/q from divmod(member weight,
  live zones) — skew <= 1 <= any legal maxSkew — plus the EXACTNESS
  CONTRACT the kernel's rank rule requires: member rows are pre-split at
  quota boundaries so every row's weighted rank interval lies inside one
  zone's quota (ops/binpack.constraint_mask assigns whole rows to the
  first zone with remaining quota; an unsplit straddling row would
  overflow it). Zone-less groups land in a trailing sink domain with
  quota 0 (spread members never place there; unconstrained pods are
  unaffected).

Nothing here raises on fleet state: an unsatisfiable constraint yields
infeasible rows (unschedulable counts), never an encode error.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from karpenter_tpu.api.core import (
    ZONE_LABEL,
    domain_of,
    matches_selector,
    reservation_of,
)
from karpenter_tpu.constraints.spec import spread_topology_key


def compile_membership(label_sets, labels_id, groups) -> np.ndarray:
    """i32[rows]: 0 = no group, g+1 = first group whose podSelector
    matches the row's pod labels. One selector evaluation per DISTINCT
    label set (label_sets registry), gathered to rows by labels_id."""
    per_set = np.zeros(max(1, len(label_sets)), np.int32)
    for sid, items in enumerate(label_sets):
        labels = dict(items)
        for g, group in enumerate(groups):
            if matches_selector(labels, group.pod_selector):
                per_set[sid] = g + 1
                break
    return per_set[np.asarray(labels_id, np.int32)]


@dataclass
class ConstraintMeta:
    """Host-side universe metadata (verdict gauges, reports) — derived
    deterministically from (groups, profiles), never shipped to the
    device."""

    reservations: List[str]  # claim id c = 1 + index
    zones: List[str]  # domain d = index; sink domain = len(zones) —
    #                   domains of `topology_key` (zone only by default)
    spread_names: List[str]  # slot s = 1 + index
    compact_names: List[str]  # pack class = 1 + index
    # the one label axis this set's spread groups balance over (the
    # validated single-key invariant, constraints/spec.py)
    topology_key: str = ZONE_LABEL


def constraint_meta(groups, profiles) -> ConstraintMeta:
    group_reservations = {
        reservation_of(labels) for _, labels, _ in profiles
    }
    spec_claims = {g.reservation for g in groups if g.reservation}
    key = spread_topology_key(groups)
    return ConstraintMeta(
        reservations=sorted(
            (spec_claims | group_reservations) - {""}
        ),
        zones=sorted(
            {domain_of(labels, key) for _, labels, _ in profiles} - {""}
        ),
        spread_names=[g.name for g in groups if g.spread is not None],
        compact_names=[g.name for g in groups if g.compact],
        topology_key=key,
    )


@dataclass
class CompiledConstraints:
    """Per-row / per-group constraint operands over the FINAL row set
    (after spread-quota splitting). `rep` gathers every pre-existing
    per-row array (row_idx, masks, exclusivity) into that final set."""

    rep: np.ndarray  # intp[hi'] — gather of pre-split row positions
    row_weight: np.ndarray  # i32[hi'] — split weights (sum preserved)
    claim: Optional[np.ndarray]  # i32[hi'] or None
    group_reservation: Optional[np.ndarray]  # i32[T_real] or None
    pack_class: Optional[np.ndarray]  # bool[hi', C] or None
    spread_slot: Optional[np.ndarray]  # i32[hi'] or None
    group_domain: Optional[np.ndarray]  # i32[T_real] or None
    spread_cap: Optional[np.ndarray]  # i32[S, D] or None
    exclusive: Optional[np.ndarray]  # bool[hi'] anti-affinity members
    meta: ConstraintMeta


def _split_spread_rows(membership, weights, valid, groups, meta):  # lint: allow-complexity — cap-boundary row splitting: each guard is a documented exactness rule
    """(rep, new_weights, slot_of_final_row, caps) — balanced zone
    quotas per spread slot and the row pre-split the kernel's rank rule
    requires. Row ORDER is preserved (split pieces adjacent): the
    kernel's exclusive weighted prefix-sum rank walks rows in order, so
    the compiler's quota accounting must walk the same order."""
    hi = len(membership)
    slot_by_group: Dict[int, int] = {}
    for j, name in enumerate(meta.spread_names):
        for gidx, group in enumerate(groups):
            if group.spread is not None and group.name == name:
                slot_by_group[gidx] = j + 1
    row_slot = np.zeros(hi, np.int32)
    for gidx, s in slot_by_group.items():
        row_slot[membership == gidx + 1] = s

    n_zones = len(meta.zones)
    n_slots = len(meta.spread_names)
    if n_slots == 0 or n_zones == 0 or not bool((row_slot != 0).any()):
        rep = np.arange(hi, dtype=np.intp)
        return rep, np.asarray(weights, np.int32).copy(), None, None

    caps = np.zeros((n_slots, n_zones + 1), np.int32)  # +1 = sink, 0
    for j in range(n_slots):
        members = (row_slot == j + 1) & valid
        total = int(np.asarray(weights)[members].sum())
        q, r = divmod(total, n_zones)
        caps[j, :n_zones] = q
        caps[j, :r] += 1
    bounds = np.cumsum(caps[:, :n_zones], axis=1)

    rep: List[int] = []
    new_w: List[int] = []
    out_slot: List[int] = []
    rank = np.zeros(n_slots, np.int64)
    for i in range(hi):
        s = int(row_slot[i])
        w = int(weights[i])
        if s == 0 or not valid[i] or w == 0:
            rep.append(i)
            new_w.append(w)
            out_slot.append(s)
            continue
        start = int(rank[s - 1])
        rank[s - 1] += w
        end = start + w
        # chunk [start, end) at the slot's quota boundaries so each
        # piece lies inside one zone's quota interval
        cuts = [start]
        cuts.extend(
            int(b) for b in bounds[s - 1] if start < b < end
        )
        cuts.append(end)
        for a, b in zip(cuts, cuts[1:]):
            rep.append(i)
            new_w.append(b - a)
            out_slot.append(s)
    return (
        np.asarray(rep, np.intp),
        np.asarray(new_w, np.int32),
        np.asarray(out_slot, np.int32),
        caps,
    )


def compile_rows(membership, weights, valid, profiles, groups):  # lint: allow-complexity — one arm per constraint kind, all optional
    """The full per-solve compile: (membership i32[hi], weights i32[hi],
    valid bool[hi], group profiles, constraint groups) ->
    CompiledConstraints. Operands are attached only when live (absent
    halves stay None so unconstrained fleets ship today's wire)."""
    membership = np.asarray(membership, np.int32)
    weights = np.asarray(weights, np.int32)
    valid = np.asarray(valid, bool)
    meta = constraint_meta(groups, profiles)
    n_groups = len(profiles)

    rep, row_weight, spread_slot, caps = _split_spread_rows(
        membership, weights, valid, groups, meta
    )
    membership = membership[rep]

    # reservation claims: claim id per row, reservation id per group
    claim = None
    group_reservation = None
    if meta.reservations:
        claim_of_group = np.zeros(len(groups) + 1, np.int32)
        for gidx, group in enumerate(groups):
            if group.reservation:
                claim_of_group[gidx + 1] = (
                    1 + meta.reservations.index(group.reservation)
                )
        claim = claim_of_group[membership]
        group_reservation = np.zeros(n_groups, np.int32)
        for t, (_, labels, _) in enumerate(profiles):
            name = reservation_of(labels)
            if name:
                group_reservation[t] = 1 + meta.reservations.index(name)
        if not claim.any() and not group_reservation.any():
            claim = None
            group_reservation = None

    # compact-placement isolation classes
    pack_class = None
    if meta.compact_names:
        class_of_group = np.zeros(len(groups) + 1, np.int32)
        for gidx, group in enumerate(groups):
            if group.compact:
                class_of_group[gidx + 1] = (
                    1 + meta.compact_names.index(group.name)
                )
        row_class = class_of_group[membership]
        if row_class.any():
            n_classes = 1 + len(meta.compact_names)
            pack_class = np.zeros((len(rep), n_classes), bool)
            pack_class[np.arange(len(rep)), row_class] = True

    # spread domains: zone index per group, sink for zone-less groups
    group_domain = None
    spread_cap = None
    if spread_slot is not None:
        group_domain = np.zeros(n_groups, np.int32)
        sink = len(meta.zones)
        for t, (_, labels, _) in enumerate(profiles):
            zone = domain_of(labels, meta.topology_key)
            group_domain[t] = (
                meta.zones.index(zone) if zone else sink
            )
        spread_cap = caps

    # anti-affinity members take whole nodes
    exclusive = None
    anti = np.zeros(len(groups) + 1, bool)
    for gidx, group in enumerate(groups):
        anti[gidx + 1] = group.anti_affinity
    row_anti = anti[membership]
    if row_anti.any():
        exclusive = row_anti

    return CompiledConstraints(
        rep=rep,
        row_weight=row_weight,
        claim=claim,
        group_reservation=group_reservation,
        pack_class=pack_class,
        spread_slot=spread_slot,
        group_domain=group_domain,
        spread_cap=spread_cap,
        exclusive=exclusive,
        meta=meta,
    )


# -- verdict helpers (host-side, from inputs + assigned) ---------------------


def spread_skew(inputs, assigned, meta: ConstraintMeta) -> Dict[str, int]:
    """Per spread group: max - min placed weight across live zones
    (assigned rows only — unschedulable members place nowhere)."""
    out: Dict[str, int] = {}
    n_zones = len(meta.zones)
    if inputs.pod_spread_slot is None or n_zones == 0:
        return {name: 0 for name in meta.spread_names}
    slot = np.asarray(inputs.pod_spread_slot)
    domain = np.asarray(inputs.group_domain)
    weight = (
        np.asarray(inputs.pod_weight)
        if inputs.pod_weight is not None
        else np.ones(len(slot), np.int32)
    )
    valid = np.asarray(inputs.pod_valid)
    assigned = np.asarray(assigned)
    for j, name in enumerate(meta.spread_names):
        rows = np.nonzero(
            (slot[: len(assigned)] == j + 1)
            & valid[: len(assigned)]
            & (assigned >= 0)
        )[0]
        per_zone = np.zeros(n_zones, np.int64)
        for i in rows:
            d = int(domain[assigned[i]])
            if d < n_zones:
                per_zone[d] += int(weight[i])
        out[name] = int(per_zone.max() - per_zone.min())
    return out


def reservation_fill(  # lint: allow-complexity — host-side verdict: one guard per absent-operand case
    inputs, assigned, meta: ConstraintMeta
) -> Dict[str, float]:
    """Per reservation: placed claimed weight / total claimed weight
    (1.0 when nothing claims it — an idle reservation is fully
    honored, not unfilled)."""
    out: Dict[str, float] = {}
    if inputs.pod_claim is None:
        return {name: 1.0 for name in meta.reservations}
    claim = np.asarray(inputs.pod_claim)
    reservation = (
        np.asarray(inputs.group_reservation)
        if inputs.group_reservation is not None
        else None
    )
    weight = (
        np.asarray(inputs.pod_weight)
        if inputs.pod_weight is not None
        else np.ones(len(claim), np.int32)
    )
    valid = np.asarray(inputs.pod_valid)
    assigned = np.asarray(assigned)
    for c, name in enumerate(meta.reservations):
        rows = np.nonzero(
            (claim[: len(assigned)] == c + 1) & valid[: len(assigned)]
        )[0]
        total = int(weight[rows].sum())
        if total == 0:
            out[name] = 1.0
            continue
        placed = 0
        for i in rows:
            t = int(assigned[i])
            if t >= 0 and (
                reservation is None or int(reservation[t]) == c + 1
            ):
                placed += int(weight[i])
        out[name] = placed / total
    return out
