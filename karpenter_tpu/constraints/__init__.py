"""Constraint plane: declarative constraint groups compiled into the
batched bin-pack's exact-integer operands.

Spec surface (spec.constraints on the pendingCapacity producer):
constraints/spec.py. Compiler (membership, reservation claims, compact
isolation classes, balanced zone-spread quotas, anti-affinity
exclusivity) and host-side verdict helpers: constraints/compiler.py.
See docs/constraints.md for worked examples.
"""

from karpenter_tpu.constraints.compiler import (
    CompiledConstraints,
    ConstraintMeta,
    compile_membership,
    compile_rows,
    constraint_meta,
    reservation_fill,
    spread_skew,
)
from karpenter_tpu.constraints.spec import (
    ConstraintGroup,
    SpreadSpec,
    canonical_constraints,
    validate_constraints,
)

__all__ = [
    "CompiledConstraints",
    "ConstraintGroup",
    "ConstraintMeta",
    "SpreadSpec",
    "canonical_constraints",
    "compile_membership",
    "compile_rows",
    "constraint_meta",
    "reservation_fill",
    "spread_skew",
    "validate_constraints",
]
