"""The water-fill and the joint chunk partition — the two primitives
every skew-bounded hand-out (spread splits, anti domain caps) shares.
Pure numpy, no store or census access."""

from __future__ import annotations

import numpy as np

def _water_fill(counts, caps, schedulable: int, seed: int) -> np.ndarray:
    """Distribute `schedulable` new replicas over domains that already
    hold `counts` matching pods, filling the least-loaded first (the
    only incremental order the skew check always admits: each placement
    lands on a current global minimum), capped per-domain by `caps`
    (None = unbounded). Returns per-domain additions. The remainder at
    the final water level rotates by content-keyed `seed`, so no domain
    is systematically overweighted across shapes (and the choice never
    depends on arena-local numbering). All-numpy: runs per dedup row on
    the churned-tick hot path."""
    c = np.asarray(counts, np.int64)
    cap = None if caps is None else np.asarray(caps, np.int64)

    def filled(level: int) -> int:
        add = np.clip(level - c, 0, None)
        if cap is not None:
            add = np.minimum(add, cap)
        return int(add.sum())

    lo = int(c.min())
    hi = (
        int(c.max()) + schedulable
        if cap is None
        else int((c + cap).max())
    )
    hi = max(lo, hi)
    while lo < hi:  # greatest level with filled(level) <= schedulable
        mid = (lo + hi + 1) // 2
        if filled(mid) <= schedulable:
            lo = mid
        else:
            hi = mid - 1
    level = lo
    out = np.clip(level - c, 0, None)
    if cap is not None:
        out = np.minimum(out, cap)
    remainder = schedulable - int(out.sum())
    if remainder:
        at_level = c + out == level
        can_grow = at_level if cap is None else at_level & (out < cap)
        candidates = np.flatnonzero(can_grow)
        if len(candidates):
            offset = seed % len(candidates)
            chosen = (
                np.arange(len(candidates)) - offset
            ) % len(candidates) < remainder
            out[candidates[chosen]] += 1
    return out


_UNBOUNDED = np.iinfo(np.int64).max // 4




def _partition_chunks(additions, masks, view, others_placed, n_groups,  # lint: allow-complexity — the wave loop: reach, floor, fill, charge, refund, repeat to fixpoint
                      seed):
    """Partition each chunk across every partition entry's domains by
    the SAME water-fill the split key uses: each entry's skew binds
    placements to a balanced distribution over its domains, and finite
    caps (occupancy, frozen minima) bound it absolutely. The relative
    bound holds against domains a chunk CANNOT reach, with WAVES to
    the fixpoint: a chunk capped by the floor may admit more once
    other chunks raise the unreachable minima (zone<->rack correlated
    topologies grow in lock-step instead of stranding weight). Totals
    and caps charge the WORKLOAD-shared `others_placed` ledger (keyed
    by entry index + value), so every row of a workload spends one
    budget; weight a LATER entry sheds is REFUNDED along its charge
    history, so phantom charges never starve later rows. Entries apply
    sequentially — a later entry re-partitions the earlier one's
    sub-chunks (product of domain counts at worst, fleet-scale
    constants). Dead groups are excluded from candidacy up front.

    Returns [(rank, count, extra mask or None)] — the pieces the
    caller emits; pods no piece can hold fall out (the caller counts
    them unschedulable). Mutates `others_placed`."""
    dead = view["dead"]
    pieces = []  # (rank, count, extra mask, charge history)
    for rank in range(len(additions)):
        chunk = int(additions[rank])
        if chunk:
            pieces.append((rank, chunk, None, ()))
    if not view["others"] or not pieces:
        return [(rank, count, extra) for rank, count, extra, _ in pieces]

    refunded = [False]

    def refund(history, amount):
        refunded[0] = True
        for ledger, value in history:
            ledger[value] = ledger.get(value, 0) - amount

    for entry_idx, skew, value_groups, caps2, counts2 in view["others"]:
        group_value = {}
        for value, groups in value_groups.items():
            for t in groups:
                group_value[t] = value
        placed = others_placed.setdefault(entry_idx, {})
        work = []  # (rank, remaining, extra, history, reachable)
        for rank, count, extra, history in pieces:
            allowed = ~masks[rank]
            if dead is not None:
                allowed = allowed & ~dead
            if extra is not None:
                allowed = allowed & ~extra
            reachable = sorted(
                {
                    group_value[t]
                    for t in np.flatnonzero(allowed)
                    if t in group_value
                }
            )
            work.append([rank, count, extra, history, reachable])
        taken = [dict() for _ in work]  # value -> count per piece
        progressed = True
        while progressed:
            progressed = False
            for w, (rank, remaining, _extra, _hist, reachable) in enumerate(
                work
            ):
                if remaining == 0 or not reachable:
                    continue
                totals = [
                    counts2.get(v, 0) + placed.get(v, 0)
                    for v in reachable
                ]
                floor = min(
                    counts2.get(v, 0) + placed.get(v, 0)
                    for v in value_groups
                )
                caps = []
                for v, total_v in zip(reachable, totals):
                    cap = caps2.get(v)
                    relative = max(0, floor + skew - total_v)
                    cap_v = (
                        relative
                        if cap is None
                        else min(
                            relative,
                            max(0, cap - placed.get(v, 0)),
                        )
                    )
                    caps.append(min(remaining, cap_v))
                schedulable = min(remaining, int(np.sum(caps)))
                if schedulable == 0:
                    continue
                adds = _water_fill(
                    totals, caps, schedulable, seed + rank
                )
                for j, value in enumerate(reachable):
                    take = int(adds[j])
                    if take:
                        taken[w][value] = taken[w].get(value, 0) + take
                        placed[value] = placed.get(value, 0) + take
                work[w][1] = remaining - schedulable
                progressed = True
        next_pieces = []
        for w, (rank, remaining, extra, history, _reachable) in enumerate(
            work
        ):
            if remaining:
                # this entry shed weight an EARLIER entry already
                # charged for: refund it, or the phantom charge starves
                # later rows (the charge-by-final-take rule, r3)
                refund(history, remaining)
            for value in sorted(taken[w]):
                restrict = np.ones(n_groups, bool)
                restrict[value_groups[value]] = False
                next_pieces.append(
                    [
                        rank,
                        taken[w][value],
                        restrict
                        if extra is None
                        else (extra | restrict),
                        (*history, (placed, value)),
                    ]
                )
        pieces = next_pieces

    # CASCADE: a refund at a later entry can invalidate the relative
    # floor that JUSTIFIED an earlier allocation (r0's third pod was
    # legal only while r1 held the charge the zone stage then shed —
    # soundness fuzz, heavy sweep). Verify every entry against the
    # FINAL ledgers and shed the excess from THIS row's pieces until
    # stable; prior rows stay valid because refunds only remove this
    # row's charges, so totals never drop below their end state. With
    # no refund, charges only grew the floor: nothing to verify.
    changed = refunded[0]
    while changed:
        changed = False
        for entry_idx, skew, value_groups, caps2, counts2 in (
            view["others"]
        ):
            ledger = others_placed[entry_idx]
            totals = {
                v: counts2.get(v, 0) + ledger.get(v, 0)
                for v in value_groups
            }
            floor = min(totals.values())
            for v in sorted(value_groups):
                excess = totals[v] - (floor + skew)
                cap = caps2.get(v)
                if cap is not None:
                    excess = max(excess, ledger.get(v, 0) - cap)
                if excess <= 0:
                    continue
                for piece in reversed(pieces):
                    if excess <= 0:
                        break
                    if piece[1] and any(
                        led is ledger and val == v
                        for led, val in piece[3]
                    ):
                        take = min(piece[1], excess)
                        piece[1] -= take
                        excess -= take
                        refund(piece[3], take)
                        changed = True
    return [
        (rank, count, extra)
        for rank, count, extra, _ in pieces
        if count
    ]

