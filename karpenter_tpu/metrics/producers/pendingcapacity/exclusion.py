"""Row-independent group exclusions a pod_affinity_shape imposes
(key presence, co pins, foreign terms vs the census), the co-bucket
pin, and the arena-independent canonical row key used to order
multi-row hand-outs."""

from __future__ import annotations

from typing import Dict

import numpy as np

from karpenter_tpu.api.core import HOSTNAME_TOPOLOGY_KEY

def _self_exclusion(
    need_keys, co_keys, co_allowed, label_dicts, n_groups
):
    """Key-presence + required self co-location pinning: groups missing
    a constrained key are out; when the workload already runs somewhere,
    new replicas pin to domains that hold a matching pod."""
    excluded = np.zeros(n_groups, bool)
    for t, labels in enumerate(label_dicts):
        if any(key not in labels for key in need_keys):
            excluded[t] = True
        elif co_allowed is not None and any(
            labels[key] not in co_allowed[key] for key in co_keys
        ):
            excluded[t] = True
    return excluded


def _foreign_scope_namespaces(census, sign, scope):
    """Resolve a foreign term's namespace scope. ("names", tuple) is
    explicit; ("selector", form, explicit) resolves against the frozen
    Namespace set unioned with the explicit list (the k8s combination
    rule) — and an ANTI term additionally blocks against every
    occupancy namespace with NO Namespace object to judge
    (conservative)."""
    if scope[0] == "names":
        return scope[1]
    _tag, ns_form, explicit = scope
    resolved = set(explicit)
    resolved |= census.namespaces_matching(ns_form)
    if sign < 0:
        known = census.known_namespace_names()
        resolved |= {
            ns
            for ns in census.occupancy_namespaces()
            if ns not in known
        }
    return sorted(resolved)


def _apply_foreign_term(excluded, census, label_dicts, sign, key, sel,
                        namespaces):
    """Fold ONE foreign term into the exclusion mask. Anti (sign -1)
    blocks occupied domains; co (sign +1) requires one with no
    first-replica bootstrap (a foreign selector the incoming pod
    doesn't match gets no grace — the scheduler's rule); sign +2 is the
    bootstrap-eligible SELF projection (api/core._foreign_terms): an
    empty census imposes nothing. Hostname co can never be met by a
    fresh node; hostname domains are node names, answered by the
    per-node materialized view without requiring the label on Node
    objects."""
    if sign == 1 and key == HOSTNAME_TOPOLOGY_KEY:
        # occupied or not, a fresh node can never host the neighbor —
        # skip the census walk entirely
        excluded[:] = True
        return
    occupied: set = set()
    for foreign_ns in namespaces:
        if key == HOSTNAME_TOPOLOGY_KEY:
            occupied |= census.matching_nodes(foreign_ns, sel)
        else:
            occupied |= census.domain_counts(foreign_ns, sel, key).keys()
    if sign < 0:
        for t, labels in enumerate(label_dicts):
            if labels.get(key) in occupied:
                excluded[t] = True
    else:
        _require_occupied_domain(excluded, label_dicts, sign, key, occupied)


def _require_occupied_domain(excluded, label_dicts, sign, key, occupied):
    """The CO arms of a foreign term: placement must land in a domain
    holding a matching pod."""
    if sign > 1 and not occupied:
        # the scheduler's first-replica grace: the pod itself is in
        # scope and matches, so an empty census imposes nothing
        return
    if key == HOSTNAME_TOPOLOGY_KEY:
        excluded[:] = True
        return
    for t, labels in enumerate(label_dicts):
        value = labels.get(key)
        if value is None or value not in occupied:
            excluded[t] = True


def _anti_base_exclusion(shape, census, label_dicts, n_groups):
    """(excluded mask, anti blocked values, co allowed values) — the
    ROW-INDEPENDENT group exclusions a pod_affinity_shape imposes:
    key-presence, required self co-location pinning to occupied
    domains (_self_exclusion), and FOREIGN required terms enforced
    against SCHEDULED state (_apply_foreign_term has the per-sign
    rules; _foreign_scope_namespaces the namespace scoping). Shared by
    the anti expansion's plan AND the spread caps' frozen-domain
    feedback — the one implementation of the exclusion rules."""
    _hostname_excl, anti_keys, co_keys, ident, foreign = shape
    blocked: Dict[str, set] = {}
    co_allowed = None
    if census is not None and ident:
        ident_ns, sel_forms = ident
        if anti_keys:
            blocked = census.anti_domains(ident_ns, sel_forms, anti_keys)
        if co_keys:
            co_allowed = census.co_domains(ident_ns, sel_forms, co_keys)
    excluded = _self_exclusion(
        [*anti_keys, *co_keys], co_keys, co_allowed, label_dicts, n_groups
    )
    if foreign and census is not None:
        for sign, key, sel, scope in foreign:
            namespaces = _foreign_scope_namespaces(census, sign, scope)
            _apply_foreign_term(
                excluded, census, label_dicts, sign, key, sel, namespaces
            )
    return excluded, blocked, co_allowed


def _anti_frozen_mask(shape, census, label_dicts, n_groups):
    """The anti-stage exclusions a SPREAD split must anticipate: base
    exclusion plus the co-only single-bucket pin (a spread split
    produces several rows, which triggers the multi-row pin in
    _expand_anti_rows). A spread domain whose groups are all excluded
    here can never receive its chunk — without feeding that back into
    the caps, the split balances over domains the anti stage then
    forbids, over-promising the survivors (found by the soundness
    fuzz). Anticipating the pin when the split ends up single-row only
    tightens: conservative."""
    _hostname_excl, anti_keys, co_keys, _ident, _foreign = shape
    excluded, _blocked, _co_allowed = _anti_base_exclusion(
        shape, census, label_dicts, n_groups
    )
    if co_keys and not anti_keys:
        excluded = _co_pin(excluded, label_dicts, co_keys, n_groups)
    return excluded


def _co_pin(excluded, label_dicts, co_keys, n_groups):
    """Pin a co-only multi-row workload to ONE deterministic co bucket
    (lexicographically first among non-excluded groups) — THE single
    implementation: the anti expansion and the spread caps' frozen
    feedback must pick the identical bucket, or the split balances
    weight into a domain the pin then forbids (the over-promise class
    the soundness fuzz caught)."""
    co_vecs: Dict[tuple, list] = {}
    for t, labels in enumerate(label_dicts):
        if not excluded[t]:
            co_vecs.setdefault(
                tuple(labels[k] for k in co_keys), []
            ).append(t)
    if not co_vecs:
        return excluded
    chosen = set(co_vecs[min(co_vecs)])
    excluded = excluded.copy()
    for t in range(n_groups):
        if t not in chosen:
            excluded[t] = True
    return excluded




def _total_order(value):
    """Totally-ordered encoding of a canonical shape component. Shape
    tuples embed OPTIONAL selector forms (None when the field is absent
    — e.g. spread_shape's selectorForm, metav1 nil-selector semantics),
    and plain tuple comparison raises TypeError on None-vs-tuple, so a
    legal spec mixing a nil and a set selector would crash the whole
    solve (r3 advisor, high). Every node gets a type rank so any two
    encoded keys compare: None < numbers < strings < tuples."""
    if isinstance(value, tuple):
        return (3, tuple(_total_order(v) for v in value))
    if value is None:
        return (0, 0.0)
    if isinstance(value, str):
        return (2, value)
    return (1, float(value))  # bool / int / float


def _shape_of(shapes, ids, slot) -> tuple:
    """A row's canonical shape from an optional (registry, id-column)
    pair; () when the snapshot doesn't carry that column."""
    if shapes is not None and ids is not None:
        return shapes[ids[slot]]
    return ()


def _canonical_row_key(snap, slot: int) -> tuple:
    """Arena-independent content key for a snapshot row: every component
    is resolved through its universe REGISTRY (resource names, label
    items, canonical shape tuples), so two arenas that numbered the same
    pod shapes differently still produce the same key. Used to order
    domain hand-out across a workload's rows (_expand_anti_rows). The
    result is passed through _total_order so keys embedding optional
    (None) selector forms stay comparable under sorted()."""
    requests = tuple(
        sorted(
            (snap.resources[r], float(snap.requests[slot, r]))
            for r in range(len(snap.resources))
            if snap.requests[slot, r] != 0
        )
    )
    selector = tuple(
        sorted(
            snap.labels[c]
            for c in range(len(snap.labels))
            if snap.required[slot, c]
        )
    )
    tolerations = tuple(
        sorted(
            (
                (t.key, t.operator, t.value, t.effect)
                for t in snap.shape_tolerations[snap.shape_id[slot]]
            ),
            # toleration value/key may be None (Exists operator)
            key=_total_order,
        )
    )
    affinity = _shape_of(snap.affinity_shapes, snap.affinity_id, slot)
    preferred = _shape_of(snap.preferred_shapes, snap.preferred_id, slot)
    spread = _shape_of(snap.spread_shapes, snap.spread_id, slot)
    soft = tuple(
        shapes[ids[slot]]
        for shapes, ids in (
            (snap.soft_spread_shapes, snap.soft_spread_id),
            (snap.soft_anti_shapes, snap.soft_anti_id),
        )
        if shapes is not None and ids is not None
    )
    return _total_order(
        (requests, selector, tolerations, affinity, preferred, spread,
         soft)
    )


