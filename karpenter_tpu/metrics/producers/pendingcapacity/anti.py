"""Required inter-pod self-(anti-)affinity row expansion: exclusive
rows, per-domain weight-1 splits with shared domain sequences, co
pins, and the spread re-validation of anti-decided rows."""

from __future__ import annotations

import collections
from typing import Dict, List, Optional

import numpy as np

from .census import _row_node_filter
from .exclusion import _anti_base_exclusion, _canonical_row_key, _co_pin
from .partition import _partition_chunks
from .spread import _spread_partition_view

def _expand_anti_rows(  # lint: allow-complexity — per-domain capping: each guard is a documented anti-affinity rule
    snap, profiles, row_idx, row_weight, prior_forbidden, label_dicts_fn,
    census=None,
):
    """Required inter-pod SELF-(anti-)affinity (api/core.pod_affinity_shape):

    - hostname anti-affinity marks the row EXCLUSIVE (one pod per node,
      the ops/binpack.py pod_exclusive operand);
    - domain anti-affinity (zone/region keys) caps the workload at ONE
      pod per topology domain OF EVERY KEY: eligible groups bucket by
      combined key values and a greedy pass selects domains no two of
      which share any key's value; the row splits into weight-1
      sub-rows, each masked to one selected domain's groups, the
      excess reported unschedulable. Rows sharing an anti shape (same
      workload identity — the canonical self-matching selector, so
      StatefulSet per-pod labels don't fragment it) draw from one
      shared domain sequence, so a workload split across
      request-distinct rows (e.g. mid-VPA-rollout) still never doubles
      up a domain;
    - co-location affinity keys exclude groups missing the key (group
      profiles hold the label INTERSECTION, so a group spanning domain
      values drops the key and is excluded). Combined with domain
      anti-affinity, ALL the workload's sub-rows pin to the single co
      bucket offering the most anti domains (independent per-domain
      assignment could split replicas across co domains the scheduler
      forces together). Co-location alone: the solver's whole-row-to-
      one-group assignment keeps a single-row workload in one domain;
      a workload split across request-distinct rows pins to one
      deterministic co bucket.

    A domain is a distinct topologyKey value among group-label
    intersections, exactly the _expand_spread_rows rule; a row with both
    hard spread and domain anti-affinity is split by the anti rule (the
    most balanced placement possible — spread's split is skipped, see
    _expand_spread_rows) while its spread keys contribute key-presence
    exclusion here.

    EXISTING-pod occupancy (`census`, a DomainCensus): domains already
    holding a scheduled pod matching the workload's selectors are spent
    for anti-affinity (seeded into the greedy pass), and required
    co-location pins new replicas to the domains that hold a matching
    pod — unless NO matching pod exists anywhere (the k8s first-replica
    bootstrap, which imposes nothing). census=None (hand-built
    snapshots) means no occupancy: bootstrap semantics throughout. Conservative throughout: the signal may report more
    unschedulable or spread wider than a legal placement, never claim
    feasibility the kube-scheduler would deny for the modeled slice
    (docs/OPERATIONS.md 'Scheduling fidelity').

    prior_forbidden (the spread expansion's per-row mask, aligned with
    the INPUT rows) is carried through the re-expansion: every output
    row inherits its source row's mask OR'd with the anti exclusions.

    Domain hand-out across a workload's rows is ordered by CANONICAL
    row content (_canonical_row_key), never by dedup-row position:
    byte-sorted row order depends on arena-local id numbering, so a
    position-ordered hand-out could give the oracle and feed paths
    different row->domain assignments — and with per-domain taints,
    different outputs — breaking the outputs-identical-on-every-
    encode-path invariant (r3 code review; the spread expansion's
    content-keyed rotation avoids the same trap).

    Returns (row_idx, row_weight, forbidden[rows, T]-or-None,
    exclusive[rows]-or-None); unconstrained snapshots pass untouched.
    """
    shapes = snap.anti_shapes
    if (
        len(row_idx) == 0
        or snap.anti_id is None
        or shapes is None
        or not (snap.anti_id[row_idx] != 0).any()
    ):
        return row_idx, row_weight, prior_forbidden, None

    n_groups = len(profiles)
    label_dicts = label_dicts_fn()
    live_ids = snap.anti_id[row_idx]
    spread_shapes = snap.spread_shapes
    live_spread = (
        snap.spread_id[row_idx] if snap.spread_id is not None else None
    )

    # per live anti shape: (ordered domain group-lists or None,
    # key-exclusion mask, hostname_exclusive); the domain sequence is
    # SHARED across rows with the same shape, handed out in canonical
    # content order (path-stable — see docstring)
    sid_rows = collections.Counter(int(s) for s in live_ids)
    # (spread shape id, row filter token) -> partition view; ledgers
    # keyed per spread sid ONLY (one budget per workload) — for anti
    # rows whose spread split was skipped (see below)
    spread_view_memo: Dict[tuple, dict] = {}
    spread_ledgers: Dict[int, dict] = {}
    plan: Dict[int, tuple] = {}
    for s in np.unique(live_ids):
        shape = shapes[s]
        if not shape:
            continue
        flags, anti_keys, co_keys, ident, foreign = shape
        hostname_excl = bool(flags & 1)
        hostname_co = bool(flags & 2)
        excluded, blocked, co_allowed = _anti_base_exclusion(
            shape, census, label_dicts, n_groups
        )
        domains = None
        if anti_keys:
            # Combined-value accounting so EVERY key's cap holds (a
            # first-key-only split can put two replicas in one domain
            # of a coarser key, r3 code review): eligible groups bucket
            # by (co-key values, anti-key values); within each co
            # bucket, greedily select anti domains such that no two
            # share ANY key's value; the co bucket with the most
            # selected domains wins — the workload's co-location keys
            # pin ALL its replicas to that one bucket (a per-domain
            # independent assignment could split replicas across co
            # domains the scheduler forces together). Deterministic:
            # sorted iteration, count-then-lexicographic choice.
            buckets: Dict[tuple, Dict[tuple, list]] = {}
            for t, labels in enumerate(label_dicts):
                if excluded[t]:
                    continue
                co_vec = tuple(labels[k] for k in co_keys)
                anti_vec = tuple(labels[k] for k in anti_keys)
                buckets.setdefault(co_vec, {}).setdefault(
                    anti_vec, []
                ).append(t)
            best: Optional[tuple] = None
            for co_vec in sorted(buckets):
                # domains an EXISTING replica occupies are spent: seed
                # the per-key used sets so no new replica shares any
                # key's value with a pod already placed
                used: List[set] = [
                    set(blocked.get(key, ())) for key in anti_keys
                ]
                selected = []
                for anti_vec in sorted(buckets[co_vec]):
                    if any(
                        value in used[i]
                        for i, value in enumerate(anti_vec)
                    ):
                        continue
                    for i, value in enumerate(anti_vec):
                        used[i].add(value)
                    selected.append(buckets[co_vec][anti_vec])
                if best is None or len(selected) > len(best[1]):
                    best = (co_vec, selected)
            domains = best[1] if best is not None else []
        elif co_keys and sid_rows[int(s)] > 1:
            # co-location-only workload split across request-distinct
            # rows (mid-VPA): whole-row-to-one-group no longer pins ONE
            # domain, so pin all the workload's rows to a single
            # deterministic co bucket (_co_pin — the same choice the
            # spread caps anticipated); single-row workloads keep full
            # group freedom
            excluded = _co_pin(excluded, label_dicts, co_keys, n_groups)
        plan[int(s)] = (domains, excluded, hostname_excl, hostname_co)

    def row_spread_view(i):
        """Partition view + shared ledger for an anti-split row's SKIPPED
        spread shape: the anti hand-out decides the anti domains, but
        every spread entry still binds through the same water-fill
        partition the spread path uses (r3; zero-cap exclusion alone let
        a workload concentrate onto one rack — soundness fuzz)."""
        if (
            live_spread is None
            or live_spread[i] == 0
            or spread_shapes is None
        ):
            return None, None
        spread_sid = int(live_spread[i])
        row_filter = (
            _row_node_filter(snap, row_idx[i])
            if census is not None
            else (None, None)
        )
        key = (spread_sid, row_filter[0])
        view = spread_view_memo.get(key)
        if view is None:
            view = _spread_partition_view(
                spread_shapes[spread_sid], row_filter, label_dicts,
                census, n_groups,
            )
            spread_view_memo[key] = view
        # the LEDGER is per WORKLOAD (per spread sid), never per filter
        # token: rows with different node selectors must spend one
        # budget (r3 code review)
        return view, spread_ledgers.setdefault(spread_sid, {})

    # hand out domains per workload in canonical content order; a
    # domain dead for one row (its spread capacity spent, or every
    # group of it excluded) is SKIPPED, not consumed — a later row may
    # still use it, while consumption stays GLOBAL per workload so no
    # two rows ever share a domain (the no-doubling invariant)
    picks: Dict[int, list] = {}
    row_views: Dict[int, tuple] = {}
    rows_by_sid: Dict[int, list] = {}
    for i, sid in enumerate(live_ids):
        entry = plan.get(int(sid))
        if entry is not None and entry[0] is not None:
            rows_by_sid.setdefault(int(sid), []).append(i)
    for sid, rows_i in rows_by_sid.items():
        domain_list = plan[sid][0]
        if len(rows_i) > 1:
            rows_i = sorted(
                rows_i,
                key=lambda i: _canonical_row_key(snap, row_idx[i]),
            )
        consumed = [False] * len(domain_list)
        for i in rows_i:
            view, ledger = row_spread_view(i)
            if view is not None:
                row_views[i] = (view, ledger)
            dead = view["dead"] if view is not None else None
            need = int(row_weight[i])
            mine = []
            for rank, groups in enumerate(domain_list):
                if len(mine) >= need:
                    break
                if consumed[rank]:
                    continue
                if dead is not None and all(dead[t] for t in groups):
                    continue
                consumed[rank] = True
                mine.append(rank)
            picks[i] = mine

    # hostname CO bootstrap cap: ONE promised replica per workload
    # (replicas beyond the first must join the first's node, which a
    # group-level pack cannot promise; with an occupied census the +2
    # foreign projection already forbade every group). The single
    # promise goes to the CANONICALLY-first row so every encode path
    # hands it out identically (the domain hand-out's path-stability
    # rule).
    co_budget_row: Dict[int, int] = {}
    for s, entry in plan.items():
        if entry[3]:
            rows_i = [i for i, s2 in enumerate(live_ids) if int(s2) == s]
            co_budget_row[s] = (
                min(
                    rows_i,
                    key=lambda i: _canonical_row_key(snap, row_idx[i]),
                )
                if len(rows_i) > 1
                else rows_i[0]
            )

    out_idx, out_weight, out_forbidden, out_exclusive = [], [], [], []
    for i, sid in enumerate(live_ids):
        prior = (
            prior_forbidden[i]
            if prior_forbidden is not None
            else np.zeros(n_groups, bool)
        )
        entry = plan.get(int(sid))
        if entry is None:
            out_idx.append(row_idx[i])
            out_weight.append(row_weight[i])
            out_forbidden.append(prior)
            out_exclusive.append(False)
            continue
        domains, excluded, hostname_excl, hostname_co = entry
        excluded = excluded | prior
        if i in row_views and row_views[i][0]["dead"] is not None:
            # partial-dead domains stay usable through their live
            # groups; the mask forbids the spent ones
            excluded |= row_views[i][0]["dead"]
        weight = int(row_weight[i])
        if domains is None:
            if hostname_co:
                take = (
                    min(1, weight)
                    if co_budget_row.get(int(sid)) == i
                    else 0
                )
                if take:
                    out_idx.append(row_idx[i])
                    out_weight.append(np.int32(take))
                    out_forbidden.append(excluded)
                    out_exclusive.append(hostname_excl)
                if weight > take:
                    out_idx.append(row_idx[i])
                    out_weight.append(np.int32(weight - take))
                    out_forbidden.append(np.ones(n_groups, bool))
                    out_exclusive.append(hostname_excl)
                continue
            # hostname/co-location only: no split, mask + flag ride along
            out_idx.append(row_idx[i])
            out_weight.append(row_weight[i])
            out_forbidden.append(excluded)
            out_exclusive.append(hostname_excl)
            continue
        mine = picks[i]
        if hostname_co:
            # one replica total: only the budget row places, one domain
            mine = mine[:1] if co_budget_row.get(int(sid)) == i else []
        view_ledger = row_views.get(i)
        placed = 0
        # content-keyed, invariant across this row's ranks (arena
        # numbering must not steer the partition)
        content_sum = int(
            np.ascontiguousarray(snap.requests[row_idx[i]])
            .view(np.uint8)
            .sum()
        )
        for rank in mine:
            forbidden = np.ones(n_groups, bool)
            forbidden[domains[rank]] = False
            forbidden |= excluded
            if view_ledger is None:
                placed += 1
                out_idx.append(row_idx[i])
                out_weight.append(np.int32(1))
                out_forbidden.append(forbidden)
                out_exclusive.append(hostname_excl)
                continue
            # the SKIPPED spread shape still binds: partition this
            # weight-1 sub-row across every spread entry's domains
            # against the workload-shared ledger (picking e.g. the
            # rack with remaining balance, not whichever group the
            # solver tries first)
            view, ledger = view_ledger
            seed = rank + content_sum
            pieces = _partition_chunks(
                np.array([1], np.int64), [forbidden], view, ledger,
                n_groups, seed,
            )
            for _rank0, count, extra in pieces:
                placed += count
                sub = forbidden
                if extra is not None:
                    # view["dead"] already rode in through `excluded`
                    sub = sub | extra
                out_idx.append(row_idx[i])
                out_weight.append(np.int32(count))
                out_forbidden.append(sub)
                out_exclusive.append(hostname_excl)
        if weight > placed:
            # beyond the usable domain count / spread capacity:
            # unschedulable by anti-affinity — keep the excess as a
            # forbidden-everywhere row so it COUNTS
            out_idx.append(row_idx[i])
            out_weight.append(np.int32(weight - placed))
            out_forbidden.append(np.ones(n_groups, bool))
            out_exclusive.append(hostname_excl)
    return (
        np.asarray(out_idx, np.intp),
        np.asarray(out_weight, np.int32),
        np.stack(out_forbidden) if out_forbidden else None,
        np.asarray(out_exclusive, bool),
    )


