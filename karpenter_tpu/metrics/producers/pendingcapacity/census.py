"""Existing-pod domain occupancy queries (DomainCensus) and the
per-row node-filter tokens the spread/anti expansions key their
memos on. See the class docstring for the memoization contract."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.api.core import matches_affinity_shape, selector_form_matches

class DomainCensus:
    """Existing-pod domain occupancy: the query layer between a
    ScheduledOccupancy census (store/columnar) and the spread/anti row
    expansions. The kube-scheduler evaluates topology spread skew and
    inter-pod (anti-)affinity against the pods ALREADY PLACED; without
    these counts the signal could promise a placement (e.g. a replica
    into a zone that already holds one) the scheduler then refuses.

    All queries are memoized per (occupancy generation, node version)
    epoch, so steady-state ticks answer from the memo; the underlying
    census and node mirror are incremental, so nothing here scans the
    store. Node-side work (label extraction, per-row node filters) and
    pod-side work (selector evaluation over distinct label sets) are
    memoized independently.

    Pod-side reads go through the census's MATERIALIZED VIEWS
    (ScheduledOccupancy.view_counts): per-pod-unique labels fragment a
    100k-replica StatefulSet into 100k label groups, and a per-epoch
    group scan costs ~600 ms — over the tick budget by itself. A
    selector's view is built once and maintained at event time, so a
    churned tick's recompute here is O(nodes with matching pods).
    """

    def __init__(self, occupancy, nodes_fn, node_version_fn=None):
        self._occupancy = occupancy
        self._nodes_fn = nodes_fn  # () -> list of Node objects
        self._node_version_fn = node_version_fn or (lambda: 0)
        # Namespace objects FROZEN per solve (set_namespaces): the
        # encode-memo fingerprint and the namespaceSelector resolution
        # must read the same snapshot, or a label change landing
        # between the two reads caches an encode under a state it was
        # not computed from (r3 code review)
        self._namespaces: list = []
        self._epoch: Optional[tuple] = None
        self._memo: Dict[tuple, object] = {}
        self._node_memo: Dict[tuple, object] = {}
        self._named_labels: Optional[List[Tuple[str, dict]]] = None
        # epoch invalidations (bound-pod or node churn between solves);
        # published as karpenter_runtime_census_refresh_total so an
        # operator can see how often constrained ticks pay a recompute.
        # `published`/`evictions_published` are _publish_census
        # watermarks.
        self.refreshes = 0
        self.published = 0
        self.evictions_published = 0

    def _fresh(self, generation: int) -> None:
        epoch = (generation, self._node_version_fn())
        if epoch != self._epoch:
            self._epoch = epoch
            self._memo.clear()
            self._node_memo.clear()
            self._named_labels = None
            self.refreshes += 1

    def _node_counts(self, namespace, sel_form) -> Dict[str, int]:
        """Epoch check + {node: matching-pod count} for one selector,
        through the census's materialized view. Unmemoized on purpose:
        the view read is O(matching nodes) and the epoch check must run
        BEFORE any memo is consulted (a cached answer from a previous
        occupancy generation must never serve this one)."""
        generation, counts = self._occupancy.view_counts(
            namespace, sel_form
        )
        self._fresh(generation)
        return counts

    def _fresh_now(self) -> None:
        self._fresh(self._occupancy.generation)

    def _nodes(self) -> List[Tuple[str, dict]]:
        if self._named_labels is None:
            self._named_labels = [
                (n.metadata.name, dict(n.metadata.labels))
                for n in self._nodes_fn()
            ]
        return self._named_labels

    def spread(
        self, namespace, sel_form, split_key, filter_token, node_passes
    ) -> Tuple[Dict[str, int], set]:
        """(counts: {domain value: matching-pod count}, present: domain
        values among filter-passing live nodes) for one spread
        constraint. The node filter is the ROW's nodeSelector + required
        node affinity (nodeAffinityPolicy=Honor, the k8s default; taints
        are Ignored per the nodeTaintsPolicy default): only nodes the
        incoming pod could land on define domains and contribute counts.
        """
        # O(1) epoch check BEFORE any memo lookup (a cached answer from
        # a previous occupancy generation must never serve this one);
        # the view is only copied on memo miss
        self._fresh_now()
        memo_hit = self._memo.get(
            ("spread", namespace, sel_form, split_key, filter_token)
        )
        by_node = (
            self._node_counts(namespace, sel_form)
            if memo_hit is None and sel_form is not None
            else {}
        )
        node_key = (split_key, filter_token)
        node_side = self._node_memo.get(node_key)
        if node_side is None:
            passing: Dict[str, str] = {}
            present: set = set()
            for name, labels in self._nodes():
                value = labels.get(split_key)
                if value is None or not node_passes(labels):
                    continue
                passing[name] = value
                present.add(value)
            node_side = (passing, present)
            self._node_memo[node_key] = node_side
        passing, present = node_side
        memo_key = ("spread", namespace, sel_form, split_key,
                    filter_token)
        got = self._memo.get(memo_key)
        if got is None:
            counts: Dict[str, int] = {}
            for node, n in by_node.items():
                value = passing.get(node)
                if value is not None:
                    counts[value] = counts.get(value, 0) + n
            got = (counts, present)
            self._memo[memo_key] = got
        return got

    def set_namespaces(self, namespaces: list) -> None:
        """Freeze the Namespace set for this solve (see __init__)."""
        self._namespaces = list(namespaces)

    def known_namespace_names(self) -> set:
        return {ns.metadata.name for ns in self._namespaces}

    def namespaces_matching(self, ns_sel_form: tuple) -> set:
        """Names of live namespaces whose labels match the canonical
        namespaceSelector form (empty form = all namespaces, the k8s
        rule)."""
        return {
            ns.metadata.name
            for ns in self._namespaces
            if selector_form_matches(ns_sel_form, ns.metadata.labels)
        }

    def occupancy_namespaces(self) -> set:
        """Every namespace the occupancy census holds scheduled pods
        in — the conservative ANTI fallback when no Namespace objects
        exist to resolve a namespaceSelector against (fixtures,
        simulations): blocking against every known namespace's pods
        can only under-promise."""
        return self._occupancy.namespace_names()

    def domain_counts(self, namespace, sel_form, key) -> Dict[str, int]:
        """{topology value: matching-pod count} over ALL live nodes —
        the scoring-side census (soft spread / preferred inter-pod
        affinity score existing placements; no node filter applies to
        a preference). One counting implementation: this is spread()
        with the pass-all node filter, sharing its memos — the same
        token the hard path's nodeAffinityPolicy=Ignore case uses."""
        counts, _present = self.spread(
            namespace, sel_form, key, ("ignore",), lambda labels: True
        )
        return counts

    def matching_nodes(self, namespace, sel_form) -> set:
        """Node names hosting scheduled pods matching the selector —
        the hostname-key census. kubernetes.io/hostname domains ARE
        node names (the kubelet's well-known label), so this reads the
        materialized per-node view directly instead of requiring the
        label on Node objects (fixtures often omit it)."""
        return set(self._node_counts(namespace, sel_form))

    def _workload_nodes(self, namespace, sel_forms) -> tuple:
        """(any_nodes, all_nodes_or_None): node-name sets occupied by
        pods matching ANY of the workload's selectors (the anti-blocking
        set — over-blocking is conservative) and, for co-location, the
        nodes hosting a matching pod for EVERY live selector — the
        scheduler's per-term rule: each required term is satisfied by a
        domain holding a pod matching THAT term's selector (they need
        not be the same pod). all_nodes is None when NO selector has a
        matching scheduled pod anywhere in the namespace (the k8s
        first-replica bootstrap: a required self-affinity term with no
        matching pod cluster-wide imposes nothing). All forms are read
        under ONE census lock hold (view_counts_many) so the set is
        generation-consistent — a replica moving nodes between
        per-form reads could otherwise appear on neither."""
        # O(1) epoch check before the memo (stale answers must never
        # cross occupancy generations)
        self._fresh_now()
        memo_key = ("workload", namespace, sel_forms)
        got = self._memo.get(memo_key)
        if got is not None:
            return got
        generation, per_form = self._occupancy.view_counts_many(
            namespace, sel_forms
        )
        self._fresh(generation)
        any_nodes: set = set()
        for counts in per_form:
            any_nodes |= counts.keys()
        live = [counts for counts in per_form if counts]
        all_nodes: Optional[set] = None
        if live:
            all_nodes = set(live[0])
            for counts in live[1:]:
                all_nodes &= counts.keys()
        got = (any_nodes, all_nodes)
        self._memo[memo_key] = got
        return got

    def anti_domains(self, namespace, sel_forms, keys) -> Dict[str, set]:
        """Per anti key: topology values already OCCUPIED by an existing
        pod matching any of the workload's selectors — a self-anti
        replica can never be placed there again. Unfiltered nodes: the
        scheduler's inter-pod terms have no node-affinity gate."""
        any_nodes, _ = self._workload_nodes(namespace, sel_forms)
        blocked: Dict[str, set] = {key: set() for key in keys}
        if any_nodes:
            for name, labels in self._nodes():
                if name not in any_nodes:
                    continue
                for key in keys:
                    value = labels.get(key)
                    if value is not None:
                        blocked[key].add(value)
        return blocked

    def co_domains(
        self, namespace, sel_forms, keys
    ) -> Optional[Dict[str, set]]:
        """Per co key: the topology values that HOLD a matching pod —
        required self-affinity forces new replicas into one of them.
        None = bootstrap (no matching scheduled pod anywhere): the
        term imposes nothing and the whole-workload-in-one-domain rule
        alone applies."""
        _, all_nodes = self._workload_nodes(namespace, sel_forms)
        if all_nodes is None:
            return None
        allowed: Dict[str, set] = {key: set() for key in keys}
        for name, labels in self._nodes():
            if name not in all_nodes:
                continue
            for key in keys:
                value = labels.get(key)
                if value is not None:
                    allowed[key].add(value)
        return allowed


def _row_node_filter(snap, slot: int) -> tuple:
    """(memo token, node_passes) for a snapshot row: the row's
    nodeSelector + required-node-affinity filter, applied to census
    nodes (nodeAffinityPolicy=Honor). Token is content-derived so census
    memo entries are shared across rows with the same filter."""
    sel_items = [
        snap.labels[c] for c in np.flatnonzero(snap.required[slot])
    ]
    shape = (
        snap.affinity_shapes[snap.affinity_id[slot]]
        if snap.affinity_shapes is not None and snap.affinity_id is not None
        else ()
    )
    token = (tuple(sorted(sel_items)), shape)

    def node_passes(labels: dict) -> bool:
        if any(labels.get(k) != v for k, v in sel_items):
            return False
        return not shape or matches_affinity_shape(labels, shape)

    return token, node_passes




def _entry_census(census, namespace, entry, row_filter):
    """({value: count}, present values) for one spread entry under one
    row filter — THE census dispatch (honor vs Ignore policy, the
    census-less fallback), shared by the split budgets and the anti
    path's zero-cap masks so the two can never diverge."""
    _key, _skew, _mind, sel, _self, honor = entry
    if census is None or sel is None:
        return {}, set()
    if honor:
        token, node_passes = row_filter
        return census.spread(
            namespace, sel, entry[0], token, node_passes
        )
    # nodeAffinityPolicy=Ignore: every live node exposing the key
    # defines a domain and contributes counts
    return census.spread(
        namespace, sel, entry[0], ("ignore",), lambda labels: True
    )


