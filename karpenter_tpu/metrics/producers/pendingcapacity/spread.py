"""Topology-spread constraint machinery: per-entry caps, the
immutable per-(shape, filter) cap views, the partition-form view for
anti-expanded rows, and the spread row expansion itself."""

from __future__ import annotations

import collections
from typing import Dict, Tuple

import numpy as np

from .census import _entry_census, _row_node_filter
from .exclusion import _anti_frozen_mask, _canonical_row_key
from .partition import _UNBOUNDED, _partition_chunks, _water_fill

def _entry_caps(
    skew, min_domains, self_match, values, counts_e, present_e
) -> Tuple[np.ndarray, np.ndarray, bool]:
    """Per-value new-replica caps imposed by ONE spread constraint
    entry over the `values` domain list (_UNBOUNDED where it imposes
    nothing). The three regimes the scheduler's skew check induces:

    - selfMatch false: placements never accumulate into the counts, so
      the check is static per domain — existing count must stay within
      maxSkew of the global minimum (0 under the minDomains rule);
      violating domains cap at 0, the rest are unbounded.
    - minDomains unsatisfied: global minimum treated as 0 — each domain
      holds at most maxSkew matching pods INCLUDING existing ones.
    - otherwise: domains among filter-passing live nodes that the
      candidate groups can't fill freeze the global minimum, capping
      each value at outside-minimum + maxSkew.
    """
    d = len(values)
    c_e = np.array([counts_e.get(v, 0) for v in values], np.int64)
    caps = np.full(d, _UNBOUNDED, np.int64)
    min_rule = bool(min_domains) and d < min_domains
    if not self_match:
        floor = 0 if min_rule else min(
            [
                int(c_e.min()),
                *(counts_e.get(v, 0) for v in present_e - set(values)),
            ]
        )
        caps[c_e - floor > skew] = 0
    elif min_rule:
        caps = np.clip(skew - c_e, 0, None)
    else:
        outside = present_e - set(values)
        m_out = min(
            (counts_e.get(v, 0) for v in outside), default=None
        )
        if m_out is not None:
            caps = np.clip(m_out + skew - c_e, 0, None)
    return caps, c_e, min_rule


def _partition_entry(entry_idx, skew, value_groups, caps, values,
                     counts_e):
    """One joint-partition 5-tuple: (entry index, maxSkew,
    value->groups, per-value caps with None = unbounded, per-value
    existing counts) — the shape _partition_chunks consumes."""
    return (
        entry_idx,
        int(skew),
        value_groups,
        {
            v: (int(caps[j]) if caps[j] < _UNBOUNDED else None)
            for j, v in enumerate(values)
        },
        {v: counts_e.get(v, 0) for v in values},
    )


def _mark_dead_domains(dead, caps2, values2, vals2, n_groups):
    """Flag every group of a zero-capacity domain in the dead mask."""
    if dead is None:
        dead = np.zeros(n_groups, bool)
    for j, value in enumerate(values2):
        if caps2[j] <= 0:
            dead[vals2[value]] = True
    return dead


def _nonsplit_entry_states(entries, split_key, entry_counts, eligible,
                           label_dicts, dead):
    """Fold the NON-split entries into (others, dead). Their
    zero-capacity domains (dead groups) can leave a split domain with
    no live group at all, and such a domain must then FREEZE the
    split-key global minimum like an unfillable outside domain —
    otherwise the surviving domains are over-promised capacity the
    scheduler's skew check denies against the frozen one (r3 code
    review). EVERY selfMatch non-split entry joins the chunk partition:
    even with unbounded caps its skew binds placements to a balanced
    distribution across its domains (the soundness fuzz caught whole
    chunks piling into one rack)."""
    others = []
    for entry_idx, e in enumerate(entries):
        if e[0] == split_key:
            continue
        _key, skew, min_domains, _sel, self_match, _honor = e
        counts_e, present_e = entry_counts(e)
        vals2: Dict[str, list] = {}
        for t in eligible:
            value = label_dicts[t].get(e[0])
            if value is not None:
                vals2.setdefault(value, []).append(t)
        if not vals2:
            continue
        values2 = sorted(vals2)
        caps2, _, _ = _entry_caps(skew, min_domains, self_match,
                                  values2, counts_e, present_e)
        if (caps2 <= 0).any():
            dead = _mark_dead_domains(
                dead, caps2, values2, vals2, len(label_dicts)
            )
        if self_match:
            others.append(
                _partition_entry(
                    entry_idx, skew, {v: vals2[v] for v in values2},
                    caps2, values2, counts_e,
                )
            )
    return others, dead


def _seed_covers(entries, split_key) -> bool:
    """Whether the fill-order seed (entries[0]'s counts) is the ONLY
    selfMatch split-key entry. The initial water-fill balances against
    entries[0]'s counts only — a fixpoint of a selfMatch split entry's
    relative skew bound just for THAT entry: a same-key selfMatch entry
    with a DIFFERENT selector has its own census counts, and with every
    live domain fillable its _entry_caps are unbounded — nothing
    enforces its skew against its own imbalance unless it joins the
    joint partition (r3 advisor, medium: two same-key DoNotSchedule
    constraints promised a replica into a domain the scheduler's second
    skew check denies)."""
    selfmatch_split = sum(
        1 for e in entries if e[0] == split_key and e[4]
    )
    return bool(entries[0][4]) and selfmatch_split == 1


def _frozen_split_values(values, split_groups, dead) -> np.ndarray:
    """Split values every live group of which is dead: unfillable, so
    they freeze the split-key global minimum."""
    frozen = np.zeros(len(values), bool)
    if dead is not None:
        for j, v in enumerate(values):
            if all(dead[t] for t in split_groups[v]):
                frozen[j] = True
    return frozen


def _split_entry_caps(e, values, counts_e, present_e, frozen):
    """Per-value caps for ONE split-key entry with the frozen-domain
    feedback applied: frozen domains' counts cap everything else at
    frozen-min + maxSkew (the outside-minimum rule), and nothing can
    actually land in a frozen domain."""
    _key, skew, min_domains, _sel, self_match, _honor = e
    caps_e, c_e, min_rule = _entry_caps(
        skew, min_domains, self_match, values, counts_e, present_e
    )
    if frozen.any():
        if self_match and not min_rule:
            m_frozen = int(c_e[frozen].min())
            caps_e = np.minimum(
                caps_e, np.clip(m_frozen + skew - c_e, 0, None)
            )
        caps_e = caps_e.copy()
        caps_e[frozen] = 0  # nothing can actually land there
    return caps_e, skew, self_match


def _spread_state(namespace, entries, values, census, row_filter,
                  label_dicts, eligible, extra_dead=None):
    """IMMUTABLE per-(shape, node-filter) cap VIEW — what the
    scheduler's skew checks admit for a row carrying this filter:

    - `static`[d]: split-key caps from non-selfMatch entries (0 or
      unbounded — placements never consume them);
    - `budget`[d]: split-key caps from selfMatch entries, the MIN over
      every same-key entry (a single "first entry" cap could silently
      drop a tighter same-key constraint, r3 code review);
    - `counts`[d]: the first entry's census counts (the fill-order
      seed);
    - `dead`: groups excluded outright — extra_dead (the anti stage's
      row-independent exclusions) plus every entry's zero-capacity
      domains;
    - `others`: EVERY selfMatch entry — non-split ones first, then the
      split entries themselves, so the joint partition
      (_partition_chunks) re-validates the split after other keys
      narrow — as (entry index, maxSkew, value->groups, per-value caps
      with None = unbounded, per-value existing counts) 5-tuples. The
      split entries also join whenever MORE THAN ONE selfMatch entry
      shares the split key (or the seed entry isn't selfMatch): each
      same-key selector has its own census counts and its relative
      skew bound only holds through the partition (r3 advisor).

    CONSUMPTION lives one level up, in the per-WORKLOAD shared ledgers
    (_expand_spread_rows): placements count against the workload's
    skew regardless of which row's node filter admitted them, so rows
    with DIFFERENT filters still spend one budget — each row's
    effective cap is its own view minus everything the workload already
    placed (r3 code review)."""
    split_key = entries[0][0]

    def entry_counts(e):
        return _entry_census(census, namespace, e, row_filter)

    d = len(values)
    static = np.full(d, _UNBOUNDED, np.int64)
    budget = np.full(d, _UNBOUNDED, np.int64)
    # `extra_dead` seeds the dead mask with the anti stage's
    # row-independent exclusions (co pins, foreign terms): a domain
    # those will forbid must freeze the minimum HERE, before the split
    # balances weight into it (found by the soundness fuzz)
    dead = extra_dead.copy() if extra_dead is not None else None
    # NON-SPLIT entries first (_nonsplit_entry_states has the freeze
    # rationale)
    others, dead = _nonsplit_entry_states(
        entries, split_key, entry_counts, eligible, label_dicts, dead
    )
    has_other_partitions = bool(others)
    seed_covers = _seed_covers(entries, split_key)
    split_groups: Dict[str, list] = {}
    for t in eligible:
        split_groups.setdefault(label_dicts[t][split_key], []).append(t)
    frozen = _frozen_split_values(values, split_groups, dead)
    for entry_idx, e in enumerate(entries):
        if e[0] != split_key:
            continue
        counts_e, present_e = entry_counts(e)
        caps_e, skew, self_match = _split_entry_caps(
            e, values, counts_e, present_e, frozen
        )
        if self_match:
            budget = np.minimum(budget, caps_e)
            # the split entry ALSO joins the joint partition (LAST, so
            # it re-validates after other keys narrow): when another
            # key's budget drops part of a domain's chunk, the split
            # key's own balance must re-bind against the shrunken
            # totals — the pre-allocation alone would leave e.g. zone
            # [2,0,1] standing after a rack cap emptied the middle
            # zone (found by the soundness fuzz). With NO other
            # partition entries AND a single selfMatch split entry
            # seeding the fill, nothing can shed and the split
            # water-fill is already a fixpoint of these exact bounds —
            # the common single-key fleet skips the partition entirely.
            # Same-key selfMatch entries beyond the seed always join
            # (seed_covers above).
            if has_other_partitions or not seed_covers:
                others.append(
                    _partition_entry(
                        entry_idx, skew, dict(split_groups), caps_e,
                        values, counts_e,
                    )
                )
        else:
            static = np.minimum(static, caps_e)
    first_counts, _ = entry_counts(entries[0])
    counts = (
        np.array([first_counts.get(v, 0) for v in values], np.int64)
        if entries[0][4]
        else np.zeros(d, np.int64)
    )
    return {
        "static": static,
        "budget": budget,
        "counts": counts,
        "first_selfmatch": bool(entries[0][4]),
        "dead": dead,
        "others": others,
    }




def _spread_partition_view(shape, row_filter, label_dicts, census,
                           n_groups):
    """Partition-form view of ALL of a spread shape's entries, for rows
    whose SPLIT was skipped in favor of the anti rule: the anti
    hand-out decides the anti domains, but every spread entry still
    binds — through the same _partition_chunks water-fill the spread
    path uses (zero-cap exclusion alone let the hand-out concentrate a
    workload onto one rack, found by the soundness fuzz).

    dead: groups missing a constrained key, non-selfMatch zero-cap
    domains, and selfMatch currently-full domains (cap 0 — also kept
    in the partition caps, but dead lets the hand-out skip them
    without consuming a pick). others: every selfMatch entry as a
    partition dimension (skew + remaining caps + existing counts)."""
    namespace, entries = shape
    dead = np.zeros(n_groups, bool)
    others = []
    for idx, entry in enumerate(entries):
        key, skew, min_domains, _sel, self_match, _honor = entry
        vals: Dict[str, list] = {}
        for t, labels in enumerate(label_dicts):
            value = labels.get(key)
            if value is None:
                dead[t] = True
            else:
                vals.setdefault(value, []).append(t)
        if not vals:
            continue
        counts_e, present_e = _entry_census(
            census, namespace, entry, row_filter
        )
        values = sorted(vals)
        caps_e, _, _ = _entry_caps(
            skew, min_domains, self_match, values, counts_e, present_e
        )
        dead = _mark_dead_domains(dead, caps_e, values, vals, n_groups)
        if self_match:
            others.append(
                _partition_entry(
                    ("spread", idx), skew, {v: vals[v] for v in values},
                    caps_e, values, counts_e,
                )
            )
    return {
        "others": others,
        "dead": dead if dead.any() else None,
    }




def _expand_spread_rows(  # lint: allow-complexity — per-domain chunking: each guard is a documented spread rule
    snap, profiles, row_idx, row_weight, label_dicts_fn, census=None
):
    """Topology spread (DoNotSchedule, non-hostname keys): partition each
    constrained row's weight into per-domain sub-rows, WATER-FILLED
    against the existing matching-pod counts per domain (DomainCensus).

    The solver assigns a whole weighted row to one group, so skew is
    enforced where it binds — in the GROUP choice: a domain is a distinct
    value of the topologyKey among the group-label INTERSECTIONS (a group
    spanning zones has no single domain value and is excluded, like a node
    missing the key is excluded by the kube-scheduler's PodTopologySpread
    filter). New replicas fill the least-loaded domains first — the only
    incremental order the scheduler's skew check always admits — so final
    totals are as balanced as the existing counts allow, satisfying any
    maxSkew >= 1. Domains among FILTER-PASSING live nodes that no
    candidate group serves freeze the global minimum: each eligible
    domain is then capped at (outside minimum + maxSkew) total, exactly
    the scheduler's skew bound against a domain a scale-up cannot fill.
    When minDomains exceeds the eligible domain count, the scheduler's
    global-minimum-0 rule applies — at most (maxSkew - existing) new
    pods per domain, the excess unschedulable. A pod that does NOT match
    its own constraint's selector (selfMatch false, incl. nil selector)
    never moves the counts: domains whose existing skew already exceeds
    the bound are excluded, the rest split balanced.

    Approximations, all conservative for a scale-up signal (may spread
    wider / mark more unschedulable than a lopsided-but-legal placement,
    never the reverse): maxSkew slack beyond 1 is not exploited when
    counts are level; with multiple constrained keys the split runs on
    the FIRST (key, selector) entry while the others are enforced
    through key-presence exclusion, zero-capacity dead masks, and the
    per-chunk domain PARTITION pass (_partition_chunks) that
    water-fills each chunk across their domains under their skews and
    remaining capacities; rows of one workload consume a SHARED budget
    in canonical content order; without a census (hand-built snapshot
    paths) counts are zero and the splits are plain balanced.

    Returns (row_idx, row_weight, spread_forbidden[rows, T]-or-None);
    unconstrained snapshots pass through untouched.
    """
    shapes = snap.spread_shapes
    if (
        len(row_idx) == 0
        or snap.spread_id is None
        or shapes is None
        or not (snap.spread_id[row_idx] != 0).any()
    ):
        return row_idx, row_weight, None

    n_groups = len(profiles)
    label_dicts = label_dicts_fn()
    live_ids = snap.spread_id[row_idx].copy()
    # rows whose self-anti-affinity carries a domain key are split
    # 1-per-domain by _expand_anti_rows — the most balanced placement a
    # topology key admits, so a second spread split would double-place
    # the weight; the spread keys still contribute key-presence
    # exclusion through the anti mask (docs/OPERATIONS.md)
    if snap.anti_id is not None and snap.anti_shapes is not None:
        anti_live = snap.anti_id[row_idx]
        domain_capped = np.array(
            [
                bool(snap.anti_shapes[a]) and bool(snap.anti_shapes[a][1])
                for a in anti_live
            ]
        )
        live_ids[domain_capped] = 0
        if not (live_ids != 0).any():
            return row_idx, row_weight, None

    # per live shape: (namespace, entries, ordered domain values,
    # [D, T] per-domain forbidden-mask matrix — built ONCE per shape,
    # rows are emitted by reference and only copied by the final stack)
    plan: Dict[int, tuple] = {}
    for s in np.unique(live_ids):
        shape = shapes[s]
        if not shape:
            continue
        namespace, entries = shape
        keys = [entry[0] for entry in entries]
        split_key = entries[0][0]
        domains: Dict[str, list] = {}
        eligible = []
        for t, labels in enumerate(label_dicts):
            if all(key in labels for key in keys):
                eligible.append(t)
                domains.setdefault(labels[split_key], []).append(t)
        values = sorted(domains)
        masks = np.ones((len(values), n_groups), bool)
        for rank, value in enumerate(values):
            masks[rank, domains[value]] = False
        plan[int(s)] = (namespace, entries, values, masks, eligible)

    all_forbidden = np.ones(n_groups, bool)
    no_forbidden = np.zeros(n_groups, bool)
    # per-(shape, filter) cap VIEWS are immutable; consumption lives in
    # per-WORKLOAD (per-sid) shared ledgers, so rows with DIFFERENT node
    # filters still spend one budget — placements count against the
    # workload's skew regardless of which filter admitted them (r3 code
    # review). Multi-row shapes process in canonical content order so
    # the hand-out never depends on arena-local numbering (the
    # path-stability rule _expand_anti_rows already follows); the
    # canonical key is only computed for shapes that actually have
    # several rows (it walks every universe — too hot for the common
    # one-row-per-workload tick).
    view_memo: Dict[tuple, dict] = {}
    ledgers: Dict[int, dict] = {}
    anti_dead_memo: Dict[int, np.ndarray] = {}
    sid_rows = collections.Counter(
        int(s) for s in live_ids if s and plan.get(int(s)) is not None
    )
    order = sorted(
        range(len(live_ids)),
        key=lambda i: (
            (0, (), i)
            if not live_ids[i] or plan.get(int(live_ids[i])) is None
            else (
                1,
                int(live_ids[i]),
                _canonical_row_key(snap, row_idx[i])
                if sid_rows[int(live_ids[i])] > 1
                else (),
            )
        ),
    )
    out_idx, out_weight, out_forbidden = [], [], []
    for i in order:
        sid = live_ids[i]
        entry = plan.get(int(sid))
        if entry is None:
            out_idx.append(row_idx[i])
            out_weight.append(row_weight[i])
            out_forbidden.append(no_forbidden)
            continue
        namespace, entries, values, masks, eligible = entry
        weight = int(row_weight[i])
        if not values or weight == 0:
            # no group exposes the key(s): unschedulable by spread —
            # keep the row, forbid everything, so the pods are COUNTED
            out_idx.append(row_idx[i])
            out_weight.append(row_weight[i])
            out_forbidden.append(all_forbidden)
            continue
        d = len(values)
        row_filter = (
            _row_node_filter(snap, row_idx[i])
            if census is not None
            else (None, None)
        )
        # the anti stage's row-independent exclusions (co pins, foreign
        # terms) feed the caps as dead groups, so a domain the anti
        # masks will forbid freezes the minimum instead of absorbing a
        # balanced chunk (found by the soundness fuzz); domain-capped
        # anti rows never reach here (their split is the anti rule's)
        anti_sid = (
            int(snap.anti_id[row_idx[i]])
            if snap.anti_id is not None and snap.anti_shapes is not None
            else 0
        )
        anti_dead = None
        if anti_sid and snap.anti_shapes[anti_sid]:
            if anti_sid in anti_dead_memo:
                anti_dead = anti_dead_memo[anti_sid]
            else:
                anti_dead = _anti_frozen_mask(
                    snap.anti_shapes[anti_sid], census, label_dicts,
                    n_groups,
                )
                if not anti_dead.any():
                    # a shape imposing no exclusions must not fragment
                    # the view memo or tax every chunk with a
                    # copy-and-OR of an all-False mask
                    anti_dead = None
                anti_dead_memo[anti_sid] = anti_dead
        view_key = (
            int(sid),
            row_filter[0],
            anti_sid if anti_dead is not None else 0,
        )
        view = view_memo.get(view_key)
        if view is None:
            view = _spread_state(
                namespace, entries, values, census, row_filter,
                label_dicts, eligible, extra_dead=anti_dead,
            )
            view_memo[view_key] = view
        ledger = ledgers.get(int(sid))
        if ledger is None:
            ledger = {
                "placed": np.zeros(d, np.int64),
                "counts": view["counts"].copy(),
                "others_placed": {},
            }
            ledgers[int(sid)] = ledger
        caps = np.minimum(
            np.clip(
                np.minimum(view["static"], view["budget"])
                - ledger["placed"],
                0,
                None,
            ),
            weight,
        )
        schedulable = min(weight, int(caps.sum()))
        # content-keyed remainder rotation (see _water_fill)
        seed = weight + int(
            np.ascontiguousarray(snap.requests[row_idx[i]])
            .view(np.uint8)
            .sum()
        )
        additions = _water_fill(
            ledger["counts"], caps, schedulable, seed
        )
        pieces = _partition_chunks(
            additions, masks, view, ledger["others_placed"], n_groups,
            seed,
        )
        # consume the shared ledgers with the KEPT counts (the
        # partition may shed part of a chunk): a later row of this
        # workload sees what THIS row placed — selfMatch placements
        # also accumulate into the fill-order counts, exactly like the
        # scheduler's sequential skew accounting
        kept = np.zeros(d, np.int64)
        for rank, count, _extra in pieces:
            kept[rank] += count
        ledger["placed"] = ledger["placed"] + kept
        if view["first_selfmatch"]:
            ledger["counts"] = ledger["counts"] + kept
        dead = view["dead"]
        placed = 0
        for rank, count, extra in pieces:
            placed += count
            forbidden = masks[rank]
            if dead is not None or extra is not None:
                forbidden = forbidden.copy()
                if dead is not None:
                    forbidden |= dead
                if extra is not None:
                    forbidden |= extra
            out_idx.append(row_idx[i])
            out_weight.append(np.int32(count))
            out_forbidden.append(forbidden)
        if placed < weight:
            out_idx.append(row_idx[i])
            out_weight.append(np.int32(weight - placed))
            out_forbidden.append(all_forbidden)
    return (
        np.asarray(out_idx, np.intp),
        np.asarray(out_weight, np.int32),
        np.stack(out_forbidden) if out_forbidden else None,
    )


