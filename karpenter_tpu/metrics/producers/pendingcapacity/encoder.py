"""THE single encoder: store snapshot -> fixed-shape solver operands
(group arrays, deduplicated weighted shape rows, spread/anti row
expansions, soft-constraint scores). Output equality across cache
states is pinned by the oracle suites."""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.api.core import (
    Taint,
    capacity_tier_of,
    is_ready_and_schedulable,
    matches_affinity_shape,
    matches_selector,
)
from karpenter_tpu.constraints import compiler as _cc
from karpenter_tpu.ops import binpack as B
from karpenter_tpu.resilience import CircuitBreaker
from karpenter_tpu.store.columnar import RESOURCE_PODS
from karpenter_tpu.utils.functional import pad_to_multiple

from .anti import _expand_anti_rows
from .constants import (
    DEFAULT_PODS_PER_NODE,
    GROUP_PAD,
    LABEL_PAD,
    POD_PAD,
    RESOURCE_PAD,
    RESOURCES_BASE,
    TAINT_PAD,
)
from .scoring import _score_rows
from .spread import _expand_spread_rows

_pad = pad_to_multiple

# -- constraint plane (karpenter_tpu/constraints) ----------------------------
# The declarative-constraint compile is gated by a circuit breaker plus
# the constraints.mask fault point: a failing compile NEVER blocks the
# solve — the tick falls back to the unconstrained-but-feasible encode
# (counted below, breaker fed) and recovers to the constrained fixed
# point once the fault clears (half-open probe succeeds -> closed).

_constraint_breaker = CircuitBreaker(failure_threshold=3, reset_s=30.0)
# degraded: the LAST constrained admission fell back — the feed memo's
# admission epoch keys on it to keep retrying until the compile heals
constraint_stats = {
    "compiles": 0,
    "fallbacks": 0,
    "short_circuits": 0,
    "degraded": False,
    "published_compiles": 0,
    "published_fallbacks": 0,
}


def reset_constraint_state() -> None:
    """Test / recovery-boot seam: fresh breaker, zeroed counters."""
    global _constraint_breaker
    _constraint_breaker = CircuitBreaker(failure_threshold=3, reset_s=30.0)
    constraint_stats.update(
        compiles=0, fallbacks=0, short_circuits=0, degraded=False,
        published_compiles=0, published_fallbacks=0,
    )


def _constraints_admitted() -> bool:
    """One breaker-gated admission per constrained encode. False means
    THIS tick encodes unconstrained (the never-block fallback); the
    breaker turns a persistently failing compile into cheap
    short-circuits and grants one probe per reset window, so clearing
    the fault restores the constrained fixed point."""
    from karpenter_tpu.faults import inject

    if not _constraint_breaker.allow():
        constraint_stats["short_circuits"] += 1
        constraint_stats["fallbacks"] += 1
        constraint_stats["degraded"] = True
        return False
    try:
        inject("constraints.mask")
    except Exception as e:
        _constraint_breaker.record_failure(type(e).__name__)
        constraint_stats["fallbacks"] += 1
        constraint_stats["degraded"] = True
        return False
    _constraint_breaker.record_success()
    constraint_stats["compiles"] += 1
    constraint_stats["degraded"] = False
    return True

def _profile_candidates(nodes: List, selector: Dict[str, str]) -> List:
    """Ready+schedulable matching nodes, falling back to ANY matching
    node when none are ready (a group scaled to zero still needs a
    shape)."""
    matching = [
        n for n in nodes if matches_selector(n.metadata.labels, selector)
    ]
    ready = [n for n in matching if is_ready_and_schedulable(n)]
    return ready or matching


def _group_profile(
    nodes: List, selector: Dict[str, str]
) -> Tuple[Dict[str, float], set, set]:
    """(allocatable by resource name, labels set, taints set) for one group.

    Ready+schedulable nodes define the group's shape; when the group is empty
    we fall back to any node matching the selector (a group scaled to zero
    still needs a shape to reason about — a limitation shared with every
    pending-pods autoscaler that lacks instance-type metadata).

    The shape is the elementwise MIN over candidate nodes (a resource a node
    lacks counts as 0): in a heterogeneous group, claiming the max across
    nodes would invent a phantom node shape no real scale-up can deliver,
    and the signal would demand nodes forever without ever scheduling the
    pod. Min keeps the promise: any node the group adds can host what we
    report feasible.

    `nodes` is the full node list (listed ONCE per solve by the caller);
    selector filtering happens here to avoid O(groups) store scans.
    """
    candidates = _profile_candidates(nodes, selector)
    alloc: Dict[str, float] = {}
    labels: set = set()
    taints: set = set()
    for i, node in enumerate(candidates):
        node_alloc = {
            r: q.to_float() for r, q in node.status.allocatable.items()
        }
        if i == 0:
            alloc = node_alloc
        else:
            alloc = {
                r: min(alloc.get(r, 0.0), node_alloc.get(r, 0.0))
                for r in set(alloc) | set(node_alloc)
            }
        node_labels = set(node.metadata.labels.items())
        labels = node_labels if i == 0 else (labels & node_labels)
        # hard taints (NoSchedule/NoExecute) exclude pods;
        # PreferNoSchedule is carried too but only SCORES (the
        # kube-scheduler's TaintToleration plugin — scoring.py): the
        # encoder's intolerance bitset filters to hard effects
        taints |= {
            (t.key, t.value, t.effect)
            for t in node.spec.taints
            if t.effect
            in ("NoSchedule", "NoExecute", "PreferNoSchedule")
        }
    if candidates and alloc.get(RESOURCE_PODS, 0.0) <= 0:
        alloc[RESOURCE_PODS] = DEFAULT_PODS_PER_NODE
    return alloc, labels, taints




def _group_arrays(profiles, resources, taint_universe, label_universe,
                  n_groups, n_resources, n_taints, n_labels):
    group_allocatable = np.zeros((n_groups, n_resources), np.float32)
    group_taints = np.zeros((n_groups, n_taints), bool)
    group_labels = np.zeros((n_groups, n_labels), bool)
    for t, (alloc, labels, taints) in enumerate(profiles):
        for r, resource in enumerate(resources):
            group_allocatable[t, r] = alloc.get(resource, 0.0)
        for taint, k in taint_universe.items():
            group_taints[t, k] = taint in taints
        for item, l in label_universe.items():
            group_labels[t, l] = item in labels
    return group_allocatable, group_taints, group_labels


def _dedup_rows(snap):
    """Collapse identical pod rows into (row indices, multiplicities).

    Two pods with the same (requests vector, required labels, toleration
    shape, validity) are interchangeable to every solver stage — same
    feasibility row, same first-feasible group, same size bucket — so the
    solve is exact over distinct shapes weighted by count. This is what
    makes the device upload O(distinct shapes), not O(pods): fleets are
    dominated by replicated workloads (Deployments/Jobs stamp identical
    pod templates).

    Raw-byte uniqueness on the concatenated row bytes: float bit-equality
    only (never merges distinct values; -0.0 vs 0.0 over-splits, which is
    merely suboptimal, never wrong).

    Fast path: cache-produced snapshots carry the INCREMENTALLY-maintained
    dedup (store/columnar.PendingPodCache._dedup_slots) — one rep row +
    count per distinct live shape, maintained at watch-event time. Only
    the S rep rows (distinct shapes, fleet-scale constant) are byte-sorted
    here for deterministic row order; the np.unique-over-all-rows below is
    the fallback for hand-built snapshots, and was ~60 ms/tick of argsort
    at 100k pods. The incremental dedup indexes live slots only; free
    (valid=False, zeroed) rows are dropped rather than collapsed into a
    zero row — output-equal, since invalid rows never contribute to any
    solver aggregate.
    """
    hi = snap.requests.shape[0]
    if hi == 0 or (snap.dedup_idx is not None and len(snap.dedup_idx) == 0):
        # hi > 0 with an empty dedup is the pending set draining to zero
        # while freed arena rows remain — the normal all-pods-scheduled
        # state, not an error
        return np.zeros(0, np.intp), np.zeros(0, np.int32)

    if snap.dedup_idx is not None:
        # O(S log S), S tiny
        order = np.argsort(_row_bytes(snap, snap.dedup_idx))
        return snap.dedup_idx[order], snap.dedup_weight[order]

    _, idx, counts = np.unique(
        _row_bytes(snap, slice(None)), return_index=True, return_counts=True
    )
    return idx, counts.astype(np.int32)


def _row_bytes(snap, idx):
    """Concatenated raw bytes of the given snapshot rows, one void scalar
    per row — the canonical sort/uniqueness key of _dedup_rows."""
    # idx=slice(None) gives zero-copy views (the arrays are already
    # contiguous); index arrays (the fast path's rep rows) gather
    hi = snap.requests.shape[0]
    n = hi if isinstance(idx, slice) else len(idx)
    parts = [
        np.ascontiguousarray(snap.requests[idx])
        .view(np.uint8)
        .reshape(n, -1),
        np.ascontiguousarray(snap.required[idx])
        .view(np.uint8)
        .reshape(n, -1),
        np.ascontiguousarray(snap.shape_id[idx])
        .view(np.uint8)
        .reshape(n, -1),
        snap.valid[idx].astype(np.uint8).reshape(n, 1),
    ]
    if snap.priority is not None:
        # priority is row identity (steering + evictability): equal-spec
        # rows of different PriorityClasses must sort/dedup apart
        parts.append(
            np.ascontiguousarray(snap.priority[idx])
            .view(np.uint8)
            .reshape(n, -1)
        )
    for ids in (
        snap.affinity_id,
        snap.preferred_id,
        snap.spread_id,
        snap.anti_id,
        snap.soft_spread_id,
        snap.soft_anti_id,
    ):
        if ids is not None:
            parts.append(
                np.ascontiguousarray(ids[idx]).view(np.uint8).reshape(n, -1)
            )
    rows = np.ascontiguousarray(np.concatenate(parts, axis=1))
    return rows.view([("k", np.void, rows.shape[1])]).ravel()


def _dedup_rows_keyed(snap):
    """(row_idx, row_weight, keys): _dedup_rows plus the canonical sparse
    dedup keys (store/columnar.PendingSnapshot.dedup_keys) reordered into
    the same byte-sorted row order. keys is None when the snapshot lacks
    the incremental dedup — the delta layer then has no stable identity
    to diff on and falls back to a full encode."""
    if (
        snap.dedup_idx is None
        or snap.dedup_keys is None
        or snap.requests.shape[0] == 0
        or len(snap.dedup_idx) == 0
    ):
        row_idx, row_weight = _dedup_rows(snap)
        keys = (
            ()
            if snap.dedup_keys is not None and len(row_idx) == 0
            else None
        )
        return row_idx, row_weight, keys
    order = np.argsort(_row_bytes(snap, snap.dedup_idx))
    keys = tuple(snap.dedup_keys[i] for i in order)
    return snap.dedup_idx[order], snap.dedup_weight[order], keys


def _dedup_rows_constrained(snap, membership):
    """_dedup_rows with group membership appended to the row identity.

    Pod labels are deliberately NOT part of the incremental dedup key
    (store/columnar.dedup_key) — unconstrained fleets must not split
    otherwise-identical rows on label noise. When constraint groups are
    live, two spec-identical pods in DIFFERENT groups are no longer
    interchangeable, so the constrained encode re-dedups over
    (row bytes, membership) with np.unique here. O(N log N) over live
    rows, paid only by constraint-carrying producers."""
    hi = snap.requests.shape[0]
    if hi == 0:
        return np.zeros(0, np.intp), np.zeros(0, np.int32)
    rows = _row_bytes(snap, slice(None))
    keyed = np.empty(
        hi,
        dtype=[("k", rows.dtype["k"]), ("m", np.int32)],
    )
    keyed["k"] = rows["k"]
    keyed["m"] = np.asarray(membership, np.int32)
    _, idx, counts = np.unique(
        keyed, return_index=True, return_counts=True
    )
    return idx, counts.astype(np.int32)




def _resource_universe(snap, profiles):
    """(resources list, resource_index, pod slot): base resources plus
    every extended resource seen in requests or allocatable, the 'pods'
    slot axis always LAST (each pod occupies exactly 1)."""
    extended = {
        r for r in snap.resources
        if r not in RESOURCES_BASE and r != RESOURCE_PODS
    }
    for alloc, _, _ in profiles:
        extended |= {
            r for r in alloc
            if r not in RESOURCES_BASE and r != RESOURCE_PODS
        }
    resources = [*RESOURCES_BASE, *sorted(extended), RESOURCE_PODS]
    resource_index = {r: idx for idx, r in enumerate(resources)}
    return resources, resource_index, resources.index(RESOURCE_PODS)


def _pod_arrays(snap, row_idx, row_weight, resources, resource_index,
                pod_slot, n_pods, n_resources, n_taints, n_labels,
                taint_universe):
    """The per-pod solver operands, gathered in bulk from the snapshot:
    requests, validity, required-label bitset, intolerance bitset (one
    evaluation per DISTINCT toleration shape, gathered to rows by
    shape id), and the dedup multiplicities (padding rows weigh
    nothing)."""
    hi = len(row_idx)
    pod_requests = np.zeros((n_pods, n_resources), np.float32)
    pod_valid = np.zeros(n_pods, bool)
    pod_required = np.zeros((n_pods, n_labels), bool)
    pod_intolerant = np.zeros((n_pods, n_taints), bool)
    pod_weight = np.zeros(n_pods, np.int32)
    if hi:
        valid = snap.valid[row_idx]
        cols = np.array(
            [resource_index[r] for r in snap.resources], np.intp
        )
        pod_requests[:hi, cols] = snap.requests[row_idx]
        pod_requests[:hi, pod_slot] = valid.astype(np.float32)
        pod_valid[:hi] = valid
        pod_weight[:hi] = row_weight
        if snap.labels:
            pod_required[:hi, : len(snap.labels)] = snap.required[row_idx]
        if snap.shape_tolerations:
            taint_objects = {
                k: Taint(key=taint[0], value=taint[1], effect=taint[2])
                for taint, k in taint_universe.items()
            }
            rows = np.zeros((len(snap.shape_tolerations), n_taints), bool)
            for s, tolerations in enumerate(snap.shape_tolerations):
                for k, taint in taint_objects.items():
                    rows[s, k] = not any(
                        tol.tolerates(taint) for tol in tolerations
                    )
            pod_intolerant[:hi] = rows[snap.shape_id[row_idx]]
    return pod_requests, pod_valid, pod_required, pod_intolerant, pod_weight


def _affinity_forbidden(snap, row_idx, group_label_dicts, n_pods,
                        n_groups):
    """Required node affinity: matchExpression semantics (In/NotIn/
    Exists/DoesNotExist/Gt/Lt, OR'd terms) don't factor into the
    conjunctive required-label bitset, so each DISTINCT affinity shape
    is evaluated host-side against each group's label assignment (the
    profile label set — the INTERSECTION of node labels, i.e. the same
    conservative single-node shape the min-allocatable uses;
    heterogeneous groups may over-admit negative operators, the caveat
    _group_profile documents for resources) and the S_a x T verdicts
    gather to rows. None when no pod constrains affinity — the common
    fleet pays nothing. Gated on LIVE rows (shape id 0 =
    unconstrained): the shape registry retains entries until
    compaction, and a long-gone affinity Job must not keep the whole
    fleet on the masked (extra-operand) kernel path."""
    hi = len(row_idx)
    shapes = snap.affinity_shapes
    live = (
        snap.affinity_id[row_idx]
        if hi and snap.affinity_id is not None and shapes is not None
        else None
    )
    if live is None or not (live != 0).any():
        return None
    allowed = np.ones((len(shapes), n_groups), bool)
    for s in np.unique(live):  # only shapes in live use
        shape = shapes[s]
        if not shape:
            continue
        for t, labels in enumerate(group_label_dicts()):
            allowed[s, t] = matches_affinity_shape(labels, shape)
    forbidden = np.zeros((n_pods, n_groups), bool)
    forbidden[:hi] = ~allowed[live]
    return forbidden


def _taint_universe(profiles) -> Dict[tuple, int]:
    """Distinct HARD taints across group profiles -> bitset slot. Soft
    (PreferNoSchedule) taints ride the profiles into the scoring plugin
    and must never gate feasibility, so they never join the bitset."""
    universe: Dict[tuple, int] = {}
    for _, _, taints in profiles:
        for taint in sorted(taints):
            if taint[2] == "PreferNoSchedule":
                continue
            if taint not in universe:
                universe[taint] = len(universe)
    return universe


def _priority_tier_operands(snap, profiles, row_idx, n_pods, n_groups):
    """Priority + capacity-tier operands (ops/binpack.py steering,
    ops/preempt.py evictability) — each absent unless the fleet
    actually carries it (a nonzero-priority pod / a spot-labeled
    group), so priority-free fleets encode byte-identically to before
    these columns existed."""
    hi = len(row_idx)
    pod_priority = None
    if (
        snap.priority is not None
        and hi
        and bool((snap.priority[row_idx] != 0).any())
    ):
        pod_priority = np.zeros(n_pods, np.int32)
        pod_priority[:hi] = snap.priority[row_idx]
    group_tier = None
    tiers = [capacity_tier_of(labels) for _, labels, _ in profiles]
    if any(tiers):
        group_tier = np.zeros(n_groups, np.int32)
        group_tier[: len(profiles)] = tiers
    return pod_priority, group_tier


def _encode_full(  # lint: allow-complexity — the encode spine: one arm per optional operand family (priority/tier/spread/constraints)
    snap, profiles, with_rows: bool = False, census=None, constraints=None
):
    """Snapshot (store/columnar.PendingSnapshot) -> solver inputs, with
    rows DEDUPLICATED into distinct pod shapes + multiplicities
    (pod_weight) — see _dedup_rows. Every solve path (feed, pod_cache,
    oracle store.list) flows through here, so outputs stay identical
    across paths by construction.

    All per-pod work here is bulk numpy (column gathers, row gathers by
    toleration-shape id); the only Python loops left are over universes —
    resources, group profiles, taints, distinct toleration shapes — whose
    cardinalities are fleet-scale constants, not pod counts.
    """
    # group label dicts: built at most once, shared by the spread
    # expansion and the affinity/preferred evaluation blocks below
    label_dicts_box: list = []

    def group_label_dicts():
        if not label_dicts_box:
            label_dicts_box.append(
                [dict(labels) for _, labels, _ in profiles]
            )
        return label_dicts_box[0]

    # declarative constraint groups (karpenter_tpu/constraints): gated
    # through the breaker + fault point; denied admission encodes this
    # tick unconstrained (never-block fallback)
    membership = None
    if constraints and _constraints_admitted():
        if snap.labels_id is not None and snap.label_sets:
            membership = _cc.compile_membership(
                snap.label_sets, snap.labels_id, constraints
            )
        else:
            membership = np.zeros(snap.requests.shape[0], np.int32)

    if membership is not None and bool((membership != 0).any()):
        # membership joins the row identity: spec-identical pods in
        # different groups are no longer interchangeable
        row_idx, row_weight = _dedup_rows_constrained(snap, membership)
    else:
        row_idx, row_weight = _dedup_rows(snap)
    # hard topology spread: constrained rows split into balanced
    # per-domain sub-rows (same source row gathered more than once, each
    # chunk masked to its domain's groups) — the device program is
    # unchanged, spread rides the existing forbidden-mask operand
    row_idx, row_weight, spread_forbidden = _expand_spread_rows(
        snap, profiles, row_idx, row_weight, group_label_dicts,
        census=census,
    )
    # required self pod-(anti-)affinity: hostname rows flag the
    # pod_exclusive operand, domain keys cap one replica per domain
    # (further sub-row expansion; the spread mask rides through)
    row_idx, row_weight, spread_forbidden, row_exclusive = (
        _expand_anti_rows(
            snap, profiles, row_idx, row_weight, spread_forbidden,
            group_label_dicts, census=census,
        )
    )

    # compile the declarative groups over the final row set; the
    # spread-quota pre-split (compiled.rep) regathers every per-row
    # array built so far
    compiled = None
    if membership is not None:
        compiled = _cc.compile_rows(
            membership[row_idx],
            row_weight,
            snap.valid[row_idx],
            profiles,
            constraints,
        )
        row_idx = row_idx[compiled.rep]
        row_weight = compiled.row_weight
        if spread_forbidden is not None:
            spread_forbidden = spread_forbidden[compiled.rep]
        if row_exclusive is not None:
            row_exclusive = row_exclusive[compiled.rep]
    hi = len(row_idx)

    resources, resource_index, pod_slot = _resource_universe(
        snap, profiles
    )
    n_resources = _pad(len(resources), RESOURCE_PAD)

    taint_universe = _taint_universe(profiles)
    label_universe = {item: l for l, item in enumerate(snap.labels)}

    n_pods = _pad(hi, POD_PAD)
    n_groups = _pad(len(profiles), GROUP_PAD)
    n_taints = _pad(len(taint_universe), TAINT_PAD)
    n_labels = _pad(len(label_universe), LABEL_PAD)

    (pod_requests, pod_valid, pod_required, pod_intolerant,
     pod_weight) = _pod_arrays(
        snap, row_idx, row_weight, resources, resource_index, pod_slot,
        n_pods, n_resources, n_taints, n_labels, taint_universe,
    )

    group_allocatable, group_taints, group_labels = _group_arrays(
        profiles, resources, taint_universe, label_universe,
        n_groups, n_resources, n_taints, n_labels,
    )

    pod_group_forbidden = _affinity_forbidden(
        snap, row_idx, group_label_dicts, n_pods, n_groups
    )

    # Topology spread + self pod-(anti-)affinity: OR the per-sub-row
    # masks into the same forbidden operand the affinity path uses
    # (padding groups are all-zero allocatable and already infeasible,
    # so mask width T_real suffices)
    if spread_forbidden is not None:
        if pod_group_forbidden is None:
            pod_group_forbidden = np.zeros((n_pods, n_groups), bool)
        pod_group_forbidden[:hi, : len(profiles)] |= spread_forbidden

    # declarative anti-affinity members take whole nodes too: OR into
    # the same exclusivity rows the hostname self-anti path flags
    if compiled is not None and compiled.exclusive is not None:
        row_exclusive = (
            compiled.exclusive
            if row_exclusive is None
            else (row_exclusive | compiled.exclusive)
        )

    # hostname self-anti-affinity rows take a whole node each — absent
    # unless some live pod actually carries the constraint
    pod_exclusive = None
    if row_exclusive is not None and row_exclusive.any():
        pod_exclusive = np.zeros(n_pods, bool)
        pod_exclusive[:hi] = row_exclusive

    # Scoring operand (ops/binpack.py pod_group_score): the kube-
    # scheduler's scoring plugins modeled over groups — preferred node
    # affinity, ScheduleAnyway spread, preferred self pod-(anti-)
    # affinity — absent unless some live pod actually prefers
    pod_group_score = _score_rows(
        snap, profiles, row_idx, group_label_dicts, census,
        n_pods, n_groups,
    )

    pod_priority, group_tier = _priority_tier_operands(
        snap, profiles, row_idx, n_pods, n_groups
    )

    # constraint-plane operands, padded to the bucketed extents (padding
    # pod rows are invalid and weightless; padding groups are all-zero
    # allocatable — both inert to every mask term). Each operand pair
    # stays None unless the compile produced it, so constraint-free
    # fleets ship today's wire byte for byte.
    pod_claim = group_reservation = None
    pod_pack_class = None
    pod_spread_slot = group_domain = spread_cap = None
    if compiled is not None:
        if compiled.claim is not None:
            pod_claim = np.zeros(n_pods, np.int32)
            pod_claim[:hi] = compiled.claim
            group_reservation = np.zeros(n_groups, np.int32)
            group_reservation[: len(profiles)] = (
                compiled.group_reservation
            )
        if compiled.pack_class is not None:
            pod_pack_class = np.zeros(
                (n_pods, compiled.pack_class.shape[1]), bool
            )
            pod_pack_class[:hi] = compiled.pack_class
        if compiled.spread_slot is not None:
            pod_spread_slot = np.zeros(n_pods, np.int32)
            pod_spread_slot[:hi] = compiled.spread_slot
            group_domain = np.zeros(n_groups, np.int32)
            group_domain[: len(profiles)] = compiled.group_domain
            spread_cap = compiled.spread_cap.copy()

    inputs = B.BinPackInputs(
        pod_requests=pod_requests,
        pod_valid=pod_valid,
        pod_intolerant=pod_intolerant,
        pod_required=pod_required,
        group_allocatable=group_allocatable,
        group_taints=group_taints,
        group_labels=group_labels,
        pod_weight=pod_weight,
        pod_group_forbidden=pod_group_forbidden,
        pod_group_score=pod_group_score,
        pod_exclusive=pod_exclusive,
        pod_priority=pod_priority,
        group_tier=group_tier,
        pod_claim=pod_claim,
        group_reservation=group_reservation,
        pod_pack_class=pod_pack_class,
        pod_spread_slot=pod_spread_slot,
        group_domain=group_domain,
        spread_cap=spread_cap,
    )
    if with_rows:
        # the simulation API maps per-row solver outputs back to pods:
        # row i of `inputs` gathers snapshot row row_idx[i] (an arena
        # slot) with multiplicity row_weight[i]
        return inputs, row_idx, row_weight
    return inputs


# -- incremental (delta) encoding --------------------------------------------


class ResidentPlan:
    """The changed-row map between two consecutive delta encodes — what
    the device-resident fleet state (solver/resident.py) scatters
    instead of re-uploading the full operand stack.

    `prev` is the PREVIOUS tick's BinPackInputs (held strongly: the
    plan is only useful while a resident buffer keyed on that identity
    exists); `rows` are the positions whose spliced operand rows
    (requests/valid/required/intolerant) differ from prev's, and
    `weight_rows` the positions whose dedup multiplicity moved (a
    scaled Deployment changes weights without changing any key). Both
    are exact: a row is listed iff its bytes changed, so scattering
    exactly these rows reproduces a cold full upload bit for bit."""

    __slots__ = ("prev", "rows", "weight_rows")

    def __init__(self, prev, rows, weight_rows):
        self.prev = prev
        self.rows = np.asarray(rows, np.int32)
        self.weight_rows = np.asarray(weight_rows, np.int32)


# id(inputs) -> (weakref-to-inputs, ResidentPlan), written by every
# SnapshotDeltaCache instance and read by ResidentFleetState.
# BinPackInputs is an eq-dataclass (unhashable), so the registry keys
# on id() with a weakref guard: the stored ref must still resolve to
# the SAME object, and a finalizer removes the entry on GC so a reused
# id can never alias a dead plan. Registering a successor plan drops
# the predecessor's entry, so prev-chains never grow past one hop.
_plan_registry: Dict[int, tuple] = {}
# RLock, not Lock: the GC can run a plan finalizer (_drop_plan) on
# whatever thread triggered collection — including one that is already
# inside _register_plan holding this lock
_plan_lock = threading.RLock()


def resident_plan(inputs) -> Optional["ResidentPlan"]:
    """The changed-row plan for a delta-encoded inputs object, or None
    (cold/full encode, or a non-delta caller)."""
    with _plan_lock:
        entry = _plan_registry.get(id(inputs))
        if entry is None or entry[0]() is not inputs:
            return None
        return entry[1]


def _drop_plan(key: int) -> None:
    with _plan_lock:
        _plan_registry.pop(key, None)


def _register_plan(inputs, plan: "ResidentPlan") -> None:
    with _plan_lock:
        _plan_registry[id(inputs)] = (weakref.ref(inputs), plan)
        # cap the identity chain: the predecessor's own plan (if any)
        # is unreachable through a resident entry once this successor
        # exists
        _plan_registry.pop(id(plan.prev), None)
    weakref.finalize(inputs, _drop_plan, id(inputs))


def reset_resident_plans() -> None:
    """Recovery-boot seam companion to SnapshotDeltaCache.reset: a plan
    computed against pre-reset state must not splice into post-reset
    resident buffers."""
    with _plan_lock:
        _plan_registry.clear()


class _DeltaEntry:
    """One cached encode per (group-set, universe) key: the canonical
    sorted dedup keys, their row positions, the operand arrays those
    positions index, and the BinPackInputs built from them. Arrays are
    never mutated after construction — a delta builds NEW arrays and
    splices cached rows across, so inputs objects handed to callers (and
    any identity-keyed device cache holding them) stay frozen."""

    __slots__ = (
        "profiles", "resources", "resource_index", "pod_slot",
        "taint_universe", "keys", "pos", "row_weight",
        "n_pods", "n_resources", "n_taints", "n_labels",
        "inputs",
    )

    def __init__(self, keys, row_weight, n_pods, inputs):
        self.keys = keys
        self.pos = {key: i for i, key in enumerate(keys)}
        self.row_weight = np.asarray(row_weight)
        self.n_pods = n_pods
        self.inputs = inputs

    def successor(self, keys, row_weight, n_pods, inputs) -> "_DeltaEntry":
        """Next-tick entry sharing every universe-derived field (equal
        by the eligibility checks) — ONE construction path, so a field
        added to the entry can't be populated on the cold path only."""
        entry = _DeltaEntry(keys, row_weight, n_pods, inputs)
        entry.profiles = self.profiles
        entry.resources = self.resources
        entry.resource_index = self.resource_index
        entry.pod_slot = self.pod_slot
        entry.taint_universe = self.taint_universe
        entry.n_resources = self.n_resources
        entry.n_taints = self.n_taints
        entry.n_labels = self.n_labels
        return entry


class SnapshotDeltaCache:
    """Delta layer over _encode_full: caches the last encoded snapshot
    per (group-set, resource-universe) key and answers the next tick by
    splicing unchanged rows instead of rebuilding _pod_arrays /
    _group_arrays from scratch.

    Output parity is BIT-IDENTICAL to a full re-encode, by construction:

      * rows are matched on the CANONICAL sparse dedup key
        (store/columnar.PendingSnapshot.dedup_keys) — the identity that
        survives slot reuse, universe growth, and arena compaction. With
        equal resource/label universes and the same group profiles, the
        same key encodes to the same operand row byte for byte, so a
        copied row equals a recomputed one;
      * row ORDER is the same byte-sort _dedup_rows canonicalizes, so
        matched rows land at the positions a full encode would put them;
      * fresh rows are produced by the SAME _pod_arrays code path on
        just their subset, then scattered into position.

    The fast path only engages for the unconstrained fleet (no live
    affinity / spread / anti / soft-score rows, no census, no with_rows)
    — everything else, and any universe or profile change, falls back to
    _encode_full (which also refreshes the cache entry). Group profiles
    are compared by IDENTITY: the runtime's NodeMirror memoizes profile
    tuples, so unchanged nodes present the same objects every tick, and
    a recomputed profile (node churn) invalidates naturally.

    An unchanged dedup set returns the SAME BinPackInputs OBJECT, so
    identity-keyed device-residency caches skip the host->device
    transfer even when the pod set churned through identical shapes."""

    _MAX_ENTRIES = 4  # distinct (group-set, universe) keys kept live

    def __init__(self):
        import collections
        import threading

        self._lock = threading.Lock()
        self._entries = collections.OrderedDict()
        # observability: identical-set hits, row-level deltas, full passes
        self.hits = 0
        self.deltas = 0
        self.fulls = 0

    def reset(self) -> None:
        """Recovery-boot seam (docs/resilience.md "Crash recovery"):
        drop every cached entry. The delta layer's fast path returns the
        SAME BinPackInputs OBJECT for an unchanged dedup set — an
        identity contract downstream device-residency caches key on —
        so after a crash-recovery boot the pre-crash entries must not be
        splice sources: the next encode of each key is a full pass.
        Resident scatter plans (the device-residency companion) drop
        with the entries — a plan against pre-reset state must never
        splice into post-reset device buffers."""
        with self._lock:
            self._entries.clear()
        reset_resident_plans()

    def encode(
        self,
        snap,
        profiles,
        with_rows: bool = False,
        census=None,
        constraints=None,
    ):
        if (
            with_rows
            or census is not None
            # constraint groups re-key the dedup (membership joins the
            # row identity) and attach operands the splice doesn't
            # carry: always a full pass
            or constraints
            # no incremental dedup (hand-built / oracle snapshots): bail
            # BEFORE the keyed dedup pass, or a 100k-row snapshot would
            # pay the O(N) np.unique row sort twice (here and inside
            # _encode_full)
            or snap.dedup_idx is None
            or snap.dedup_keys is None
        ):
            self.fulls += 1
            return _encode_full(
                snap, profiles, with_rows=with_rows, census=census,
                constraints=constraints,
            )
        row_idx, row_weight, keys = _dedup_rows_keyed(snap)
        if keys is None or self._live_constraints(snap, row_idx):
            self.fulls += 1
            return _encode_full(snap, profiles, census=census)
        cache_key = (
            len(profiles),
            tuple(snap.resources),
            tuple(snap.labels),
        )
        with self._lock:
            return self._encode_locked(
                cache_key, snap, profiles, row_idx, row_weight, keys
            )

    def _encode_locked(
        self, cache_key, snap, profiles, row_idx, row_weight, keys
    ):
        entry = self._entries.get(cache_key)
        if entry is not None and self._entry_valid(entry, profiles):
            n_pods = _pad(len(row_idx), POD_PAD)
            if (
                entry.keys == keys
                and entry.n_pods == n_pods
                and np.array_equal(entry.row_weight, row_weight)
            ):
                self.hits += 1
                self._entries.move_to_end(cache_key)
                return entry.inputs
            self.deltas += 1
            entry = self._apply_delta(
                entry, snap, row_idx, row_weight, keys, n_pods
            )
        else:
            self.fulls += 1
            entry = self._build_entry(
                snap, profiles, row_idx, row_weight, keys
            )
        self._entries[cache_key] = entry
        self._entries.move_to_end(cache_key)
        while len(self._entries) > self._MAX_ENTRIES:
            self._entries.popitem(last=False)
        return entry.inputs

    @staticmethod
    def _live_constraints(snap, row_idx) -> bool:
        """Any live row carrying affinity/spread/anti/soft shapes routes
        to the full encode (those operands need census + row expansion);
        id 0 is always the unconstrained shape. Nonzero-priority rows
        route there too: the delta layer does not splice the
        pod_priority operand, and priority fleets are preemption-scale
        (small), so the full encode is cheap where it matters."""
        if len(row_idx) == 0:
            return False
        if snap.priority is not None and bool(
            (snap.priority[row_idx] != 0).any()
        ):
            return True
        for ids in (
            snap.affinity_id,
            snap.preferred_id,
            snap.spread_id,
            snap.anti_id,
            snap.soft_spread_id,
            snap.soft_anti_id,
        ):
            if ids is not None and bool((ids[row_idx] != 0).any()):
                return True
        return False

    @staticmethod
    def _entry_valid(entry, profiles) -> bool:
        # identity, not equality: profile tuples are memoized upstream
        # (NodeMirror), so pointer-equal means node state unchanged, and
        # value comparison would cost what _group_arrays costs
        return len(entry.profiles) == len(profiles) and all(
            a is b for a, b in zip(entry.profiles, profiles)
        )

    def _build_entry(self, snap, profiles, row_idx, row_weight, keys):
        """Cold path: one _encode_full pass, then index its output rows
        by dedup key so the next tick can splice from them. The cached
        inputs ARE the full encode's output — parity is definitional."""
        inputs = _encode_full(snap, profiles)
        entry = _DeltaEntry(
            keys, row_weight, inputs.pod_requests.shape[0], inputs
        )
        entry.profiles = list(profiles)
        entry.resources, entry.resource_index, entry.pod_slot = (
            _resource_universe(snap, profiles)
        )
        entry.taint_universe = _taint_universe(profiles)
        entry.n_resources = inputs.pod_requests.shape[1]
        entry.n_taints = inputs.pod_intolerant.shape[1]
        entry.n_labels = inputs.pod_required.shape[1]
        return entry

    def _apply_delta(self, entry, snap, row_idx, row_weight, keys, n_pods):
        """Row-level splice: copy rows whose canonical key survived from
        the cached arrays, gather only the fresh rows through the normal
        _pod_arrays path, and reuse the group arrays untouched.

        Also publishes the ResidentPlan for the new inputs: a row is
        CHANGED unless its key matched AT THE SAME POSITION (same key
        elsewhere means the byte-sorted order moved — the resident
        buffer's row at that position holds different bytes either
        way), and weight rows are diffed value-wise since multiplicity
        is not part of the key."""
        hi = len(row_idx)
        matched_new, matched_old, fresh_new = [], [], []
        in_place = []
        for i, key in enumerate(keys):
            j = entry.pos.get(key)
            if j is None:
                fresh_new.append(i)
            else:
                matched_new.append(i)
                matched_old.append(j)
                if j == i:
                    in_place.append(i)

        pod_requests = np.zeros((n_pods, entry.n_resources), np.float32)
        pod_valid = np.zeros(n_pods, bool)
        pod_required = np.zeros((n_pods, entry.n_labels), bool)
        pod_intolerant = np.zeros((n_pods, entry.n_taints), bool)
        pod_weight = np.zeros(n_pods, np.int32)

        old = entry.inputs
        if matched_new:
            m_new = np.asarray(matched_new, np.intp)
            m_old = np.asarray(matched_old, np.intp)
            pod_requests[m_new] = old.pod_requests[m_old]
            pod_valid[m_new] = old.pod_valid[m_old]
            pod_required[m_new] = old.pod_required[m_old]
            pod_intolerant[m_new] = old.pod_intolerant[m_old]
        if fresh_new:
            f_new = np.asarray(fresh_new, np.intp)
            sub = _pod_arrays(
                snap,
                row_idx[f_new],
                row_weight[f_new],
                entry.resources,
                entry.resource_index,
                entry.pod_slot,
                len(fresh_new),
                entry.n_resources,
                entry.n_taints,
                entry.n_labels,
                entry.taint_universe,
            )
            pod_requests[f_new] = sub[0]
            pod_valid[f_new] = sub[1]
            pod_required[f_new] = sub[2]
            pod_intolerant[f_new] = sub[3]
        pod_weight[:hi] = row_weight

        inputs = B.BinPackInputs(
            pod_requests=pod_requests,
            pod_valid=pod_valid,
            pod_intolerant=pod_intolerant,
            pod_required=pod_required,
            group_allocatable=old.group_allocatable,
            group_taints=old.group_taints,
            group_labels=old.group_labels,
            pod_weight=pod_weight,
            # tier is a pure function of the (identity-equal) profiles:
            # reuse like the other group arrays. pod_priority needs no
            # splice — priority rows never reach the delta path
            # (_live_constraints).
            group_tier=old.group_tier,
        )
        if n_pods == entry.n_pods:
            # the device-resident scatter plan (solver/resident.py):
            # only meaningful when the padded extent held — a bucket
            # crossing rebuilds the resident stack anyway
            hi_old = len(entry.keys)
            span = max(hi, hi_old)
            changed = np.ones(span, bool)
            if in_place:
                changed[np.asarray(in_place, np.intp)] = False
            w_new = np.zeros(span, np.int32)
            w_new[:hi] = row_weight
            w_old = np.asarray(old.pod_weight[:span], np.int32)
            _register_plan(
                inputs,
                ResidentPlan(
                    prev=old,
                    rows=np.nonzero(changed)[0],
                    weight_rows=np.nonzero(w_new != w_old)[0],
                ),
            )
        return entry.successor(keys, row_weight, n_pods, inputs)


_default_delta = SnapshotDeltaCache()


def reset_delta_cache() -> None:
    """Invalidate the process-default SnapshotDeltaCache (the recovery
    boot calls this — see SnapshotDeltaCache.reset)."""
    _default_delta.reset()


def _encode_from_cache(
    snap, profiles, with_rows: bool = False, census=None, constraints=None
):
    """THE encode seam (public face: pendingcapacity.encode_snapshot):
    delta-accelerated when the process-default SnapshotDeltaCache has a
    matching entry, bit-identical to _encode_full always."""
    # injection point (faults/registry.py): a failed encode is a
    # producer-reconcile failure — row-isolated by solve_pending, then
    # ridden down the engine's retryable-backoff ladder
    from karpenter_tpu.faults import inject

    inject("encoder.encode")
    return _default_delta.encode(
        snap, profiles, with_rows=with_rows, census=census,
        constraints=constraints,
    )


