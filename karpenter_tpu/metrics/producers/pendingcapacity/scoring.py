"""Soft-constraint scoring: ScheduleAnyway spread and preferred
inter-pod (anti-)affinity as pod_group_score contributions (the
kube-scheduler scoring plugins — steering, never constraining)."""

from __future__ import annotations

import numpy as np

from karpenter_tpu.api.core import Taint, preference_score


def _taint_toleration_raw(snap, profiles, row_idx, n_real):
    """TaintToleration plugin: groups with FEWER PreferNoSchedule
    taints the pod does not tolerate rank higher (soft taints never
    gate feasibility — the encoder keeps them out of the intolerance
    bitset). One evaluation per DISTINCT toleration shape, gathered to
    rows by shape id. None when no group carries a soft taint — the
    common fleet pays nothing."""
    soft = [
        [
            Taint(key=k, value=v, effect=e)
            for (k, v, e) in sorted(taints)
            if e == "PreferNoSchedule"
        ]
        for _, _, taints in profiles
    ]
    if not any(soft):
        return None
    shapes = snap.shape_tolerations
    raw = np.zeros((len(shapes), n_real), np.float32)
    for s, tolerations in enumerate(shapes):
        for t, group_soft in enumerate(soft):
            for taint in group_soft:
                if not any(tol.tolerates(taint) for tol in tolerations):
                    raw[s, t] -= 1.0
    return raw[snap.shape_id[row_idx]]


def _live_ids(snap_ids, shapes, row_idx):
    """Per-row shape ids for one plugin, or None when the fleet carries
    none of this preference kind."""
    if snap_ids is None or shapes is None:
        return None
    live = snap_ids[row_idx]
    return live if (live != 0).any() else None


def _node_affinity_raw(shapes, live, label_dicts_fn, n_real):
    """NodeAffinity plugin: preferred-term weight sums
    (api/core.preference_score) per (shape, group)."""
    raw = np.zeros((len(shapes), n_real), np.float32)
    for s in np.unique(live):
        shape = shapes[s]
        if not shape:
            continue
        for t, labels in enumerate(label_dicts_fn()):
            raw[s, t] = preference_score(labels, shape)
    return raw


def _soft_spread_raw(shapes, live, label_dicts_fn, census, n_real):
    """PodTopologySpread plugin (ScheduleAnyway): domains with FEWER
    existing matching pods rank higher; groups missing the key rank
    strictly below every keyed group (the plugin's keyless-node rule)."""
    raw = np.zeros((len(shapes), n_real), np.float32)
    for s in np.unique(live):
        shape = shapes[s]
        if not shape:
            continue
        namespace, entries = shape
        for key, sel in entries:
            counts = (
                census.domain_counts(namespace, sel, key)
                if census is not None and sel is not None
                else {}
            )
            # keyless groups rank strictly below every keyed one
            worst = float(max(counts.values(), default=0)) + 1.0
            for t, labels in enumerate(label_dicts_fn()):
                value = labels.get(key)
                raw[s, t] -= (
                    float(counts.get(value, 0))
                    if value is not None
                    else worst
                )
    return raw


def _soft_anti_raw(shapes, live, label_dicts_fn, census, n_real):
    """InterPodAffinity plugin: preferred self-(anti-)affinity terms add
    sign x weight per existing matching pod in the group's domain."""
    raw = np.zeros((len(shapes), n_real), np.float32)
    for s in np.unique(live):
        shape = shapes[s]
        if not shape:
            continue
        namespace, entries = shape
        for sign, weight, key, sel in entries:
            counts = census.domain_counts(namespace, sel, key)
            for t, labels in enumerate(label_dicts_fn()):
                value = labels.get(key)
                if value is not None:
                    raw[s, t] += (
                        sign * weight * float(counts.get(value, 0))
                    )
    return raw


def _score_pieces(snap, profiles, row_idx, label_dicts_fn, census, n_real):
    """(plugin weight, raw[hi, n_real]) per active scoring plugin."""
    pieces = []

    taint_raw = _taint_toleration_raw(snap, profiles, row_idx, n_real)
    if taint_raw is not None and taint_raw.any():
        # all-zero contributions (every pod tolerates every soft taint)
        # must not put the fleet on the scored kernel path
        pieces.append((3.0, taint_raw))

    live = _live_ids(snap.preferred_id, snap.preferred_shapes, row_idx)
    if live is not None:
        raw = _node_affinity_raw(
            snap.preferred_shapes, live, label_dicts_fn, n_real
        )
        pieces.append((1.0, raw[live]))

    live = _live_ids(snap.soft_spread_id, snap.soft_spread_shapes, row_idx)
    if live is not None:
        raw = _soft_spread_raw(
            snap.soft_spread_shapes, live, label_dicts_fn, census, n_real
        )
        pieces.append((2.0, raw[live]))

    live = _live_ids(snap.soft_anti_id, snap.soft_anti_shapes, row_idx)
    if live is not None and census is not None:
        raw = _soft_anti_raw(
            snap.soft_anti_shapes, live, label_dicts_fn, census, n_real
        )
        if raw.any():
            pieces.append((1.0, raw[live]))
    return pieces


def _score_rows(
    snap, profiles, row_idx, label_dicts_fn, census, n_pods, n_groups
):
    """The kube-scheduler's scoring plugins over candidate groups ->
    the solver's pod_group_score operand (argmax among feasible, index
    tie-break). Three plugins, combined with the scheduler's default
    weights after per-row min-max normalization to 0..100 (min-max is
    monotone, so a fleet using only ONE plugin keeps exactly the raw
    scores' argmax and tie-break order):

    - NodeAffinity (weight 1): preferred-term weight sums
      (api/core.preference_score).
    - PodTopologySpread (weight 2): ScheduleAnyway constraints prefer
      domains with FEWER existing matching pods (DomainCensus counts);
      groups missing the key rank below every keyed group, matching
      the scoring plugin's treatment of keyless nodes.
    - InterPodAffinity (weight 1): preferred self-(anti-)affinity
      terms add sign x weight per existing matching pod in the
      group's domain.
    - TaintToleration (weight 3): groups with fewer PreferNoSchedule
      taints the pod does not tolerate rank higher.

    Returns None when no live row carries any preference AND no group
    carries a soft taint — the common fleet skips the score operand
    entirely. census=None (hand-built snapshots) scores with zero
    counts: spread still ranks keyless groups last; inter-pod terms
    contribute nothing.
    """
    hi = len(row_idx)
    if hi == 0:
        return None
    n_real = len(profiles)
    pieces = _score_pieces(
        snap, profiles, row_idx, label_dicts_fn, census, n_real
    )
    if not pieces:
        return None
    acc = np.zeros((hi, n_real), np.float32)
    for weight, raw in pieces:
        lo = raw.min(axis=1, keepdims=True)
        rng = raw.max(axis=1, keepdims=True) - lo
        safe = np.where(rng > 0, rng, 1.0)
        acc += weight * np.where(rng > 0, (raw - lo) / safe * 100.0, 0.0)
    total = np.zeros((n_pods, n_groups), np.float32)
    total[:hi, :n_real] = acc
    return total
