"""Shared constants: gauge names and the pad buckets that keep
compiled shapes stable (universes GROW in these steps rather than
truncating — silent constraint drops would be false feasibility)."""

from __future__ import annotations

from karpenter_tpu.store.columnar import BASE_RESOURCES

SUBSYSTEM = "pending_capacity"
PENDING_PODS = "pending_pods"
ADDITIONAL_NODES_NEEDED = "additional_nodes_needed"
LP_LOWER_BOUND = "lp_lower_bound"
UNSCHEDULABLE_PODS = "unschedulable_pods"

# base resources always present; the per-solve universe adds any extended
# resources (GPUs/TPUs/ephemeral-storage/...) seen in requests or allocatable,
# with the 'pods' slot axis always LAST (each pod occupies exactly 1).
# Single definition lives with the encoder (store/columnar.py).
RESOURCES_BASE = BASE_RESOURCES

# pad buckets for stable compiled shapes; universes GROW in these steps
# rather than truncating (silent constraint drops = false feasibility)
TAINT_PAD = 32
LABEL_PAD = 64
POD_PAD = 256  # pods padded to a multiple of this
GROUP_PAD = 8
RESOURCE_PAD = 4

# kubernetes' default max-pods when a node doesn't report a 'pods' allocatable
DEFAULT_PODS_PER_NODE = 110.0
