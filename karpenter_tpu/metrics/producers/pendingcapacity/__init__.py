"""PendingCapacity producer: would a scale-up let pending pods schedule?

reference: pkg/metrics/producers/pendingcapacity/producer.go:29-31 is a STUB
in the reference; the design intent (docs/designs/DESIGN.md "Pending Pods")
is a per-node-group signal derived from global bin-packing of unschedulable
pods, with the rule that each pod drives at most ONE group's scale-up.

This implementation is the TPU build's north star: ALL pendingCapacity
producers are solved together in one device call (ops/binpack) — the
controller's batch hook collects them per tick. The host side only encodes
the store snapshot into fixed-shape arrays:

- pending pods = Pods with no nodeName (the unschedulable set)
- each producer's node group contributes one row of the type matrix: its
  per-node shape is the elementwise MIN allocatable over ready+schedulable
  nodes (labels: intersection; taints: union — conservative on all three
  axes: a scale-up signal must never claim feasibility that no real node
  shape of the group can satisfy)
- the resource universe is dynamic: cpu/memory/pods plus every extended
  resource (GPUs, TPUs, ephemeral-storage, ...) appearing in pending-pod
  requests or node allocatables, padded for compile stability; a pod
  requesting a resource a group doesn't provide fails fit there, and a pod
  requesting a resource no group provides counts as unschedulable
- taint and label universes are encoded into padded bitsets so the device
  feasibility math is two boolean matmuls (see ops/binpack.py)

Gauges: karpenter_pending_capacity_{pending_pods,additional_nodes_needed,
lp_lower_bound,unschedulable_pods}{name,namespace}.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu import constraints as _constraints
from karpenter_tpu.api.metricsproducer import PendingCapacityStatus
from karpenter_tpu.metrics.registry import GaugeRegistry, default_registry
from karpenter_tpu.observability import solver_trace
from karpenter_tpu.ops import binpack as B
from karpenter_tpu.store.columnar import (
    occupancy_from_pods,
    snapshot_from_pods,
)

from . import encoder as _encoder
from .census import DomainCensus
from .constants import (  # noqa: F401 — public constants (gauge names, pads)
    ADDITIONAL_NODES_NEEDED,
    DEFAULT_PODS_PER_NODE,
    GROUP_PAD,
    LABEL_PAD,
    LP_LOWER_BOUND,
    PENDING_PODS,
    POD_PAD,
    RESOURCE_PAD,
    RESOURCES_BASE,
    SUBSYSTEM,
    TAINT_PAD,
    UNSCHEDULABLE_PODS,
)
from .encoder import _group_profile as _group_profile_impl

# NOTE: the pendingcapacity._* underscore re-exports (PR 1's deprecated
# compat shims: _encode_from_cache, _dedup_rows, _group_profile, the
# spread/anti/exclusion/partition helpers) are GONE — every in-repo
# caller was migrated to the public names below or to the helpers'
# home submodules (encoder, partition, ...). Test seams intercept
# `encode_snapshot`, which every internal solve path resolves at call
# time through this module's global namespace.


def encode_snapshot(
    snap, profiles, with_rows: bool = False, census=None, constraints=None
):
    """PUBLIC encoding API: store snapshot -> fixed-shape solver inputs.

    The one encoder every solve path uses — runtime reconcile, HA
    controller, consolidation, simulate, the oracle tests. Routes
    through encoder._encode_from_cache, whose incremental delta layer
    (encoder.SnapshotDeltaCache) reuses the last encode per (group-set,
    resource-universe) key and splices pod add/remove/rebind deltas in
    place of a full rebuild — output parity with a full re-encode is
    bit-identical (pinned by tests/test_encoder_delta.py). See
    encoder.py for the full contract (deduplicated weighted shape rows,
    spread/anti expansion, padding). `constraints` is the merged
    declarative constraint-group list (karpenter_tpu/constraints);
    None/empty encodes today's unconstrained wire byte for byte."""
    return _encoder._encode_from_cache(
        snap, profiles, with_rows=with_rows, census=census,
        constraints=constraints,
    )


def group_profile(nodes, selector):
    """PUBLIC profile API: (allocatable by resource, labels set, taints
    set) for one node group — the conservative elementwise-MIN shape over
    ready+schedulable nodes matching `selector` (encoder._group_profile,
    promoted like encode_snapshot)."""
    return _group_profile_impl(nodes, selector)


def register_gauges(registry: GaugeRegistry) -> None:
    for name in (
        PENDING_PODS,
        ADDITIONAL_NODES_NEEDED,
        LP_LOWER_BOUND,
        UNSCHEDULABLE_PODS,
    ):
        registry.register(SUBSYSTEM, name)




def _solve_targets(store, feed, due_keys):
    """The group axis: (namespace, name, due-object-or-None, selector,
    nodeGroupRef, constraint-group tuple) in deterministic key order —
    from the feed's watch-maintained producer index when present, else
    one store listing. Due producers use the CALLER's object so status
    lands on the instance the engine persists."""
    if feed is not None:
        return [
            (key[0], key[1], due_keys.get(key), selector, ref, cons)
            for key, (selector, ref, cons) in feed.producers.items()
        ]
    targets = []
    for mp in sorted(
        store.list("MetricsProducer"),
        key=lambda m: (m.metadata.namespace, m.metadata.name),
    ):
        if mp.spec.pending_capacity is None:
            continue
        key = (mp.metadata.namespace, mp.metadata.name)
        targets.append(
            (key[0], key[1], due_keys.get(key, mp),
             mp.spec.pending_capacity.node_selector,
             getattr(mp.spec.pending_capacity, "node_group_ref", ""),
             tuple(
                 getattr(mp.spec.pending_capacity, "constraints", None)
                 or ()
             ))
        )
    return targets


def _gather_constraints(targets, errors):
    """Merged constraint-group list across producers, in target order
    (first-match-wins membership makes the order semantic). Validation
    is row-isolated like every other per-producer failure: a producer
    with a poisoned constraint spec drops ITS groups and records its
    error; every other producer's groups still compile. Cross-producer
    duplicate names keep the first occurrence."""
    from karpenter_tpu.constraints import validate_constraints

    merged: List = []
    seen: set = set()
    for namespace, name, _, _, _, cons in targets:
        if not cons:
            continue
        try:
            validate_constraints(list(cons))
        except Exception as e:  # noqa: BLE001 — row-isolated failure
            errors[(namespace, name)] = e
            continue
        for group in cons:
            if group.name in seen:
                continue
            seen.add(group.name)
            merged.append(group)
    return merged


def _target_profiles(targets, feed, nodes, template_resolver, errors):
    """(profiles, template_rows): one group shape per target, with
    per-ROW failure isolation — a poisoned spec fails only its own row
    (empty all-infeasible shape, error recorded), every healthy
    producer still solves. Template-derived rows (scale-from-zero) are
    returned for the encode-memo fingerprint: templates live OUTSIDE
    the watch-versioned store state the fingerprint otherwise covers."""
    profiles = []
    template_rows = []
    for namespace, name, _, sel, ref, _cons in targets:
        try:
            profile = (
                feed.nodes.profile(sel)
                if feed is not None
                else _group_profile_impl(nodes, sel)
            )
            if not profile[0] and ref and template_resolver is not None:
                resolved = template_resolver(namespace, ref)
                if resolved is not None:
                    profile = resolved
                    template_rows.append(
                        (namespace, name,
                         tuple(sorted(profile[0].items())),
                         tuple(sorted(profile[1])),
                         tuple(sorted(profile[2])))
                    )
            profiles.append(profile)
        except Exception as e:  # noqa: BLE001 — row-isolated failure
            errors[(namespace, name)] = e
            # empty shape: zero allocatable everywhere, which
            # _feasibility already rejects — the row solves as
            # "nothing fits here"
            profiles.append(({}, set(), set()))
    return profiles, template_rows


def _build_census(store, feed, all_pods, nodes):
    """(census, namespace_state) for a fleet with live spread/anti/soft
    constraints. ONE Namespace read per solve: the encode-memo
    fingerprint and the namespaceSelector resolution must see the SAME
    snapshot (a label change landing between two reads would cache an
    encode under a state it was not computed from)."""
    if feed is not None:
        if feed.census is None:
            feed.census = DomainCensus(
                feed.occupancy,
                feed.nodes.nodes,
                lambda: feed.nodes.version,
            )
        census = feed.census
    else:
        census = DomainCensus(
            occupancy_from_pods(all_pods), lambda: nodes
        )
    namespace_objects = store.list("Namespace")
    census.set_namespaces(namespace_objects)
    namespace_state = tuple(
        sorted(
            (
                ns.metadata.name,
                tuple(sorted(ns.metadata.labels.items())),
            )
            for ns in namespace_objects
        )
    )
    return census, namespace_state


def _feed_fingerprint(feed, snap, needs_census, namespace_state, targets,
                      template_rows):
    """Encode-memo key: inputs are a pure function of (pod arena
    generation, node set, producer selectors, occupancy). Bound-pod
    churn moves spread/anti masks only when a constraint is live, so
    the occupancy slot pins to -1 otherwise and the memo survives
    scheduled-pod events."""
    return (
        snap.generation,
        feed.nodes.version,
        feed.occupancy.generation if needs_census else -1,
        namespace_state,
        tuple(
            (
                namespace,
                name,
                # poisoned specs (e.g. selector=None) must stay
                # row-isolated: never assume dict shape here
                tuple(sorted(sel.items()))
                if isinstance(sel, dict)
                else repr(sel),
                ref,
                _canonical_cons(cons),
            )
            for namespace, name, _, sel, ref, cons in targets
        ),
        tuple(template_rows),
    )


def _canonical_cons(cons):
    """Constraint groups are fingerprint identity (a spec edit must
    re-encode), row-isolated like selectors: a malformed group falls
    back to repr rather than poisoning the whole memo key."""
    from karpenter_tpu.constraints import canonical_constraints

    try:
        return canonical_constraints(list(cons))
    except Exception:  # noqa: BLE001 — fingerprint must never raise
        return repr(cons)


def solve_pending(
    store, due_producers: List, registry: GaugeRegistry, solver=None,
    pod_cache=None, feed=None, template_resolver=None,
) -> Dict[tuple, Optional[Exception]]:
    """One device call over ALL pendingCapacity producers in the store.

    Solving the full set — not just the due subset — is what upholds the
    DESIGN.md single-scale-up rule: assignment is only exclusive when every
    candidate group is in the same solve. Status objects are mutated on the
    due producers (the engine persists those); gauges are refreshed for every
    group since they are global registry state (non-due status writes would
    land on discarded copies, so only their selectors matter).

    `solver` is the Algorithm seam: any (inputs, buckets=...) ->
    BinPackOutputs callable — in-process ops/binpack.solve (default) or a
    sidecar SolverClient.solve (gRPC process split).

    `feed` (store/columnar.PendingFeed) makes the whole host side
    incremental: pod arena (O(changed pods)), memoized node profiles
    (recomputed only on node churn), and a producer-selector index (no
    per-tick store listing). `pod_cache` alone caches just the pod arena.
    With neither, the oracle path lists + encodes everything from the
    store — the reference the property tests compare the caches against.
    Outputs are identical on every path (the solver is permutation-
    invariant over pods: per-pod first-feasible assignment + bucket
    histograms).

    Returns {(namespace, name): error or None} for every target. Failure
    isolation is per ROW: one producer with a poisoned spec (e.g. a
    selector that blows up profile computation) fails only its own row —
    its group encodes as an empty (all-infeasible) shape and its status/
    gauges are left untouched — while every healthy producer still solves
    (mirrors the reference's per-object failure containment,
    pkg/controllers/controller.go:85-91). Only genuinely global failures
    (the pod snapshot, the device solve itself) fail the whole batch, by
    raising.

    `template_resolver` (producers.Factory.template_resolver) enables
    SCALE-FROM-ZERO: a callable (namespace, node_group_ref) ->
    Optional[(alloc floats, labels set, taints set)] consulted only when
    a producer's selector matches no nodes and its spec names a
    nodeGroupRef — the provider's declared instance shape stands in for
    the missing live node. Live nodes always win.
    """
    due_keys = {
        (mp.metadata.namespace, mp.metadata.name): mp for mp in due_producers
    }
    targets = _solve_targets(store, feed, due_keys)
    if not targets:
        return {}

    nodes = None
    if feed is None:
        nodes = store.list("Node")  # listed ONCE; profiles filter in-memory
    errors: Dict[tuple, Optional[Exception]] = {}
    profiles, template_rows = _target_profiles(
        targets, feed, nodes, template_resolver, errors
    )

    snap, all_pods = _pods_snapshot(store, feed, pod_cache)
    census, namespace_state, needs_census = _occupancy_census(
        store, feed, all_pods, nodes, snap
    )

    # declarative constraint groups (karpenter_tpu/constraints): merged
    # across producers in target order, validation row-isolated
    constraints = _gather_constraints(targets, errors)
    cmeta = (
        _constraints.constraint_meta(constraints, profiles)
        if constraints
        else None
    )

    if feed is not None:
        _solve_feed_path(
            feed, snap, profiles, census, needs_census, namespace_state,
            targets, template_rows, registry, solver, errors,
            constraints=constraints, cmeta=cmeta,
        )
    else:
        inputs = encode_snapshot(
            snap, profiles, census=census, constraints=constraints
        )
        _dispatch_and_record(
            inputs, targets, registry, solver, errors, cmeta=cmeta
        )
    _publish_census(registry, census)
    _publish_constraints(registry, cmeta)
    return {
        (namespace, name): errors.get((namespace, name))
        for namespace, name, _, _, _, _ in targets
    }


def _pods_snapshot(store, feed, pod_cache):
    """ONE encode implementation for every path (store/columnar.py): the
    caches snapshot their watch-maintained arenas; the oracle path runs
    the same detached encoder over a fresh store.list — no drift
    possible. Returns (snapshot, all_pods) — all_pods is only non-None
    on the oracle path, where the census can reuse the listing."""
    if feed is not None:
        return feed.pods.snapshot(), None
    if pod_cache is not None:
        return pod_cache.snapshot(), None
    all_pods = store.list("Pod")
    return snapshot_from_pods(all_pods), all_pods


def _occupancy_census(store, feed, all_pods, nodes, snap):
    """Existing-pod domain occupancy: only fleets with live spread/anti
    constraints or soft preferences pay for a census (freed arena slots
    are zeroed, so the id scan is exact); unconstrained fleets skip it
    entirely — and their encode memo stays insensitive to bound-pod
    churn. Returns (census, namespace_state, needs_census)."""
    needs_census = any(
        ids is not None and bool((ids != 0).any())
        for ids in (
            snap.spread_id,
            snap.anti_id,
            snap.soft_spread_id,
            snap.soft_anti_id,
        )
    )
    if not needs_census:
        return None, (), False
    if feed is None and all_pods is None:
        all_pods = store.list("Pod")
    census, namespace_state = _build_census(store, feed, all_pods, nodes)
    return census, namespace_state, True


def _solve_feed_path(
    feed, snap, profiles, census, needs_census, namespace_state,
    targets, template_rows, registry, solver, errors,
    constraints=None, cmeta=None,
) -> None:
    """Encode memo (feed path only): inputs are a pure function of
    (pod arena generation, node set, producer selectors, occupancy).
    When none of those moved since the last solve, reuse the previous
    BinPackInputs OBJECT — the solver's identity-keyed device cache
    (ops/binpack.solve) then skips the host->device transfer entirely,
    which dominates the tick when the chip sits behind a network
    tunnel."""
    fingerprint = _feed_fingerprint(
        feed, snap, needs_census, namespace_state, targets, template_rows
    )
    if constraints:
        # admission epoch: while the constraint compile is degraded
        # (last admission fell back — fault / open breaker), the epoch
        # tracks the fallback counter, so every tick's fingerprint
        # differs and re-encodes (retrying admission) — the memo can
        # never pin the never-block fallback past the fault clearing.
        # A healthy compile pins the constant "ok" epoch, so the
        # constrained steady state memoizes like the unconstrained one.
        fingerprint = fingerprint + (
            ("degraded", _encoder.constraint_stats["fallbacks"])
            if _encoder.constraint_stats.get("degraded")
            else ("ok",),
        )
    memo = feed.encode_memo
    cached_outputs = None
    if memo is not None and memo[0] == fingerprint:
        inputs = memo[1]
        # the solve is a pure function of inputs: identical inputs
        # reuse the PREVIOUS host outputs and skip the device call
        # entirely — an unchanged tick costs no round-trip at all
        cached_outputs = memo[2]
        _count_cache(registry, "hit")
    else:
        inputs = encode_snapshot(
            snap, profiles, census=census, constraints=constraints
        )
        feed.encode_memo = (fingerprint, inputs, None)
        _count_cache(registry, "miss")
    host = _dispatch_and_record(
        inputs, targets, registry, solver, errors,
        cached_outputs=cached_outputs, cmeta=cmeta,
    )
    feed.encode_memo = (fingerprint, inputs, host)




def _publish_census(registry: GaugeRegistry, census) -> None:
    """karpenter_runtime_census_refresh_total: occupancy-census epoch
    recomputes (bound-pod / node churn between constrained solves).
    karpenter_runtime_census_view_evictions_total: materialized-view
    LRU evictions — a rising rate means more live (namespace, selector)
    pairs than ScheduledOccupancy.VIEW_CAP, and each re-build is a
    group scan (the silent-thrash signal, r3 code review).
    Delta-published so the persistent feed census and the per-solve
    oracle census report the same way."""
    if census is None:
        return
    delta = census.refreshes - census.published
    if delta:
        registry.register(
            "runtime", "census_refresh_total", kind="counter"
        ).inc("-", "-", delta)
        census.published = census.refreshes
    evictions = getattr(census._occupancy, "view_evictions", 0)
    delta = evictions - census.evictions_published
    if delta:
        registry.register(
            "runtime", "census_view_evictions_total", kind="counter"
        ).inc("-", "-", delta)
        census.evictions_published = evictions


CONSTRAINTS_SUBSYSTEM = "constraints"
SPREAD_SKEW = "spread_skew"
RESERVATION_FILL = "reservation_fill"
CONSTRAINT_FALLBACK_TOTAL = "fallback_total"
CONSTRAINT_COMPILE_TOTAL = "compile_total"
CONSTRAINT_BREAKER_STATE = "breaker_state"


def _publish_verdicts(registry, inputs, assigned, cmeta) -> None:
    """karpenter_constraints_spread_skew{name=<group>} and
    karpenter_constraints_reservation_fill{name=<reservation>}: the
    constraint plane's verdicts, recomputed host-side from the solve's
    per-row assignment (constraints/compiler.py helpers)."""
    registry.register(CONSTRAINTS_SUBSYSTEM, SPREAD_SKEW)
    registry.register(CONSTRAINTS_SUBSYSTEM, RESERVATION_FILL)
    for name, skew in _constraints.spread_skew(
        inputs, assigned, cmeta
    ).items():
        registry.gauge(CONSTRAINTS_SUBSYSTEM, SPREAD_SKEW).set(
            name, "-", float(skew)
        )
    for name, fill in _constraints.reservation_fill(
        inputs, assigned, cmeta
    ).items():
        registry.gauge(CONSTRAINTS_SUBSYSTEM, RESERVATION_FILL).set(
            name, "-", float(fill)
        )


def _publish_constraints(registry: GaugeRegistry, cmeta) -> None:
    """Constraint-plane health: compile/fallback counters (delta-
    published from encoder.constraint_stats so repeated solves don't
    double-count) and the breaker state gauge (0 closed / 1 half-open /
    2 open). Published only while constraint groups are live — the
    unconstrained fleet's metrics surface is unchanged."""
    stats = _encoder.constraint_stats
    unpublished = (
        stats["compiles"] != stats["published_compiles"]
        or stats["fallbacks"] != stats["published_fallbacks"]
    )
    if cmeta is None and not unpublished:
        return
    delta = stats["compiles"] - stats["published_compiles"]
    if delta:
        registry.register(
            CONSTRAINTS_SUBSYSTEM, CONSTRAINT_COMPILE_TOTAL, kind="counter"
        ).inc("-", "-", delta)
        stats["published_compiles"] = stats["compiles"]
    delta = stats["fallbacks"] - stats["published_fallbacks"]
    if delta:
        registry.register(
            CONSTRAINTS_SUBSYSTEM, CONSTRAINT_FALLBACK_TOTAL, kind="counter"
        ).inc("-", "-", delta)
        stats["published_fallbacks"] = stats["fallbacks"]
    registry.register(CONSTRAINTS_SUBSYSTEM, CONSTRAINT_BREAKER_STATE)
    registry.gauge(CONSTRAINTS_SUBSYSTEM, CONSTRAINT_BREAKER_STATE).set(
        "-", "-", float(_encoder._constraint_breaker.state_value())
    )


def _count_cache(registry: GaugeRegistry, outcome: str) -> None:
    """karpenter_runtime_encode_cache_total{name=hit|miss}: how often the
    tick-collapse encode memo spares a re-encode + device re-upload."""
    registry.register("runtime", "encode_cache_total", kind="counter").inc(
        outcome, "-"
    )


_pack_outputs_jit = None


def _pack_outputs(assigned_count, nodes_needed, lp_bound, unschedulable):
    """Jitted on first use: concat the per-group outputs + the scalar into
    one vector so the host fetch is a single device round-trip."""
    global _pack_outputs_jit
    if _pack_outputs_jit is None:
        import jax
        import jax.numpy as jnp

        _pack_outputs_jit = jax.jit(
            lambda a, n, l, u: jnp.concatenate(
                [a, n, l, u.astype(a.dtype)[None]]
            )
        )
    return _pack_outputs_jit(
        assigned_count, nodes_needed, lp_bound, unschedulable
    )


def _dispatch_and_record(  # lint: allow-complexity — the dispatch seam: one guard per optional telemetry/constraint surface
    inputs, targets, registry, solver, errors=None, cached_outputs=None,
    cmeta=None,
):
    """Solve + one host fetch + status/gauge writes. Returns the host
    output tuple (assigned_count, nodes_needed, lp_bound, unschedulable)
    so callers can memoize it; `cached_outputs` short-circuits the solve
    for identical inputs (the memo-hit path). `cmeta` (ConstraintMeta)
    enables the constraint verdict gauges — published from the solve's
    per-row assignment, skipped on the memo-hit path (identical inputs
    republish identical verdicts, already on the registry)."""
    out = None
    if cached_outputs is not None:
        assigned_count, nodes_needed, lp_bound, unschedulable = cached_outputs
    else:
        if solver is None:
            solver = B.solve
        # numpy arrays go straight through: the in-process jitted solve
        # device-puts them itself, and a remote solver serializes host
        # bytes — wrapping in jnp here would force a device round-trip
        # (and JAX init) in the control-plane process the sidecar split
        # exists to relieve
        with solver_trace("pendingcapacity.solve"):
            out = solver(inputs)

        # ONE device->host fetch for all four outputs: device_get still
        # issues a round-trip PER leaf (measured ~35 ms each through the
        # network tunnel), so the four outputs are first concatenated ON
        # DEVICE into a single i32[3T+1] vector — one transfer total.
        # Plain numpy outputs (sidecar path) pass through untouched.
        import jax

        if isinstance(out.assigned_count, jax.Array):
            packed = np.asarray(
                _pack_outputs(
                    out.assigned_count, out.nodes_needed, out.lp_bound,
                    out.unschedulable,
                )
            )
            n = out.assigned_count.shape[0]
            assigned_count = packed[:n]
            nodes_needed = packed[n : 2 * n]
            lp_bound = packed[2 * n : 3 * n]
            unschedulable = int(packed[3 * n])
        else:
            assigned_count, nodes_needed, lp_bound = (
                np.asarray(out.assigned_count),
                np.asarray(out.nodes_needed),
                np.asarray(out.lp_bound),
            )
            unschedulable = int(out.unschedulable)

    if (
        cmeta is not None
        and out is not None
        and B.has_constraint_operands(inputs)
    ):
        _publish_verdicts(
            registry, inputs, np.asarray(out.assigned), cmeta
        )

    register_gauges(registry)
    gauge = lambda g: registry.gauge(SUBSYSTEM, g)
    for t, (namespace, name, mp, *_rest) in enumerate(targets):
        if errors and (namespace, name) in errors:
            # poisoned row: keep its last-good status/gauges rather than
            # publishing the placeholder all-infeasible solve
            continue
        if mp is not None:  # due: status lands on the persisted instance
            mp.status.pending_capacity = PendingCapacityStatus(
                pending_pods=int(assigned_count[t]),
                additional_nodes_needed=int(nodes_needed[t]),
                lp_lower_bound=int(lp_bound[t]),
                unschedulable_pods=unschedulable,
            )
        gauge(PENDING_PODS).set(name, namespace, float(assigned_count[t]))
        gauge(ADDITIONAL_NODES_NEEDED).set(name, namespace, float(nodes_needed[t]))
        gauge(LP_LOWER_BOUND).set(name, namespace, float(lp_bound[t]))
        gauge(UNSCHEDULABLE_PODS).set(name, namespace, float(unschedulable))
    return (assigned_count, nodes_needed, lp_bound, unschedulable)


class PendingCapacityProducer:
    """Single-producer path; the controller batches when it can."""

    def __init__(
        self,
        mp,
        store,
        registry: Optional[GaugeRegistry] = None,
        solver=None,
        feed=None,
        template_resolver=None,
    ):
        self.mp = mp
        self.store = store
        self.registry = registry if registry is not None else default_registry()
        self.solver = solver
        self.feed = feed
        self.template_resolver = template_resolver
        register_gauges(self.registry)

    def reconcile(self) -> None:
        outcomes = solve_pending(
            self.store, [self.mp], self.registry, solver=self.solver,
            feed=self.feed, template_resolver=self.template_resolver,
        )
        error = outcomes.get(
            (self.mp.metadata.namespace, self.mp.metadata.name)
        )
        if error is not None:
            raise error
