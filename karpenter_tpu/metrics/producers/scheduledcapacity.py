"""ScheduledCapacity producer: time-based replica schedules.

reference: pkg/metrics/producers/scheduledcapacity/{producer,crontabs}.go —
for each behavior, compute the next cron match of its start and end patterns
in the configured timezone; a behavior is active when the next end comes at
or before the next start (i.e. we are inside the window). First active
behavior wins; otherwise defaultReplicas.
"""

from __future__ import annotations

import datetime
import zoneinfo
from typing import Optional

from karpenter_tpu.api.metricsproducer import ScheduledCapacityStatus
from karpenter_tpu.metrics.registry import GaugeRegistry, default_registry

SUBSYSTEM = "scheduled_capacity"
VALUE = "value"


def register_gauges(registry: GaugeRegistry) -> None:
    registry.register(SUBSYSTEM, VALUE)


class ScheduledCapacityProducer:
    def __init__(self, mp, registry: Optional[GaugeRegistry] = None, clock=None):
        self.mp = mp
        self.registry = registry if registry is not None else default_registry()
        self.clock = clock or (lambda: datetime.datetime.now(datetime.timezone.utc))
        register_gauges(self.registry)

    def reconcile(self) -> None:
        schedule = self.mp.spec.schedule
        if schedule.timezone is not None:
            try:
                tz = zoneinfo.ZoneInfo(schedule.timezone)
            except (zoneinfo.ZoneInfoNotFoundError, ValueError):
                raise RuntimeError("timezone was not a valid input")
        else:
            tz = datetime.timezone.utc
        now = self.clock().astimezone(tz)

        value = schedule.default_replicas
        for behavior in schedule.behaviors:
            next_start = behavior.start.to_cron().next_after(now)
            next_end = behavior.end.to_cron().next_after(now)
            # Inside the window iff the next end fires no later than the next
            # start (reference: producer.go:61-66). Spec order resolves
            # collisions: first match wins.
            if next_end <= next_start:
                value = behavior.replicas
                break

        self.mp.status.scheduled_capacity = ScheduledCapacityStatus(
            current_value=value
        )
        self.registry.gauge(SUBSYSTEM, VALUE).set(
            self.mp.metadata.name, self.mp.metadata.namespace, float(value)
        )
