"""MetricsProducer implementations + one-of factory dispatch.

reference: pkg/metrics/producers/factory.go:36-62.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.metrics.producers.fake import FakeProducer, NOT_IMPLEMENTED_ERROR
from karpenter_tpu.metrics.producers.pendingcapacity import PendingCapacityProducer
from karpenter_tpu.metrics.producers.queue import QueueProducer
from karpenter_tpu.metrics.producers.reservedcapacity import ReservedCapacityProducer
from karpenter_tpu.metrics.producers.scheduledcapacity import (
    ScheduledCapacityProducer,
)
from karpenter_tpu.utils.log import logger


def profile_from_template(template):
    """cloudprovider.NodeTemplate -> the (alloc floats, labels set,
    taints set) profile tuple _group_profile produces from live nodes —
    the ONE conversion shared by the scale-from-zero resolver and the
    what-if simulation. Mirrors _group_profile's conventions: the pods
    resource defaults when undeclared, only hard taints constrain."""
    from karpenter_tpu.metrics.producers.pendingcapacity import (
        DEFAULT_PODS_PER_NODE,
    )
    from karpenter_tpu.store.columnar import RESOURCE_PODS

    alloc = {r: q.to_float() for r, q in template.allocatable.items()}
    if alloc and alloc.get(RESOURCE_PODS, 0.0) <= 0:
        alloc[RESOURCE_PODS] = DEFAULT_PODS_PER_NODE
    labels = set(template.labels.items())
    taints = {
        (t.key, t.value, t.effect)
        for t in template.taints
        if t.effect in ("NoSchedule", "NoExecute", "PreferNoSchedule")
    }
    return alloc, labels, taints


class ProducerFactory:
    def __init__(
        self, store, cloud_provider_factory, registry=None, solver=None,
        default_priority: int = 0,
    ):
        from karpenter_tpu.metrics.registry import default_registry

        self.store = store
        self.cloud_provider_factory = cloud_provider_factory
        self.registry = registry if registry is not None else default_registry()
        # optional remote bin-pack (sidecar SolverClient.solve); None =
        # in-process device call
        self.solver = solver
        # fleet default for pods naming an unknown PriorityClass
        # (runtime --default-priority; docs/preemption.md)
        self.default_priority = default_priority
        self._pending_feed = None
        self._node_mirror = None
        self._reservations = None

    def node_mirror(self):
        """Shared watch-maintained Node mirror (store/columnar.NodeMirror),
        lazy like the feeds that use it."""
        if self._node_mirror is None:
            from karpenter_tpu.metrics.producers.pendingcapacity import (
                group_profile,
            )
            from karpenter_tpu.store.columnar import NodeMirror

            self._node_mirror = NodeMirror(self.store, group_profile)
        return self._node_mirror

    def reservations(self):
        """Incremental per-node reserved-resource sums for the
        reservedCapacity producer (store/columnar.ReservationsCache)."""
        if self._reservations is None:
            from karpenter_tpu.store.columnar import ReservationsCache

            self._reservations = ReservationsCache(self.store)
        return self._reservations

    def pending_feed(self):
        """Incremental feed for the pending-pods solve — pod arena, node
        profiles, producer selectors, all watch-maintained
        (store/columnar.py). Created on FIRST pendingCapacity use so
        deployments without that producer never pay the per-mutation watch
        cost."""
        if self._pending_feed is None:
            from karpenter_tpu.metrics.producers.pendingcapacity import (
                group_profile,
            )
            from karpenter_tpu.store.columnar import PendingFeed

            self._pending_feed = PendingFeed(
                self.store, group_profile, node_mirror=self.node_mirror(),
                default_priority=self.default_priority,
            )
        return self._pending_feed

    # scale-from-zero templates change on pool reconfiguration, not per
    # tick; cache resolutions so an idle tick never pays a cloud-API
    # round trip (the memoized-tick cost model in OPERATIONS.md). A
    # changed template is picked up within TTL + one producer interval.
    template_cache_ttl = 60.0

    def template_resolver(self):
        """(namespace, node_group_ref) -> Optional[(alloc floats, labels
        set, taints set)] — the scale-from-zero seam for the pending-pods
        solve. Resolves the referenced ScalableNodeGroup from the store,
        asks the cloud provider for its NodeTemplate (optional protocol
        method; providers that can't know their instance shape return
        None / don't implement it), and converts to the profile tuple
        _group_profile produces from live nodes. Results are TTL-cached
        (template_cache_ttl) so the per-tick profile loop never blocks on
        the provider API."""
        import time as _time

        if not hasattr(self, "_template_cache"):
            self._template_cache = {}

        def resolve(namespace: str, ref: str):
            now = _time.monotonic()
            cached = self._template_cache.get((namespace, ref))
            if cached is not None and cached[0] > now:
                return cached[1]

            def uncached():
                sng = self.store.try_get(
                    "ScalableNodeGroup", namespace, ref
                )
                if sng is None:
                    return None
                group = self.cloud_provider_factory.node_group_for(sng.spec)
                template_fn = getattr(group, "template", None)
                template = (
                    template_fn() if template_fn is not None else None
                )
                if template is None:
                    return None
                return profile_from_template(template)

            result = uncached()
            self._template_cache[(namespace, ref)] = (
                now + self.template_cache_ttl,
                result,
            )
            return result

        return resolve

    def for_producer(self, mp):
        spec = mp.spec
        if spec.pending_capacity is not None:
            return PendingCapacityProducer(
                mp, self.store, registry=self.registry, solver=self.solver,
                feed=self.pending_feed(),
                template_resolver=self.template_resolver(),
            )
        if spec.queue is not None:
            return QueueProducer(
                mp,
                self.cloud_provider_factory.queue_for(spec.queue),
                registry=self.registry,
            )
        if spec.reserved_capacity is not None:
            return ReservedCapacityProducer(
                mp, self.store, registry=self.registry,
                reservations=self.reservations(),
                node_mirror=self.node_mirror(),
            )
        if spec.schedule is not None:
            return ScheduledCapacityProducer(mp, registry=self.registry)
        logger().error(
            "Failed to instantiate metrics producer, no spec defined for %s",
            mp.metadata.name,
        )
        return FakeProducer(want_err=NOT_IMPLEMENTED_ERROR)
