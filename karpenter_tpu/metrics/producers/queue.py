"""Queue producer: queue length + oldest message age as scaling signals.

reference: pkg/metrics/producers/queue/{producer,gauges}.go.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.api.metricsproducer import QueueStatus
from karpenter_tpu.metrics.registry import GaugeRegistry, default_registry

SUBSYSTEM = "queue"
LENGTH = "length"
OLDEST_MESSAGE_AGE_SECONDS = "oldest_message_age_seconds"


def register_gauges(registry: GaugeRegistry) -> None:
    registry.register(SUBSYSTEM, LENGTH)
    registry.register(SUBSYSTEM, OLDEST_MESSAGE_AGE_SECONDS)


class QueueProducer:
    def __init__(self, mp, queue, registry: Optional[GaugeRegistry] = None):
        self.mp = mp
        self.queue = queue
        self.registry = registry if registry is not None else default_registry()
        register_gauges(self.registry)

    def reconcile(self) -> None:
        length = self.queue.length()
        oldest = self.queue.oldest_message_age_seconds()
        self.mp.status.queue = QueueStatus(
            length=length, oldest_message_age_seconds=oldest
        )
        name, namespace = self.mp.metadata.name, self.mp.metadata.namespace
        self.registry.gauge(SUBSYSTEM, LENGTH).set(name, namespace, float(length))
        self.registry.gauge(SUBSYSTEM, OLDEST_MESSAGE_AGE_SECONDS).set(
            name, namespace, float(oldest)
        )
