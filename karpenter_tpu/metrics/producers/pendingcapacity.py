"""PendingCapacity producer: would a scale-up let pending pods schedule?

reference: pkg/metrics/producers/pendingcapacity/producer.go:29-31 is a STUB
in the reference; the design intent (docs/designs/DESIGN.md "Pending Pods")
is a per-node-group signal derived from global bin-packing of unschedulable
pods. This is the north-star workload the TPU build vectorizes: the solver
in karpenter_tpu/ops/binpack.py evaluates the pods × node-groups constraint
matrix on device; this producer feeds it from the store and publishes the
per-group signal.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.metrics.registry import GaugeRegistry, default_registry

SUBSYSTEM = "pending_capacity"
PENDING_PODS = "pending_pods"
SCHEDULABLE_NOW = "schedulable_now"
ADDITIONAL_NODES_NEEDED = "additional_nodes_needed"


def register_gauges(registry: GaugeRegistry) -> None:
    for name in (PENDING_PODS, SCHEDULABLE_NOW, ADDITIONAL_NODES_NEEDED):
        registry.register(SUBSYSTEM, name)


class PendingCapacityProducer:
    def __init__(self, mp, store, registry: Optional[GaugeRegistry] = None):
        self.mp = mp
        self.store = store
        self.registry = registry if registry is not None else default_registry()
        register_gauges(self.registry)

    def reconcile(self) -> None:
        # Solver wiring lands with ops/binpack; the reference's producer is a
        # no-op stub at this point in its history too (producer.go:29-31).
        return None
