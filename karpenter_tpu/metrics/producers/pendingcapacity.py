"""PendingCapacity producer: would a scale-up let pending pods schedule?

reference: pkg/metrics/producers/pendingcapacity/producer.go:29-31 is a STUB
in the reference; the design intent (docs/designs/DESIGN.md "Pending Pods")
is a per-node-group signal derived from global bin-packing of unschedulable
pods, with the rule that each pod drives at most ONE group's scale-up.

This implementation is the TPU build's north star: ALL pendingCapacity
producers are solved together in one device call (ops/binpack) — the
controller's batch hook collects them per tick. The host side only encodes
the store snapshot into fixed-shape arrays:

- pending pods = Pods with no nodeName (the unschedulable set)
- each producer's node group contributes one row of the type matrix: its
  per-node shape is the elementwise max allocatable over ready+schedulable
  nodes (labels: intersection; taints: union — conservative on both sides)
- taint and label universes are encoded into padded bitsets so the device
  feasibility math is two boolean matmuls (see ops/binpack.py)

Gauges: karpenter_pending_capacity_{pending_pods,additional_nodes_needed,
lp_lower_bound,unschedulable_pods}{name,namespace}.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.api.core import Taint, is_ready_and_schedulable
from karpenter_tpu.api.metricsproducer import PendingCapacityStatus
from karpenter_tpu.metrics.registry import GaugeRegistry, default_registry
from karpenter_tpu.ops import binpack as B

SUBSYSTEM = "pending_capacity"
PENDING_PODS = "pending_pods"
ADDITIONAL_NODES_NEEDED = "additional_nodes_needed"
LP_LOWER_BOUND = "lp_lower_bound"
UNSCHEDULABLE_PODS = "unschedulable_pods"

RESOURCES = ("cpu", "memory", "pods")

# pad buckets for stable compiled shapes; universes GROW in these steps
# rather than truncating (silent constraint drops = false feasibility)
TAINT_PAD = 32
LABEL_PAD = 64
POD_PAD = 256  # pods padded to a multiple of this
GROUP_PAD = 8

# kubernetes' default max-pods when a node doesn't report a 'pods' allocatable
DEFAULT_PODS_PER_NODE = 110.0


def register_gauges(registry: GaugeRegistry) -> None:
    for name in (
        PENDING_PODS,
        ADDITIONAL_NODES_NEEDED,
        LP_LOWER_BOUND,
        UNSCHEDULABLE_PODS,
    ):
        registry.register(SUBSYSTEM, name)


def _pad(n: int, bucket: int) -> int:
    return max(bucket, ((n + bucket - 1) // bucket) * bucket)


def _group_profile(store, selector) -> Tuple[np.ndarray, set, set]:
    """(allocatable[R], labels set, taints set) for one node group.

    Ready+schedulable nodes define the group's shape; when the group is empty
    we fall back to any node matching the selector (a group scaled to zero
    still needs a shape to reason about — a limitation shared with every
    pending-pods autoscaler that lacks instance-type metadata).
    """
    nodes = store.list("Node", label_selector=selector)
    ready = [n for n in nodes if is_ready_and_schedulable(n)]
    candidates = ready or nodes
    alloc = np.zeros(len(RESOURCES), np.float32)
    labels: set = set()
    taints: set = set()
    for i, node in enumerate(candidates):
        for r, resource in enumerate(RESOURCES):
            q = node.status.allocatable.get(resource)
            if q is not None:
                alloc[r] = max(alloc[r], q.to_float())
        node_labels = set(node.metadata.labels.items())
        labels = node_labels if i == 0 else (labels & node_labels)
        # only hard taints exclude pods; PreferNoSchedule is a preference
        # in the kube scheduler, never a constraint
        taints |= {
            (t.key, t.value, t.effect)
            for t in node.spec.taints
            if t.effect in ("NoSchedule", "NoExecute")
        }
    if candidates and alloc[RESOURCES.index("pods")] <= 0:
        alloc[RESOURCES.index("pods")] = DEFAULT_PODS_PER_NODE
    return alloc, labels, taints


def solve_pending(store, due_producers: List, registry: GaugeRegistry) -> None:
    """One device call over ALL pendingCapacity producers in the store.

    Solving the full set — not just the due subset — is what upholds the
    DESIGN.md single-scale-up rule: assignment is only exclusive when every
    candidate group is in the same solve. Status objects are mutated on the
    due producers (the engine persists those); gauges are refreshed for every
    group since they are global registry state.
    """
    import jax.numpy as jnp

    due_keys = {
        (mp.metadata.namespace, mp.metadata.name): mp for mp in due_producers
    }
    producers = []
    for mp in sorted(
        store.list("MetricsProducer"),
        key=lambda m: (m.metadata.namespace, m.metadata.name),
    ):
        if mp.spec.pending_capacity is None:
            continue
        # use the caller's object for due producers so status lands on the
        # instance the engine will persist
        producers.append(
            due_keys.get((mp.metadata.namespace, mp.metadata.name), mp)
        )
    if not producers:
        return

    pods = [
        p
        for p in store.list("Pod")
        if not p.spec.node_name and p.status.phase in ("", "Pending")
    ]

    profiles = [
        _group_profile(store, mp.spec.pending_capacity.node_selector)
        for mp in producers
    ]

    # encode universes; sized to the data (padded), never truncated
    taint_universe: Dict[tuple, int] = {}
    for _, _, taints in profiles:
        for taint in sorted(taints):
            if taint not in taint_universe:
                taint_universe[taint] = len(taint_universe)
    label_universe: Dict[tuple, int] = {}
    for pod in pods:
        for item in sorted(pod.spec.node_selector.items()):
            if item not in label_universe:
                label_universe[item] = len(label_universe)

    n_pods = _pad(len(pods), POD_PAD)
    n_groups = _pad(len(producers), GROUP_PAD)
    n_taints = _pad(len(taint_universe), TAINT_PAD)
    n_labels = _pad(len(label_universe), LABEL_PAD)

    # one Taint object per universe entry, reused across all pods
    taint_objects = {
        k: Taint(key=taint[0], value=taint[1], effect=taint[2])
        for taint, k in taint_universe.items()
    }

    pod_requests = np.zeros((n_pods, len(RESOURCES)), np.float32)
    pod_valid = np.zeros(n_pods, bool)
    pod_intolerant = np.zeros((n_pods, n_taints), bool)
    pod_required = np.zeros((n_pods, n_labels), bool)
    for i, pod in enumerate(pods):
        requests = pod.requests()
        for r, resource in enumerate(RESOURCES[:-1]):
            q = requests.get(resource)
            pod_requests[i, r] = q.to_float() if q is not None else 0.0
        pod_requests[i, len(RESOURCES) - 1] = 1.0  # each pod occupies 1 slot
        pod_valid[i] = True
        for k, taint in taint_objects.items():
            pod_intolerant[i, k] = not any(
                tol.tolerates(taint) for tol in pod.spec.tolerations
            )
        for item, l in label_universe.items():
            pod_required[i, l] = pod.spec.node_selector.get(item[0]) == item[1]

    group_allocatable = np.zeros((n_groups, len(RESOURCES)), np.float32)
    group_taints = np.zeros((n_groups, n_taints), bool)
    group_labels = np.zeros((n_groups, n_labels), bool)
    for t, (alloc, labels, taints) in enumerate(profiles):
        group_allocatable[t] = alloc
        for taint, k in taint_universe.items():
            group_taints[t, k] = taint in taints
        for item, l in label_universe.items():
            group_labels[t, l] = item in labels

    out = B.binpack(
        B.BinPackInputs(
            pod_requests=jnp.asarray(pod_requests),
            pod_valid=jnp.asarray(pod_valid),
            pod_intolerant=jnp.asarray(pod_intolerant),
            pod_required=jnp.asarray(pod_required),
            group_allocatable=jnp.asarray(group_allocatable),
            group_taints=jnp.asarray(group_taints),
            group_labels=jnp.asarray(group_labels),
        )
    )

    assigned_count = np.asarray(out.assigned_count)
    nodes_needed = np.asarray(out.nodes_needed)
    lp_bound = np.asarray(out.lp_bound)
    unschedulable = int(out.unschedulable)

    register_gauges(registry)
    for t, mp in enumerate(producers):
        mp.status.pending_capacity = PendingCapacityStatus(
            pending_pods=int(assigned_count[t]),
            additional_nodes_needed=int(nodes_needed[t]),
            lp_lower_bound=int(lp_bound[t]),
            unschedulable_pods=unschedulable,
        )
        name, namespace = mp.metadata.name, mp.metadata.namespace
        gauge = lambda g: registry.gauge(SUBSYSTEM, g)
        gauge(PENDING_PODS).set(name, namespace, float(assigned_count[t]))
        gauge(ADDITIONAL_NODES_NEEDED).set(name, namespace, float(nodes_needed[t]))
        gauge(LP_LOWER_BOUND).set(name, namespace, float(lp_bound[t]))
        gauge(UNSCHEDULABLE_PODS).set(name, namespace, float(unschedulable))


class PendingCapacityProducer:
    """Single-producer path; the controller batches when it can."""

    def __init__(self, mp, store, registry: Optional[GaugeRegistry] = None):
        self.mp = mp
        self.store = store
        self.registry = registry if registry is not None else default_registry()
        register_gauges(self.registry)

    def reconcile(self) -> None:
        solve_pending(self.store, [self.mp], self.registry)
