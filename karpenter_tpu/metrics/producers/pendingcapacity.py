"""PendingCapacity producer: would a scale-up let pending pods schedule?

reference: pkg/metrics/producers/pendingcapacity/producer.go:29-31 is a STUB
in the reference; the design intent (docs/designs/DESIGN.md "Pending Pods")
is a per-node-group signal derived from global bin-packing of unschedulable
pods, with the rule that each pod drives at most ONE group's scale-up.

This implementation is the TPU build's north star: ALL pendingCapacity
producers are solved together in one device call (ops/binpack) — the
controller's batch hook collects them per tick. The host side only encodes
the store snapshot into fixed-shape arrays:

- pending pods = Pods with no nodeName (the unschedulable set)
- each producer's node group contributes one row of the type matrix: its
  per-node shape is the elementwise MIN allocatable over ready+schedulable
  nodes (labels: intersection; taints: union — conservative on all three
  axes: a scale-up signal must never claim feasibility that no real node
  shape of the group can satisfy)
- the resource universe is dynamic: cpu/memory/pods plus every extended
  resource (GPUs, TPUs, ephemeral-storage, ...) appearing in pending-pod
  requests or node allocatables, padded for compile stability; a pod
  requesting a resource a group doesn't provide fails fit there, and a pod
  requesting a resource no group provides counts as unschedulable
- taint and label universes are encoded into padded bitsets so the device
  feasibility math is two boolean matmuls (see ops/binpack.py)

Gauges: karpenter_pending_capacity_{pending_pods,additional_nodes_needed,
lp_lower_bound,unschedulable_pods}{name,namespace}.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.api.core import (
    HOSTNAME_TOPOLOGY_KEY,
    Taint,
    is_ready_and_schedulable,
    matches_affinity_shape,
    matches_selector,
    preference_score,
    selector_form_matches,
)
from karpenter_tpu.api.metricsproducer import PendingCapacityStatus
from karpenter_tpu.metrics.registry import GaugeRegistry, default_registry
from karpenter_tpu.observability import solver_trace
from karpenter_tpu.ops import binpack as B
from karpenter_tpu.store.columnar import (
    BASE_RESOURCES,
    RESOURCE_PODS,
    occupancy_from_pods,
    snapshot_from_pods,
)
from karpenter_tpu.utils.functional import pad_to_multiple

SUBSYSTEM = "pending_capacity"
PENDING_PODS = "pending_pods"
ADDITIONAL_NODES_NEEDED = "additional_nodes_needed"
LP_LOWER_BOUND = "lp_lower_bound"
UNSCHEDULABLE_PODS = "unschedulable_pods"

# base resources always present; the per-solve universe adds any extended
# resources (GPUs/TPUs/ephemeral-storage/...) seen in requests or allocatable,
# with the 'pods' slot axis always LAST (each pod occupies exactly 1).
# Single definition lives with the encoder (store/columnar.py).
RESOURCES_BASE = BASE_RESOURCES

# pad buckets for stable compiled shapes; universes GROW in these steps
# rather than truncating (silent constraint drops = false feasibility)
TAINT_PAD = 32
LABEL_PAD = 64
POD_PAD = 256  # pods padded to a multiple of this
GROUP_PAD = 8
RESOURCE_PAD = 4

# kubernetes' default max-pods when a node doesn't report a 'pods' allocatable
DEFAULT_PODS_PER_NODE = 110.0


def register_gauges(registry: GaugeRegistry) -> None:
    for name in (
        PENDING_PODS,
        ADDITIONAL_NODES_NEEDED,
        LP_LOWER_BOUND,
        UNSCHEDULABLE_PODS,
    ):
        registry.register(SUBSYSTEM, name)


_pad = pad_to_multiple


def _group_profile(
    nodes: List, selector: Dict[str, str]
) -> Tuple[Dict[str, float], set, set]:
    """(allocatable by resource name, labels set, taints set) for one group.

    Ready+schedulable nodes define the group's shape; when the group is empty
    we fall back to any node matching the selector (a group scaled to zero
    still needs a shape to reason about — a limitation shared with every
    pending-pods autoscaler that lacks instance-type metadata).

    The shape is the elementwise MIN over candidate nodes (a resource a node
    lacks counts as 0): in a heterogeneous group, claiming the max across
    nodes would invent a phantom node shape no real scale-up can deliver,
    and the signal would demand nodes forever without ever scheduling the
    pod. Min keeps the promise: any node the group adds can host what we
    report feasible.

    `nodes` is the full node list (listed ONCE per solve by the caller);
    selector filtering happens here to avoid O(groups) store scans.
    """
    matching = [
        n for n in nodes if matches_selector(n.metadata.labels, selector)
    ]
    ready = [n for n in matching if is_ready_and_schedulable(n)]
    candidates = ready or matching
    alloc: Dict[str, float] = {}
    labels: set = set()
    taints: set = set()
    for i, node in enumerate(candidates):
        node_alloc = {
            r: q.to_float() for r, q in node.status.allocatable.items()
        }
        if i == 0:
            alloc = node_alloc
        else:
            alloc = {
                r: min(alloc.get(r, 0.0), node_alloc.get(r, 0.0))
                for r in set(alloc) | set(node_alloc)
            }
        node_labels = set(node.metadata.labels.items())
        labels = node_labels if i == 0 else (labels & node_labels)
        # only hard taints exclude pods; PreferNoSchedule is a preference
        # in the kube scheduler, never a constraint
        taints |= {
            (t.key, t.value, t.effect)
            for t in node.spec.taints
            if t.effect in ("NoSchedule", "NoExecute")
        }
    if candidates and alloc.get(RESOURCE_PODS, 0.0) <= 0:
        alloc[RESOURCE_PODS] = DEFAULT_PODS_PER_NODE
    return alloc, labels, taints


def solve_pending(  # lint: allow-complexity — the one batched solve: per-target row isolation + path select
    store, due_producers: List, registry: GaugeRegistry, solver=None,
    pod_cache=None, feed=None, template_resolver=None,
) -> Dict[tuple, Optional[Exception]]:
    """One device call over ALL pendingCapacity producers in the store.

    Solving the full set — not just the due subset — is what upholds the
    DESIGN.md single-scale-up rule: assignment is only exclusive when every
    candidate group is in the same solve. Status objects are mutated on the
    due producers (the engine persists those); gauges are refreshed for every
    group since they are global registry state (non-due status writes would
    land on discarded copies, so only their selectors matter).

    `solver` is the Algorithm seam: any (inputs, buckets=...) ->
    BinPackOutputs callable — in-process ops/binpack.solve (default) or a
    sidecar SolverClient.solve (gRPC process split).

    `feed` (store/columnar.PendingFeed) makes the whole host side
    incremental: pod arena (O(changed pods)), memoized node profiles
    (recomputed only on node churn), and a producer-selector index (no
    per-tick store listing). `pod_cache` alone caches just the pod arena.
    With neither, the oracle path lists + encodes everything from the
    store — the reference the property tests compare the caches against.
    Outputs are identical on every path (the solver is permutation-
    invariant over pods: per-pod first-feasible assignment + bucket
    histograms).

    Returns {(namespace, name): error or None} for every target. Failure
    isolation is per ROW: one producer with a poisoned spec (e.g. a
    selector that blows up profile computation) fails only its own row —
    its group encodes as an empty (all-infeasible) shape and its status/
    gauges are left untouched — while every healthy producer still solves
    (mirrors the reference's per-object failure containment,
    pkg/controllers/controller.go:85-91). Only genuinely global failures
    (the pod snapshot, the device solve itself) fail the whole batch, by
    raising.

    `template_resolver` (producers.Factory.template_resolver) enables
    SCALE-FROM-ZERO: a callable (namespace, node_group_ref) ->
    Optional[(alloc floats, labels set, taints set)] consulted only when
    a producer's selector matches no nodes and its spec names a
    nodeGroupRef — the provider's declared instance shape stands in for
    the missing live node. Live nodes always win.
    """
    due_keys = {
        (mp.metadata.namespace, mp.metadata.name): mp for mp in due_producers
    }

    # group axis: (namespace, name, due-object-or-None, selector, ref) in
    # deterministic key order
    if feed is not None:
        targets = [
            (key[0], key[1], due_keys.get(key), selector, ref)
            for key, (selector, ref) in feed.producers.items()
        ]
    else:
        targets = []
        for mp in sorted(
            store.list("MetricsProducer"),
            key=lambda m: (m.metadata.namespace, m.metadata.name),
        ):
            if mp.spec.pending_capacity is None:
                continue
            key = (mp.metadata.namespace, mp.metadata.name)
            # use the caller's object for due producers so status lands on
            # the instance the engine will persist
            targets.append(
                (key[0], key[1], due_keys.get(key, mp),
                 mp.spec.pending_capacity.node_selector,
                 getattr(mp.spec.pending_capacity, "node_group_ref", ""))
            )
    if not targets:
        return {}

    if feed is None:
        nodes = store.list("Node")  # listed ONCE; profiles filter in-memory
    errors: Dict[tuple, Optional[Exception]] = {}
    profiles = []
    # template-derived rows participate in the encode-memo fingerprint:
    # templates live OUTSIDE the watch-versioned store state the
    # fingerprint otherwise covers
    template_rows = []
    for namespace, name, _, sel, ref in targets:
        try:
            profile = (
                feed.nodes.profile(sel)
                if feed is not None
                else _group_profile(nodes, sel)
            )
            if not profile[0] and ref and template_resolver is not None:
                resolved = template_resolver(namespace, ref)
                if resolved is not None:
                    profile = resolved
                    template_rows.append(
                        (namespace, name,
                         tuple(sorted(profile[0].items())),
                         tuple(sorted(profile[1])),
                         tuple(sorted(profile[2])))
                    )
            profiles.append(profile)
        except Exception as e:  # noqa: BLE001 — row-isolated failure
            errors[(namespace, name)] = e
            # empty shape: zero allocatable everywhere, which _feasibility
            # already rejects — the row solves as "nothing fits here"
            profiles.append(({}, set(), set()))

    # ONE encode implementation for every path (store/columnar.py): the
    # caches snapshot their watch-maintained arenas; the oracle path runs
    # the same detached encoder over a fresh store.list — no drift possible
    all_pods = None
    if feed is not None:
        snap = feed.pods.snapshot()
    elif pod_cache is not None:
        snap = pod_cache.snapshot()
    else:
        all_pods = store.list("Pod")
        snap = snapshot_from_pods(all_pods)

    # Existing-pod domain occupancy: only fleets with live spread/anti
    # constraints or soft preferences pay for a census (freed arena
    # slots are zeroed, so the id scan is exact); unconstrained fleets
    # skip it entirely — and their encode memo stays insensitive to
    # bound-pod churn
    needs_census = any(
        ids is not None and bool((ids != 0).any())
        for ids in (
            snap.spread_id,
            snap.anti_id,
            snap.soft_spread_id,
            snap.soft_anti_id,
        )
    )
    census = None
    namespace_state = ()
    if needs_census:
        if feed is not None:
            if feed.census is None:
                feed.census = DomainCensus(
                    feed.occupancy,
                    feed.nodes.nodes,
                    lambda: feed.nodes.version,
                )
            census = feed.census
        else:
            if all_pods is None:
                all_pods = store.list("Pod")
            census = DomainCensus(
                occupancy_from_pods(all_pods), lambda: nodes
            )
        # ONE Namespace read per solve: the encode-memo fingerprint and
        # the namespaceSelector resolution must see the SAME snapshot
        # (a label change landing between two reads would cache an
        # encode under a state it was not computed from)
        namespace_objects = store.list("Namespace")
        census.set_namespaces(namespace_objects)
        namespace_state = tuple(
            sorted(
                (
                    ns.metadata.name,
                    tuple(sorted(ns.metadata.labels.items())),
                )
                for ns in namespace_objects
            )
        )

    # Encode memo (feed path only): inputs are a pure function of
    # (pod arena generation, node set, producer selectors, occupancy).
    # When none of those moved since the last solve, reuse the previous
    # BinPackInputs OBJECT — the solver's identity-keyed device cache
    # (ops/binpack.solve) then skips the host->device transfer entirely,
    # which dominates the tick when the chip sits behind a network
    # tunnel.
    fingerprint = None
    if feed is not None:
        fingerprint = (
            snap.generation,
            feed.nodes.version,
            # bound-pod churn moves spread/anti masks only when a
            # constraint is live; otherwise pin the slot so the memo
            # survives scheduled-pod events
            feed.occupancy.generation if needs_census else -1,
            namespace_state,
            tuple(
                (
                    namespace,
                    name,
                    # poisoned specs (e.g. selector=None) must stay
                    # row-isolated: never assume dict shape here
                    tuple(sorted(sel.items()))
                    if isinstance(sel, dict)
                    else repr(sel),
                    ref,
                )
                for namespace, name, _, sel, ref in targets
            ),
            tuple(template_rows),
        )
        memo = feed.encode_memo
        cached_outputs = None
        if memo is not None and memo[0] == fingerprint:
            inputs = memo[1]
            # the solve is a pure function of inputs: identical inputs
            # reuse the PREVIOUS host outputs and skip the device call
            # entirely — an unchanged tick costs no round-trip at all
            cached_outputs = memo[2]
            _count_cache(registry, "hit")
        else:
            inputs = _encode_from_cache(snap, profiles, census=census)
            feed.encode_memo = (fingerprint, inputs, None)
            _count_cache(registry, "miss")
        host = _dispatch_and_record(
            inputs, targets, registry, solver, errors,
            cached_outputs=cached_outputs,
        )
        feed.encode_memo = (fingerprint, inputs, host)
    else:
        inputs = _encode_from_cache(snap, profiles, census=census)
        _dispatch_and_record(inputs, targets, registry, solver, errors)
    _publish_census(registry, census)
    return {
        (namespace, name): errors.get((namespace, name))
        for namespace, name, _, _, _ in targets
    }


def _group_arrays(profiles, resources, taint_universe, label_universe,
                  n_groups, n_resources, n_taints, n_labels):
    group_allocatable = np.zeros((n_groups, n_resources), np.float32)
    group_taints = np.zeros((n_groups, n_taints), bool)
    group_labels = np.zeros((n_groups, n_labels), bool)
    for t, (alloc, labels, taints) in enumerate(profiles):
        for r, resource in enumerate(resources):
            group_allocatable[t, r] = alloc.get(resource, 0.0)
        for taint, k in taint_universe.items():
            group_taints[t, k] = taint in taints
        for item, l in label_universe.items():
            group_labels[t, l] = item in labels
    return group_allocatable, group_taints, group_labels


def _dedup_rows(snap):
    """Collapse identical pod rows into (row indices, multiplicities).

    Two pods with the same (requests vector, required labels, toleration
    shape, validity) are interchangeable to every solver stage — same
    feasibility row, same first-feasible group, same size bucket — so the
    solve is exact over distinct shapes weighted by count. This is what
    makes the device upload O(distinct shapes), not O(pods): fleets are
    dominated by replicated workloads (Deployments/Jobs stamp identical
    pod templates).

    Raw-byte uniqueness on the concatenated row bytes: float bit-equality
    only (never merges distinct values; -0.0 vs 0.0 over-splits, which is
    merely suboptimal, never wrong).

    Fast path: cache-produced snapshots carry the INCREMENTALLY-maintained
    dedup (store/columnar.PendingPodCache._dedup_slots) — one rep row +
    count per distinct live shape, maintained at watch-event time. Only
    the S rep rows (distinct shapes, fleet-scale constant) are byte-sorted
    here for deterministic row order; the np.unique-over-all-rows below is
    the fallback for hand-built snapshots, and was ~60 ms/tick of argsort
    at 100k pods. The incremental dedup indexes live slots only; free
    (valid=False, zeroed) rows are dropped rather than collapsed into a
    zero row — output-equal, since invalid rows never contribute to any
    solver aggregate.
    """
    hi = snap.requests.shape[0]
    if hi == 0 or (snap.dedup_idx is not None and len(snap.dedup_idx) == 0):
        # hi > 0 with an empty dedup is the pending set draining to zero
        # while freed arena rows remain — the normal all-pods-scheduled
        # state, not an error
        return np.zeros(0, np.intp), np.zeros(0, np.int32)

    def row_bytes(idx):
        # idx=slice(None) gives zero-copy views (the arrays are already
        # contiguous); index arrays (the fast path's rep rows) gather
        n = hi if isinstance(idx, slice) else len(idx)
        parts = [
            np.ascontiguousarray(snap.requests[idx])
            .view(np.uint8)
            .reshape(n, -1),
            np.ascontiguousarray(snap.required[idx])
            .view(np.uint8)
            .reshape(n, -1),
            np.ascontiguousarray(snap.shape_id[idx])
            .view(np.uint8)
            .reshape(n, -1),
            snap.valid[idx].astype(np.uint8).reshape(n, 1),
        ]
        if snap.affinity_id is not None:
            parts.append(
                np.ascontiguousarray(snap.affinity_id[idx])
                .view(np.uint8)
                .reshape(n, -1)
            )
        if snap.preferred_id is not None:
            parts.append(
                np.ascontiguousarray(snap.preferred_id[idx])
                .view(np.uint8)
                .reshape(n, -1)
            )
        if snap.spread_id is not None:
            parts.append(
                np.ascontiguousarray(snap.spread_id[idx])
                .view(np.uint8)
                .reshape(n, -1)
            )
        if snap.anti_id is not None:
            parts.append(
                np.ascontiguousarray(snap.anti_id[idx])
                .view(np.uint8)
                .reshape(n, -1)
            )
        if snap.soft_spread_id is not None:
            parts.append(
                np.ascontiguousarray(snap.soft_spread_id[idx])
                .view(np.uint8)
                .reshape(n, -1)
            )
        if snap.soft_anti_id is not None:
            parts.append(
                np.ascontiguousarray(snap.soft_anti_id[idx])
                .view(np.uint8)
                .reshape(n, -1)
            )
        rows = np.ascontiguousarray(np.concatenate(parts, axis=1))
        return rows.view([("k", np.void, rows.shape[1])]).ravel()

    if snap.dedup_idx is not None:
        order = np.argsort(row_bytes(snap.dedup_idx))  # O(S log S), S tiny
        return snap.dedup_idx[order], snap.dedup_weight[order]

    _, idx, counts = np.unique(
        row_bytes(slice(None)), return_index=True, return_counts=True
    )
    return idx, counts.astype(np.int32)


class DomainCensus:
    """Existing-pod domain occupancy: the query layer between a
    ScheduledOccupancy census (store/columnar) and the spread/anti row
    expansions. The kube-scheduler evaluates topology spread skew and
    inter-pod (anti-)affinity against the pods ALREADY PLACED; without
    these counts the signal could promise a placement (e.g. a replica
    into a zone that already holds one) the scheduler then refuses.

    All queries are memoized per (occupancy generation, node version)
    epoch, so steady-state ticks answer from the memo; the underlying
    census and node mirror are incremental, so nothing here scans the
    store. Node-side work (label extraction, per-row node filters) and
    pod-side work (selector evaluation over distinct label sets) are
    memoized independently.

    Pod-side reads go through the census's MATERIALIZED VIEWS
    (ScheduledOccupancy.view_counts): per-pod-unique labels fragment a
    100k-replica StatefulSet into 100k label groups, and a per-epoch
    group scan costs ~600 ms — over the tick budget by itself. A
    selector's view is built once and maintained at event time, so a
    churned tick's recompute here is O(nodes with matching pods).
    """

    def __init__(self, occupancy, nodes_fn, node_version_fn=None):
        self._occupancy = occupancy
        self._nodes_fn = nodes_fn  # () -> list of Node objects
        self._node_version_fn = node_version_fn or (lambda: 0)
        # Namespace objects FROZEN per solve (set_namespaces): the
        # encode-memo fingerprint and the namespaceSelector resolution
        # must read the same snapshot, or a label change landing
        # between the two reads caches an encode under a state it was
        # not computed from (r3 code review)
        self._namespaces: list = []
        self._epoch: Optional[tuple] = None
        self._memo: Dict[tuple, object] = {}
        self._node_memo: Dict[tuple, object] = {}
        self._named_labels: Optional[List[Tuple[str, dict]]] = None
        # epoch invalidations (bound-pod or node churn between solves);
        # published as karpenter_runtime_census_refresh_total so an
        # operator can see how often constrained ticks pay a recompute.
        # `published`/`evictions_published` are _publish_census
        # watermarks.
        self.refreshes = 0
        self.published = 0
        self.evictions_published = 0

    def _fresh(self, generation: int) -> None:
        epoch = (generation, self._node_version_fn())
        if epoch != self._epoch:
            self._epoch = epoch
            self._memo.clear()
            self._node_memo.clear()
            self._named_labels = None
            self.refreshes += 1

    def _node_counts(self, namespace, sel_form) -> Dict[str, int]:
        """Epoch check + {node: matching-pod count} for one selector,
        through the census's materialized view. Unmemoized on purpose:
        the view read is O(matching nodes) and the epoch check must run
        BEFORE any memo is consulted (a cached answer from a previous
        occupancy generation must never serve this one)."""
        generation, counts = self._occupancy.view_counts(
            namespace, sel_form
        )
        self._fresh(generation)
        return counts

    def _fresh_now(self) -> None:
        self._fresh(self._occupancy.generation)

    def _nodes(self) -> List[Tuple[str, dict]]:
        if self._named_labels is None:
            self._named_labels = [
                (n.metadata.name, dict(n.metadata.labels))
                for n in self._nodes_fn()
            ]
        return self._named_labels

    def spread(
        self, namespace, sel_form, split_key, filter_token, node_passes
    ) -> Tuple[Dict[str, int], set]:
        """(counts: {domain value: matching-pod count}, present: domain
        values among filter-passing live nodes) for one spread
        constraint. The node filter is the ROW's nodeSelector + required
        node affinity (nodeAffinityPolicy=Honor, the k8s default; taints
        are Ignored per the nodeTaintsPolicy default): only nodes the
        incoming pod could land on define domains and contribute counts.
        """
        # O(1) epoch check BEFORE any memo lookup (a cached answer from
        # a previous occupancy generation must never serve this one);
        # the view is only copied on memo miss
        self._fresh_now()
        memo_hit = self._memo.get(
            ("spread", namespace, sel_form, split_key, filter_token)
        )
        by_node = (
            self._node_counts(namespace, sel_form)
            if memo_hit is None and sel_form is not None
            else {}
        )
        node_key = (split_key, filter_token)
        node_side = self._node_memo.get(node_key)
        if node_side is None:
            passing: Dict[str, str] = {}
            present: set = set()
            for name, labels in self._nodes():
                value = labels.get(split_key)
                if value is None or not node_passes(labels):
                    continue
                passing[name] = value
                present.add(value)
            node_side = (passing, present)
            self._node_memo[node_key] = node_side
        passing, present = node_side
        memo_key = ("spread", namespace, sel_form, split_key,
                    filter_token)
        got = self._memo.get(memo_key)
        if got is None:
            counts: Dict[str, int] = {}
            for node, n in by_node.items():
                value = passing.get(node)
                if value is not None:
                    counts[value] = counts.get(value, 0) + n
            got = (counts, present)
            self._memo[memo_key] = got
        return got

    def set_namespaces(self, namespaces: list) -> None:
        """Freeze the Namespace set for this solve (see __init__)."""
        self._namespaces = list(namespaces)

    def known_namespace_names(self) -> set:
        return {ns.metadata.name for ns in self._namespaces}

    def namespaces_matching(self, ns_sel_form: tuple) -> set:
        """Names of live namespaces whose labels match the canonical
        namespaceSelector form (empty form = all namespaces, the k8s
        rule)."""
        return {
            ns.metadata.name
            for ns in self._namespaces
            if selector_form_matches(ns_sel_form, ns.metadata.labels)
        }

    def occupancy_namespaces(self) -> set:
        """Every namespace the occupancy census holds scheduled pods
        in — the conservative ANTI fallback when no Namespace objects
        exist to resolve a namespaceSelector against (fixtures,
        simulations): blocking against every known namespace's pods
        can only under-promise."""
        return self._occupancy.namespace_names()

    def domain_counts(self, namespace, sel_form, key) -> Dict[str, int]:
        """{topology value: matching-pod count} over ALL live nodes —
        the scoring-side census (soft spread / preferred inter-pod
        affinity score existing placements; no node filter applies to
        a preference). One counting implementation: this is spread()
        with the pass-all node filter, sharing its memos — the same
        token the hard path's nodeAffinityPolicy=Ignore case uses."""
        counts, _present = self.spread(
            namespace, sel_form, key, ("ignore",), lambda labels: True
        )
        return counts

    def matching_nodes(self, namespace, sel_form) -> set:
        """Node names hosting scheduled pods matching the selector —
        the hostname-key census. kubernetes.io/hostname domains ARE
        node names (the kubelet's well-known label), so this reads the
        materialized per-node view directly instead of requiring the
        label on Node objects (fixtures often omit it)."""
        return set(self._node_counts(namespace, sel_form))

    def _workload_nodes(self, namespace, sel_forms) -> tuple:
        """(any_nodes, all_nodes_or_None): node-name sets occupied by
        pods matching ANY of the workload's selectors (the anti-blocking
        set — over-blocking is conservative) and, for co-location, the
        nodes hosting a matching pod for EVERY live selector — the
        scheduler's per-term rule: each required term is satisfied by a
        domain holding a pod matching THAT term's selector (they need
        not be the same pod). all_nodes is None when NO selector has a
        matching scheduled pod anywhere in the namespace (the k8s
        first-replica bootstrap: a required self-affinity term with no
        matching pod cluster-wide imposes nothing). All forms are read
        under ONE census lock hold (view_counts_many) so the set is
        generation-consistent — a replica moving nodes between
        per-form reads could otherwise appear on neither."""
        # O(1) epoch check before the memo (stale answers must never
        # cross occupancy generations)
        self._fresh_now()
        memo_key = ("workload", namespace, sel_forms)
        got = self._memo.get(memo_key)
        if got is not None:
            return got
        generation, per_form = self._occupancy.view_counts_many(
            namespace, sel_forms
        )
        self._fresh(generation)
        any_nodes: set = set()
        for counts in per_form:
            any_nodes |= counts.keys()
        live = [counts for counts in per_form if counts]
        all_nodes: Optional[set] = None
        if live:
            all_nodes = set(live[0])
            for counts in live[1:]:
                all_nodes &= counts.keys()
        got = (any_nodes, all_nodes)
        self._memo[memo_key] = got
        return got

    def anti_domains(self, namespace, sel_forms, keys) -> Dict[str, set]:
        """Per anti key: topology values already OCCUPIED by an existing
        pod matching any of the workload's selectors — a self-anti
        replica can never be placed there again. Unfiltered nodes: the
        scheduler's inter-pod terms have no node-affinity gate."""
        any_nodes, _ = self._workload_nodes(namespace, sel_forms)
        blocked: Dict[str, set] = {key: set() for key in keys}
        if any_nodes:
            for name, labels in self._nodes():
                if name not in any_nodes:
                    continue
                for key in keys:
                    value = labels.get(key)
                    if value is not None:
                        blocked[key].add(value)
        return blocked

    def co_domains(
        self, namespace, sel_forms, keys
    ) -> Optional[Dict[str, set]]:
        """Per co key: the topology values that HOLD a matching pod —
        required self-affinity forces new replicas into one of them.
        None = bootstrap (no matching scheduled pod anywhere): the
        term imposes nothing and the whole-workload-in-one-domain rule
        alone applies."""
        _, all_nodes = self._workload_nodes(namespace, sel_forms)
        if all_nodes is None:
            return None
        allowed: Dict[str, set] = {key: set() for key in keys}
        for name, labels in self._nodes():
            if name not in all_nodes:
                continue
            for key in keys:
                value = labels.get(key)
                if value is not None:
                    allowed[key].add(value)
        return allowed


def _row_node_filter(snap, slot: int) -> tuple:
    """(memo token, node_passes) for a snapshot row: the row's
    nodeSelector + required-node-affinity filter, applied to census
    nodes (nodeAffinityPolicy=Honor). Token is content-derived so census
    memo entries are shared across rows with the same filter."""
    sel_items = [
        snap.labels[c] for c in np.flatnonzero(snap.required[slot])
    ]
    shape = (
        snap.affinity_shapes[snap.affinity_id[slot]]
        if snap.affinity_shapes is not None and snap.affinity_id is not None
        else ()
    )
    token = (tuple(sorted(sel_items)), shape)

    def node_passes(labels: dict) -> bool:
        if any(labels.get(k) != v for k, v in sel_items):
            return False
        return not shape or matches_affinity_shape(labels, shape)

    return token, node_passes


def _water_fill(counts, caps, schedulable: int, seed: int) -> np.ndarray:
    """Distribute `schedulable` new replicas over domains that already
    hold `counts` matching pods, filling the least-loaded first (the
    only incremental order the skew check always admits: each placement
    lands on a current global minimum), capped per-domain by `caps`
    (None = unbounded). Returns per-domain additions. The remainder at
    the final water level rotates by content-keyed `seed`, so no domain
    is systematically overweighted across shapes (and the choice never
    depends on arena-local numbering). All-numpy: runs per dedup row on
    the churned-tick hot path."""
    c = np.asarray(counts, np.int64)
    cap = None if caps is None else np.asarray(caps, np.int64)

    def filled(level: int) -> int:
        add = np.clip(level - c, 0, None)
        if cap is not None:
            add = np.minimum(add, cap)
        return int(add.sum())

    lo = int(c.min())
    hi = (
        int(c.max()) + schedulable
        if cap is None
        else int((c + cap).max())
    )
    hi = max(lo, hi)
    while lo < hi:  # greatest level with filled(level) <= schedulable
        mid = (lo + hi + 1) // 2
        if filled(mid) <= schedulable:
            lo = mid
        else:
            hi = mid - 1
    level = lo
    out = np.clip(level - c, 0, None)
    if cap is not None:
        out = np.minimum(out, cap)
    remainder = schedulable - int(out.sum())
    if remainder:
        at_level = c + out == level
        can_grow = at_level if cap is None else at_level & (out < cap)
        candidates = np.flatnonzero(can_grow)
        if len(candidates):
            offset = seed % len(candidates)
            chosen = (
                np.arange(len(candidates)) - offset
            ) % len(candidates) < remainder
            out[candidates[chosen]] += 1
    return out


_UNBOUNDED = np.iinfo(np.int64).max // 4


def _entry_census(census, namespace, entry, row_filter):
    """({value: count}, present values) for one spread entry under one
    row filter — THE census dispatch (honor vs Ignore policy, the
    census-less fallback), shared by the split budgets and the anti
    path's zero-cap masks so the two can never diverge."""
    _key, _skew, _mind, sel, _self, honor = entry
    if census is None or sel is None:
        return {}, set()
    if honor:
        token, node_passes = row_filter
        return census.spread(
            namespace, sel, entry[0], token, node_passes
        )
    # nodeAffinityPolicy=Ignore: every live node exposing the key
    # defines a domain and contributes counts
    return census.spread(
        namespace, sel, entry[0], ("ignore",), lambda labels: True
    )


def _entry_caps(
    skew, min_domains, self_match, values, counts_e, present_e
) -> Tuple[np.ndarray, np.ndarray, bool]:
    """Per-value new-replica caps imposed by ONE spread constraint
    entry over the `values` domain list (_UNBOUNDED where it imposes
    nothing). The three regimes the scheduler's skew check induces:

    - selfMatch false: placements never accumulate into the counts, so
      the check is static per domain — existing count must stay within
      maxSkew of the global minimum (0 under the minDomains rule);
      violating domains cap at 0, the rest are unbounded.
    - minDomains unsatisfied: global minimum treated as 0 — each domain
      holds at most maxSkew matching pods INCLUDING existing ones.
    - otherwise: domains among filter-passing live nodes that the
      candidate groups can't fill freeze the global minimum, capping
      each value at outside-minimum + maxSkew.
    """
    d = len(values)
    c_e = np.array([counts_e.get(v, 0) for v in values], np.int64)
    caps = np.full(d, _UNBOUNDED, np.int64)
    min_rule = bool(min_domains) and d < min_domains
    if not self_match:
        floor = 0 if min_rule else min(
            [
                int(c_e.min()),
                *(counts_e.get(v, 0) for v in present_e - set(values)),
            ]
        )
        caps[c_e - floor > skew] = 0
    elif min_rule:
        caps = np.clip(skew - c_e, 0, None)
    else:
        outside = present_e - set(values)
        m_out = min(
            (counts_e.get(v, 0) for v in outside), default=None
        )
        if m_out is not None:
            caps = np.clip(m_out + skew - c_e, 0, None)
    return caps, c_e, min_rule


def _spread_state(namespace, entries, values, census, row_filter,  # lint: allow-complexity — one guard per budget regime (split/static/other-key/dead), the whole shape contract in one place
                  label_dicts, eligible, extra_dead=None):
    """IMMUTABLE per-(shape, node-filter) cap VIEW — what the
    scheduler's skew checks admit for a row carrying this filter:

    - `static`[d]: split-key caps from non-selfMatch entries (0 or
      unbounded — placements never consume them);
    - `budget`[d]: split-key caps from selfMatch entries, the MIN over
      every same-key entry (a single "first entry" cap could silently
      drop a tighter same-key constraint, r3 code review);
    - `counts`[d]: the first entry's census counts (the fill-order
      seed);
    - `dead`: groups excluded outright — extra_dead (the anti stage's
      row-independent exclusions) plus every entry's zero-capacity
      domains;
    - `others`: EVERY selfMatch entry — non-split ones first, then the
      split entries themselves, so the joint partition
      (_partition_chunks) re-validates the split after other keys
      narrow — as (entry index, maxSkew, value->groups, per-value caps
      with None = unbounded, per-value existing counts) 5-tuples. The
      split entries also join whenever MORE THAN ONE selfMatch entry
      shares the split key (or the seed entry isn't selfMatch): each
      same-key selector has its own census counts and its relative
      skew bound only holds through the partition (r3 advisor).

    CONSUMPTION lives one level up, in the per-WORKLOAD shared ledgers
    (_expand_spread_rows): placements count against the workload's
    skew regardless of which row's node filter admitted them, so rows
    with DIFFERENT filters still spend one budget — each row's
    effective cap is its own view minus everything the workload already
    placed (r3 code review)."""
    split_key = entries[0][0]

    def entry_counts(e):
        return _entry_census(census, namespace, e, row_filter)

    d = len(values)
    static = np.full(d, _UNBOUNDED, np.int64)
    budget = np.full(d, _UNBOUNDED, np.int64)
    # `extra_dead` seeds the dead mask with the anti stage's
    # row-independent exclusions (co pins, foreign terms): a domain
    # those will forbid must freeze the minimum HERE, before the split
    # balances weight into it (found by the soundness fuzz)
    dead = extra_dead.copy() if extra_dead is not None else None
    others = []
    # NON-SPLIT entries first: their zero-capacity domains (dead
    # groups) can leave a split domain with no live group at all, and
    # such a domain must then FREEZE the split-key global minimum like
    # an unfillable outside domain — otherwise the surviving domains
    # are over-promised capacity the scheduler's skew check denies
    # against the frozen one (r3 code review)
    for entry_idx, e in enumerate(entries):
        if e[0] == split_key:
            continue
        _key, skew, min_domains, _sel, self_match, _honor = e
        counts_e, present_e = entry_counts(e)
        vals2: Dict[str, list] = {}
        for t in eligible:
            value = label_dicts[t].get(e[0])
            if value is not None:
                vals2.setdefault(value, []).append(t)
        if not vals2:
            continue
        values2 = sorted(vals2)
        caps2, _, _ = _entry_caps(skew, min_domains, self_match,
                                  values2, counts_e, present_e)
        if (caps2 <= 0).any():
            if dead is None:
                dead = np.zeros(len(label_dicts), bool)
            for j, value in enumerate(values2):
                if caps2[j] <= 0:
                    dead[vals2[value]] = True
        if self_match:
            # EVERY selfMatch non-split entry participates in the
            # chunk partition — even with unbounded caps its skew
            # binds placements to a balanced distribution across its
            # domains (the soundness fuzz caught whole chunks piling
            # into one rack)
            others.append(
                (
                    entry_idx,
                    int(skew),
                    {v: vals2[v] for v in values2},
                    {
                        v: (
                            int(caps2[j])
                            if caps2[j] < _UNBOUNDED
                            else None
                        )
                        for j, v in enumerate(values2)
                    },
                    {v: counts_e.get(v, 0) for v in values2},
                )
            )
    has_other_partitions = bool(others)
    # The initial water-fill balances against entries[0]'s counts ONLY
    # (view["counts"]). That is a fixpoint of a selfMatch split entry's
    # relative skew bound just for THAT entry: a same-key selfMatch
    # entry with a DIFFERENT selector has its own census counts, and
    # with every live domain fillable its _entry_caps are unbounded —
    # nothing enforces its skew against its own imbalance unless it
    # joins the joint partition (r3 advisor, medium: two same-key
    # DoNotSchedule constraints promised a replica into a domain the
    # scheduler's second skew check denies).
    selfmatch_split = sum(
        1 for e in entries if e[0] == split_key and e[4]
    )
    seed_covers = bool(entries[0][4]) and selfmatch_split == 1
    split_groups: Dict[str, list] = {}
    for t in eligible:
        split_groups.setdefault(label_dicts[t][split_key], []).append(t)
    # split values every live group of which is dead: unfillable
    frozen = np.zeros(d, bool)
    if dead is not None:
        for j, v in enumerate(values):
            if all(dead[t] for t in split_groups[v]):
                frozen[j] = True
    for entry_idx, e in enumerate(entries):
        if e[0] != split_key:
            continue
        _key, skew, min_domains, _sel, self_match, _honor = e
        counts_e, present_e = entry_counts(e)
        caps_e, c_e, min_rule = _entry_caps(
            skew, min_domains, self_match, values, counts_e, present_e
        )
        if frozen.any():
            if self_match and not min_rule:
                # the frozen domains' counts cap everything else at
                # frozen-min + maxSkew, the outside-minimum rule
                m_frozen = int(c_e[frozen].min())
                caps_e = np.minimum(
                    caps_e, np.clip(m_frozen + skew - c_e, 0, None)
                )
            caps_e = caps_e.copy()
            caps_e[frozen] = 0  # nothing can actually land there
        if self_match:
            budget = np.minimum(budget, caps_e)
            # the split entry ALSO joins the joint partition (LAST, so
            # it re-validates after other keys narrow): when another
            # key's budget drops part of a domain's chunk, the split
            # key's own balance must re-bind against the shrunken
            # totals — the pre-allocation alone would leave e.g. zone
            # [2,0,1] standing after a rack cap emptied the middle
            # zone (found by the soundness fuzz). With NO other
            # partition entries AND a single selfMatch split entry
            # seeding the fill, nothing can shed and the split
            # water-fill is already a fixpoint of these exact bounds —
            # the common single-key fleet skips the partition entirely.
            # Same-key selfMatch entries beyond the seed always join
            # (seed_covers above).
            if has_other_partitions or not seed_covers:
                others.append(
                    (
                        entry_idx,
                        int(skew),
                        dict(split_groups),
                        {
                            v: (
                                int(caps_e[j])
                                if caps_e[j] < _UNBOUNDED
                                else None
                            )
                            for j, v in enumerate(values)
                        },
                        {v: counts_e.get(v, 0) for v in values},
                    )
                )
        else:
            static = np.minimum(static, caps_e)
    first_counts, _ = entry_counts(entries[0])
    counts = (
        np.array([first_counts.get(v, 0) for v in values], np.int64)
        if entries[0][4]
        else np.zeros(d, np.int64)
    )
    return {
        "static": static,
        "budget": budget,
        "counts": counts,
        "first_selfmatch": bool(entries[0][4]),
        "dead": dead,
        "others": others,
    }


def _anti_base_exclusion(shape, census, label_dicts, n_groups):  # lint: allow-complexity — one block per k8s exclusion rule (key presence, co pinning, foreign anti/co, namespace scoping)
    """(excluded mask, anti blocked values, co allowed values) — the
    ROW-INDEPENDENT group exclusions a pod_affinity_shape imposes:
    key-presence, required self co-location pinning to occupied
    domains, and FOREIGN required terms enforced against SCHEDULED
    state (anti forbids occupied domains; co requires one, with no
    first-replica bootstrap — a foreign selector the incoming pod
    doesn't match gets no such grace, the scheduler's rule; foreign
    hostname co can never be met by a fresh node). namespaceSelector
    scopes resolve against the frozen Namespace set, and an anti term
    also blocks against every occupancy namespace with NO Namespace
    object to judge. Shared by the anti expansion's plan AND the
    spread caps' frozen-domain feedback — the one implementation of
    the exclusion rules."""
    _hostname_excl, anti_keys, co_keys, ident, foreign = shape
    need_keys = [*anti_keys, *co_keys]
    blocked: Dict[str, set] = {}
    co_allowed = None
    if census is not None and ident:
        ident_ns, sel_forms = ident
        if anti_keys:
            blocked = census.anti_domains(ident_ns, sel_forms, anti_keys)
        if co_keys:
            co_allowed = census.co_domains(ident_ns, sel_forms, co_keys)
    excluded = np.zeros(n_groups, bool)
    for t, labels in enumerate(label_dicts):
        if any(key not in labels for key in need_keys):
            excluded[t] = True
        elif co_allowed is not None and any(
            labels[key] not in co_allowed[key] for key in co_keys
        ):
            # the workload already runs somewhere: required
            # self-affinity pins new replicas to domains that hold a
            # matching pod — groups elsewhere are excluded
            excluded[t] = True
    if foreign and census is not None:
        for sign, key, sel, scope in foreign:
            if scope[0] == "names":
                namespaces = scope[1]
            else:
                # ("selector", form, explicit): resolve against the
                # live Namespace set, unioned with the explicit list
                # (the k8s combination rule)
                _tag, ns_form, explicit = scope
                resolved = set(explicit)
                resolved |= census.namespaces_matching(ns_form)
                if sign < 0:
                    known = census.known_namespace_names()
                    resolved |= {
                        ns
                        for ns in census.occupancy_namespaces()
                        if ns not in known
                    }
                namespaces = sorted(resolved)
            if sign == 1 and key == HOSTNAME_TOPOLOGY_KEY:
                # true foreign hostname co: a fresh node can never host
                # the required neighbor, occupied or not — skip the
                # census walk entirely
                excluded[:] = True
                continue
            occupied: set = set()
            for foreign_ns in namespaces:
                if key == HOSTNAME_TOPOLOGY_KEY:
                    # hostname domains are node names; the per-node
                    # materialized view answers without requiring the
                    # hostname label on Node objects
                    occupied |= census.matching_nodes(foreign_ns, sel)
                else:
                    occupied |= census.domain_counts(
                        foreign_ns, sel, key
                    ).keys()
            if sign < 0:
                for t, labels in enumerate(label_dicts):
                    if labels.get(key) in occupied:
                        excluded[t] = True
            elif sign > 1 and not occupied:
                # bootstrap-eligible co (a SELF term projected over its
                # extra namespaces, api/core._foreign_terms): no
                # matching pod anywhere in scope means the scheduler's
                # first-replica grace applies — the term imposes
                # nothing; true foreign co (sign +1) gets no grace
                continue
            elif key == HOSTNAME_TOPOLOGY_KEY:
                excluded[:] = True
            else:
                for t, labels in enumerate(label_dicts):
                    value = labels.get(key)
                    if value is None or value not in occupied:
                        excluded[t] = True
    return excluded, blocked, co_allowed


def _anti_frozen_mask(shape, census, label_dicts, n_groups):
    """The anti-stage exclusions a SPREAD split must anticipate: base
    exclusion plus the co-only single-bucket pin (a spread split
    produces several rows, which triggers the multi-row pin in
    _expand_anti_rows). A spread domain whose groups are all excluded
    here can never receive its chunk — without feeding that back into
    the caps, the split balances over domains the anti stage then
    forbids, over-promising the survivors (found by the soundness
    fuzz). Anticipating the pin when the split ends up single-row only
    tightens: conservative."""
    _hostname_excl, anti_keys, co_keys, _ident, _foreign = shape
    excluded, _blocked, _co_allowed = _anti_base_exclusion(
        shape, census, label_dicts, n_groups
    )
    if co_keys and not anti_keys:
        excluded = _co_pin(excluded, label_dicts, co_keys, n_groups)
    return excluded


def _co_pin(excluded, label_dicts, co_keys, n_groups):
    """Pin a co-only multi-row workload to ONE deterministic co bucket
    (lexicographically first among non-excluded groups) — THE single
    implementation: the anti expansion and the spread caps' frozen
    feedback must pick the identical bucket, or the split balances
    weight into a domain the pin then forbids (the over-promise class
    the soundness fuzz caught)."""
    co_vecs: Dict[tuple, list] = {}
    for t, labels in enumerate(label_dicts):
        if not excluded[t]:
            co_vecs.setdefault(
                tuple(labels[k] for k in co_keys), []
            ).append(t)
    if not co_vecs:
        return excluded
    chosen = set(co_vecs[min(co_vecs)])
    excluded = excluded.copy()
    for t in range(n_groups):
        if t not in chosen:
            excluded[t] = True
    return excluded


def _spread_partition_view(shape, row_filter, label_dicts, census,
                           n_groups):
    """Partition-form view of ALL of a spread shape's entries, for rows
    whose SPLIT was skipped in favor of the anti rule: the anti
    hand-out decides the anti domains, but every spread entry still
    binds — through the same _partition_chunks water-fill the spread
    path uses (zero-cap exclusion alone let the hand-out concentrate a
    workload onto one rack, found by the soundness fuzz).

    dead: groups missing a constrained key, non-selfMatch zero-cap
    domains, and selfMatch currently-full domains (cap 0 — also kept
    in the partition caps, but dead lets the hand-out skip them
    without consuming a pick). others: every selfMatch entry as a
    partition dimension (skew + remaining caps + existing counts)."""
    namespace, entries = shape
    dead = np.zeros(n_groups, bool)
    others = []
    for idx, entry in enumerate(entries):
        key, skew, min_domains, _sel, self_match, _honor = entry
        vals: Dict[str, list] = {}
        for t, labels in enumerate(label_dicts):
            value = labels.get(key)
            if value is None:
                dead[t] = True
            else:
                vals.setdefault(value, []).append(t)
        if not vals:
            continue
        counts_e, present_e = _entry_census(
            census, namespace, entry, row_filter
        )
        values = sorted(vals)
        caps_e, _, _ = _entry_caps(
            skew, min_domains, self_match, values, counts_e, present_e
        )
        for j, value in enumerate(values):
            if caps_e[j] <= 0:
                dead[vals[value]] = True
        if self_match:
            others.append(
                (
                    ("spread", idx),
                    int(skew),
                    {v: vals[v] for v in values},
                    {
                        v: (
                            int(caps_e[j])
                            if caps_e[j] < _UNBOUNDED
                            else None
                        )
                        for j, v in enumerate(values)
                    },
                    {v: counts_e.get(v, 0) for v in values},
                )
            )
    return {
        "others": others,
        "dead": dead if dead.any() else None,
    }


def _partition_chunks(additions, masks, view, others_placed, n_groups,  # lint: allow-complexity — the wave loop: reach, floor, fill, charge, refund, repeat to fixpoint
                      seed):
    """Partition each chunk across every partition entry's domains by
    the SAME water-fill the split key uses: each entry's skew binds
    placements to a balanced distribution over its domains, and finite
    caps (occupancy, frozen minima) bound it absolutely. The relative
    bound holds against domains a chunk CANNOT reach, with WAVES to
    the fixpoint: a chunk capped by the floor may admit more once
    other chunks raise the unreachable minima (zone<->rack correlated
    topologies grow in lock-step instead of stranding weight). Totals
    and caps charge the WORKLOAD-shared `others_placed` ledger (keyed
    by entry index + value), so every row of a workload spends one
    budget; weight a LATER entry sheds is REFUNDED along its charge
    history, so phantom charges never starve later rows. Entries apply
    sequentially — a later entry re-partitions the earlier one's
    sub-chunks (product of domain counts at worst, fleet-scale
    constants). Dead groups are excluded from candidacy up front.

    Returns [(rank, count, extra mask or None)] — the pieces the
    caller emits; pods no piece can hold fall out (the caller counts
    them unschedulable). Mutates `others_placed`."""
    dead = view["dead"]
    pieces = []  # (rank, count, extra mask, charge history)
    for rank in range(len(additions)):
        chunk = int(additions[rank])
        if chunk:
            pieces.append((rank, chunk, None, ()))
    if not view["others"] or not pieces:
        return [(rank, count, extra) for rank, count, extra, _ in pieces]

    refunded = [False]

    def refund(history, amount):
        refunded[0] = True
        for ledger, value in history:
            ledger[value] = ledger.get(value, 0) - amount

    for entry_idx, skew, value_groups, caps2, counts2 in view["others"]:
        group_value = {}
        for value, groups in value_groups.items():
            for t in groups:
                group_value[t] = value
        placed = others_placed.setdefault(entry_idx, {})
        work = []  # (rank, remaining, extra, history, reachable)
        for rank, count, extra, history in pieces:
            allowed = ~masks[rank]
            if dead is not None:
                allowed = allowed & ~dead
            if extra is not None:
                allowed = allowed & ~extra
            reachable = sorted(
                {
                    group_value[t]
                    for t in np.flatnonzero(allowed)
                    if t in group_value
                }
            )
            work.append([rank, count, extra, history, reachable])
        taken = [dict() for _ in work]  # value -> count per piece
        progressed = True
        while progressed:
            progressed = False
            for w, (rank, remaining, _extra, _hist, reachable) in enumerate(
                work
            ):
                if remaining == 0 or not reachable:
                    continue
                totals = [
                    counts2.get(v, 0) + placed.get(v, 0)
                    for v in reachable
                ]
                floor = min(
                    counts2.get(v, 0) + placed.get(v, 0)
                    for v in value_groups
                )
                caps = []
                for v, total_v in zip(reachable, totals):
                    cap = caps2.get(v)
                    relative = max(0, floor + skew - total_v)
                    cap_v = (
                        relative
                        if cap is None
                        else min(
                            relative,
                            max(0, cap - placed.get(v, 0)),
                        )
                    )
                    caps.append(min(remaining, cap_v))
                schedulable = min(remaining, int(np.sum(caps)))
                if schedulable == 0:
                    continue
                adds = _water_fill(
                    totals, caps, schedulable, seed + rank
                )
                for j, value in enumerate(reachable):
                    take = int(adds[j])
                    if take:
                        taken[w][value] = taken[w].get(value, 0) + take
                        placed[value] = placed.get(value, 0) + take
                work[w][1] = remaining - schedulable
                progressed = True
        next_pieces = []
        for w, (rank, remaining, extra, history, _reachable) in enumerate(
            work
        ):
            if remaining:
                # this entry shed weight an EARLIER entry already
                # charged for: refund it, or the phantom charge starves
                # later rows (the charge-by-final-take rule, r3)
                refund(history, remaining)
            for value in sorted(taken[w]):
                restrict = np.ones(n_groups, bool)
                restrict[value_groups[value]] = False
                next_pieces.append(
                    [
                        rank,
                        taken[w][value],
                        restrict
                        if extra is None
                        else (extra | restrict),
                        (*history, (placed, value)),
                    ]
                )
        pieces = next_pieces

    # CASCADE: a refund at a later entry can invalidate the relative
    # floor that JUSTIFIED an earlier allocation (r0's third pod was
    # legal only while r1 held the charge the zone stage then shed —
    # soundness fuzz, heavy sweep). Verify every entry against the
    # FINAL ledgers and shed the excess from THIS row's pieces until
    # stable; prior rows stay valid because refunds only remove this
    # row's charges, so totals never drop below their end state. With
    # no refund, charges only grew the floor: nothing to verify.
    changed = refunded[0]
    while changed:
        changed = False
        for entry_idx, skew, value_groups, caps2, counts2 in (
            view["others"]
        ):
            ledger = others_placed[entry_idx]
            totals = {
                v: counts2.get(v, 0) + ledger.get(v, 0)
                for v in value_groups
            }
            floor = min(totals.values())
            for v in sorted(value_groups):
                excess = totals[v] - (floor + skew)
                cap = caps2.get(v)
                if cap is not None:
                    excess = max(excess, ledger.get(v, 0) - cap)
                if excess <= 0:
                    continue
                for piece in reversed(pieces):
                    if excess <= 0:
                        break
                    if piece[1] and any(
                        led is ledger and val == v
                        for led, val in piece[3]
                    ):
                        take = min(piece[1], excess)
                        piece[1] -= take
                        excess -= take
                        refund(piece[3], take)
                        changed = True
    return [
        (rank, count, extra)
        for rank, count, extra, _ in pieces
        if count
    ]

def _expand_spread_rows(  # lint: allow-complexity — per-domain chunking: each guard is a documented spread rule
    snap, profiles, row_idx, row_weight, label_dicts_fn, census=None
):
    """Topology spread (DoNotSchedule, non-hostname keys): partition each
    constrained row's weight into per-domain sub-rows, WATER-FILLED
    against the existing matching-pod counts per domain (DomainCensus).

    The solver assigns a whole weighted row to one group, so skew is
    enforced where it binds — in the GROUP choice: a domain is a distinct
    value of the topologyKey among the group-label INTERSECTIONS (a group
    spanning zones has no single domain value and is excluded, like a node
    missing the key is excluded by the kube-scheduler's PodTopologySpread
    filter). New replicas fill the least-loaded domains first — the only
    incremental order the scheduler's skew check always admits — so final
    totals are as balanced as the existing counts allow, satisfying any
    maxSkew >= 1. Domains among FILTER-PASSING live nodes that no
    candidate group serves freeze the global minimum: each eligible
    domain is then capped at (outside minimum + maxSkew) total, exactly
    the scheduler's skew bound against a domain a scale-up cannot fill.
    When minDomains exceeds the eligible domain count, the scheduler's
    global-minimum-0 rule applies — at most (maxSkew - existing) new
    pods per domain, the excess unschedulable. A pod that does NOT match
    its own constraint's selector (selfMatch false, incl. nil selector)
    never moves the counts: domains whose existing skew already exceeds
    the bound are excluded, the rest split balanced.

    Approximations, all conservative for a scale-up signal (may spread
    wider / mark more unschedulable than a lopsided-but-legal placement,
    never the reverse): maxSkew slack beyond 1 is not exploited when
    counts are level; with multiple constrained keys the split runs on
    the FIRST (key, selector) entry while the others are enforced
    through key-presence exclusion, zero-capacity dead masks, and the
    per-chunk domain PARTITION pass (_partition_chunks) that
    water-fills each chunk across their domains under their skews and
    remaining capacities; rows of one workload consume a SHARED budget
    in canonical content order; without a census (hand-built snapshot
    paths) counts are zero and the splits are plain balanced.

    Returns (row_idx, row_weight, spread_forbidden[rows, T]-or-None);
    unconstrained snapshots pass through untouched.
    """
    shapes = snap.spread_shapes
    if (
        len(row_idx) == 0
        or snap.spread_id is None
        or shapes is None
        or not (snap.spread_id[row_idx] != 0).any()
    ):
        return row_idx, row_weight, None

    n_groups = len(profiles)
    label_dicts = label_dicts_fn()
    live_ids = snap.spread_id[row_idx].copy()
    # rows whose self-anti-affinity carries a domain key are split
    # 1-per-domain by _expand_anti_rows — the most balanced placement a
    # topology key admits, so a second spread split would double-place
    # the weight; the spread keys still contribute key-presence
    # exclusion through the anti mask (docs/OPERATIONS.md)
    if snap.anti_id is not None and snap.anti_shapes is not None:
        anti_live = snap.anti_id[row_idx]
        domain_capped = np.array(
            [
                bool(snap.anti_shapes[a]) and bool(snap.anti_shapes[a][1])
                for a in anti_live
            ]
        )
        live_ids[domain_capped] = 0
        if not (live_ids != 0).any():
            return row_idx, row_weight, None

    # per live shape: (namespace, entries, ordered domain values,
    # [D, T] per-domain forbidden-mask matrix — built ONCE per shape,
    # rows are emitted by reference and only copied by the final stack)
    plan: Dict[int, tuple] = {}
    for s in np.unique(live_ids):
        shape = shapes[s]
        if not shape:
            continue
        namespace, entries = shape
        keys = [entry[0] for entry in entries]
        split_key = entries[0][0]
        domains: Dict[str, list] = {}
        eligible = []
        for t, labels in enumerate(label_dicts):
            if all(key in labels for key in keys):
                eligible.append(t)
                domains.setdefault(labels[split_key], []).append(t)
        values = sorted(domains)
        masks = np.ones((len(values), n_groups), bool)
        for rank, value in enumerate(values):
            masks[rank, domains[value]] = False
        plan[int(s)] = (namespace, entries, values, masks, eligible)

    all_forbidden = np.ones(n_groups, bool)
    no_forbidden = np.zeros(n_groups, bool)
    # per-(shape, filter) cap VIEWS are immutable; consumption lives in
    # per-WORKLOAD (per-sid) shared ledgers, so rows with DIFFERENT node
    # filters still spend one budget — placements count against the
    # workload's skew regardless of which filter admitted them (r3 code
    # review). Multi-row shapes process in canonical content order so
    # the hand-out never depends on arena-local numbering (the
    # path-stability rule _expand_anti_rows already follows); the
    # canonical key is only computed for shapes that actually have
    # several rows (it walks every universe — too hot for the common
    # one-row-per-workload tick).
    view_memo: Dict[tuple, dict] = {}
    ledgers: Dict[int, dict] = {}
    anti_dead_memo: Dict[int, np.ndarray] = {}
    sid_rows = collections.Counter(
        int(s) for s in live_ids if s and plan.get(int(s)) is not None
    )
    order = sorted(
        range(len(live_ids)),
        key=lambda i: (
            (0, (), i)
            if not live_ids[i] or plan.get(int(live_ids[i])) is None
            else (
                1,
                int(live_ids[i]),
                _canonical_row_key(snap, row_idx[i])
                if sid_rows[int(live_ids[i])] > 1
                else (),
            )
        ),
    )
    out_idx, out_weight, out_forbidden = [], [], []
    for i in order:
        sid = live_ids[i]
        entry = plan.get(int(sid))
        if entry is None:
            out_idx.append(row_idx[i])
            out_weight.append(row_weight[i])
            out_forbidden.append(no_forbidden)
            continue
        namespace, entries, values, masks, eligible = entry
        weight = int(row_weight[i])
        if not values or weight == 0:
            # no group exposes the key(s): unschedulable by spread —
            # keep the row, forbid everything, so the pods are COUNTED
            out_idx.append(row_idx[i])
            out_weight.append(row_weight[i])
            out_forbidden.append(all_forbidden)
            continue
        d = len(values)
        row_filter = (
            _row_node_filter(snap, row_idx[i])
            if census is not None
            else (None, None)
        )
        # the anti stage's row-independent exclusions (co pins, foreign
        # terms) feed the caps as dead groups, so a domain the anti
        # masks will forbid freezes the minimum instead of absorbing a
        # balanced chunk (found by the soundness fuzz); domain-capped
        # anti rows never reach here (their split is the anti rule's)
        anti_sid = (
            int(snap.anti_id[row_idx[i]])
            if snap.anti_id is not None and snap.anti_shapes is not None
            else 0
        )
        anti_dead = None
        if anti_sid and snap.anti_shapes[anti_sid]:
            if anti_sid in anti_dead_memo:
                anti_dead = anti_dead_memo[anti_sid]
            else:
                anti_dead = _anti_frozen_mask(
                    snap.anti_shapes[anti_sid], census, label_dicts,
                    n_groups,
                )
                if not anti_dead.any():
                    # a shape imposing no exclusions must not fragment
                    # the view memo or tax every chunk with a
                    # copy-and-OR of an all-False mask
                    anti_dead = None
                anti_dead_memo[anti_sid] = anti_dead
        view_key = (
            int(sid),
            row_filter[0],
            anti_sid if anti_dead is not None else 0,
        )
        view = view_memo.get(view_key)
        if view is None:
            view = _spread_state(
                namespace, entries, values, census, row_filter,
                label_dicts, eligible, extra_dead=anti_dead,
            )
            view_memo[view_key] = view
        ledger = ledgers.get(int(sid))
        if ledger is None:
            ledger = {
                "placed": np.zeros(d, np.int64),
                "counts": view["counts"].copy(),
                "others_placed": {},
            }
            ledgers[int(sid)] = ledger
        caps = np.minimum(
            np.clip(
                np.minimum(view["static"], view["budget"])
                - ledger["placed"],
                0,
                None,
            ),
            weight,
        )
        schedulable = min(weight, int(caps.sum()))
        # content-keyed remainder rotation (see _water_fill)
        seed = weight + int(
            np.ascontiguousarray(snap.requests[row_idx[i]])
            .view(np.uint8)
            .sum()
        )
        additions = _water_fill(
            ledger["counts"], caps, schedulable, seed
        )
        pieces = _partition_chunks(
            additions, masks, view, ledger["others_placed"], n_groups,
            seed,
        )
        # consume the shared ledgers with the KEPT counts (the
        # partition may shed part of a chunk): a later row of this
        # workload sees what THIS row placed — selfMatch placements
        # also accumulate into the fill-order counts, exactly like the
        # scheduler's sequential skew accounting
        kept = np.zeros(d, np.int64)
        for rank, count, _extra in pieces:
            kept[rank] += count
        ledger["placed"] = ledger["placed"] + kept
        if view["first_selfmatch"]:
            ledger["counts"] = ledger["counts"] + kept
        dead = view["dead"]
        placed = 0
        for rank, count, extra in pieces:
            placed += count
            forbidden = masks[rank]
            if dead is not None or extra is not None:
                forbidden = forbidden.copy()
                if dead is not None:
                    forbidden |= dead
                if extra is not None:
                    forbidden |= extra
            out_idx.append(row_idx[i])
            out_weight.append(np.int32(count))
            out_forbidden.append(forbidden)
        if placed < weight:
            out_idx.append(row_idx[i])
            out_weight.append(np.int32(weight - placed))
            out_forbidden.append(all_forbidden)
    return (
        np.asarray(out_idx, np.intp),
        np.asarray(out_weight, np.int32),
        np.stack(out_forbidden) if out_forbidden else None,
    )


def _total_order(value):
    """Totally-ordered encoding of a canonical shape component. Shape
    tuples embed OPTIONAL selector forms (None when the field is absent
    — e.g. spread_shape's selectorForm, metav1 nil-selector semantics),
    and plain tuple comparison raises TypeError on None-vs-tuple, so a
    legal spec mixing a nil and a set selector would crash the whole
    solve (r3 advisor, high). Every node gets a type rank so any two
    encoded keys compare: None < numbers < strings < tuples."""
    if isinstance(value, tuple):
        return (3, tuple(_total_order(v) for v in value))
    if value is None:
        return (0, 0.0)
    if isinstance(value, str):
        return (2, value)
    return (1, float(value))  # bool / int / float


def _canonical_row_key(snap, slot: int) -> tuple:
    """Arena-independent content key for a snapshot row: every component
    is resolved through its universe REGISTRY (resource names, label
    items, canonical shape tuples), so two arenas that numbered the same
    pod shapes differently still produce the same key. Used to order
    domain hand-out across a workload's rows (_expand_anti_rows). The
    result is passed through _total_order so keys embedding optional
    (None) selector forms stay comparable under sorted()."""
    requests = tuple(
        sorted(
            (snap.resources[r], float(snap.requests[slot, r]))
            for r in range(len(snap.resources))
            if snap.requests[slot, r] != 0
        )
    )
    selector = tuple(
        sorted(
            snap.labels[c]
            for c in range(len(snap.labels))
            if snap.required[slot, c]
        )
    )
    tolerations = tuple(
        sorted(
            (
                (t.key, t.operator, t.value, t.effect)
                for t in snap.shape_tolerations[snap.shape_id[slot]]
            ),
            # toleration value/key may be None (Exists operator)
            key=_total_order,
        )
    )
    affinity = (
        snap.affinity_shapes[snap.affinity_id[slot]]
        if snap.affinity_shapes is not None and snap.affinity_id is not None
        else ()
    )
    preferred = (
        snap.preferred_shapes[snap.preferred_id[slot]]
        if snap.preferred_shapes is not None
        and snap.preferred_id is not None
        else ()
    )
    spread = (
        snap.spread_shapes[snap.spread_id[slot]]
        if snap.spread_shapes is not None and snap.spread_id is not None
        else ()
    )
    soft = tuple(
        shapes[ids[slot]]
        for shapes, ids in (
            (snap.soft_spread_shapes, snap.soft_spread_id),
            (snap.soft_anti_shapes, snap.soft_anti_id),
        )
        if shapes is not None and ids is not None
    )
    return _total_order(
        (requests, selector, tolerations, affinity, preferred, spread,
         soft)
    )


def _expand_anti_rows(  # lint: allow-complexity — per-domain capping: each guard is a documented anti-affinity rule
    snap, profiles, row_idx, row_weight, prior_forbidden, label_dicts_fn,
    census=None,
):
    """Required inter-pod SELF-(anti-)affinity (api/core.pod_affinity_shape):

    - hostname anti-affinity marks the row EXCLUSIVE (one pod per node,
      the ops/binpack.py pod_exclusive operand);
    - domain anti-affinity (zone/region keys) caps the workload at ONE
      pod per topology domain OF EVERY KEY: eligible groups bucket by
      combined key values and a greedy pass selects domains no two of
      which share any key's value; the row splits into weight-1
      sub-rows, each masked to one selected domain's groups, the
      excess reported unschedulable. Rows sharing an anti shape (same
      workload identity — the canonical self-matching selector, so
      StatefulSet per-pod labels don't fragment it) draw from one
      shared domain sequence, so a workload split across
      request-distinct rows (e.g. mid-VPA-rollout) still never doubles
      up a domain;
    - co-location affinity keys exclude groups missing the key (group
      profiles hold the label INTERSECTION, so a group spanning domain
      values drops the key and is excluded). Combined with domain
      anti-affinity, ALL the workload's sub-rows pin to the single co
      bucket offering the most anti domains (independent per-domain
      assignment could split replicas across co domains the scheduler
      forces together). Co-location alone: the solver's whole-row-to-
      one-group assignment keeps a single-row workload in one domain;
      a workload split across request-distinct rows pins to one
      deterministic co bucket.

    A domain is a distinct topologyKey value among group-label
    intersections, exactly the _expand_spread_rows rule; a row with both
    hard spread and domain anti-affinity is split by the anti rule (the
    most balanced placement possible — spread's split is skipped, see
    _expand_spread_rows) while its spread keys contribute key-presence
    exclusion here.

    EXISTING-pod occupancy (`census`, a DomainCensus): domains already
    holding a scheduled pod matching the workload's selectors are spent
    for anti-affinity (seeded into the greedy pass), and required
    co-location pins new replicas to the domains that hold a matching
    pod — unless NO matching pod exists anywhere (the k8s first-replica
    bootstrap, which imposes nothing). census=None (hand-built
    snapshots) means no occupancy: bootstrap semantics throughout. Conservative throughout: the signal may report more
    unschedulable or spread wider than a legal placement, never claim
    feasibility the kube-scheduler would deny for the modeled slice
    (docs/OPERATIONS.md 'Scheduling fidelity').

    prior_forbidden (the spread expansion's per-row mask, aligned with
    the INPUT rows) is carried through the re-expansion: every output
    row inherits its source row's mask OR'd with the anti exclusions.

    Domain hand-out across a workload's rows is ordered by CANONICAL
    row content (_canonical_row_key), never by dedup-row position:
    byte-sorted row order depends on arena-local id numbering, so a
    position-ordered hand-out could give the oracle and feed paths
    different row->domain assignments — and with per-domain taints,
    different outputs — breaking the outputs-identical-on-every-
    encode-path invariant (r3 code review; the spread expansion's
    content-keyed rotation avoids the same trap).

    Returns (row_idx, row_weight, forbidden[rows, T]-or-None,
    exclusive[rows]-or-None); unconstrained snapshots pass untouched.
    """
    shapes = snap.anti_shapes
    if (
        len(row_idx) == 0
        or snap.anti_id is None
        or shapes is None
        or not (snap.anti_id[row_idx] != 0).any()
    ):
        return row_idx, row_weight, prior_forbidden, None

    n_groups = len(profiles)
    label_dicts = label_dicts_fn()
    live_ids = snap.anti_id[row_idx]
    spread_shapes = snap.spread_shapes
    live_spread = (
        snap.spread_id[row_idx] if snap.spread_id is not None else None
    )

    # per live anti shape: (ordered domain group-lists or None,
    # key-exclusion mask, hostname_exclusive); the domain sequence is
    # SHARED across rows with the same shape, handed out in canonical
    # content order (path-stable — see docstring)
    sid_rows = collections.Counter(int(s) for s in live_ids)
    # (spread shape id, row filter token) -> partition view; ledgers
    # keyed per spread sid ONLY (one budget per workload) — for anti
    # rows whose spread split was skipped (see below)
    spread_view_memo: Dict[tuple, dict] = {}
    spread_ledgers: Dict[int, dict] = {}
    plan: Dict[int, tuple] = {}
    for s in np.unique(live_ids):
        shape = shapes[s]
        if not shape:
            continue
        hostname_excl, anti_keys, co_keys, ident, foreign = shape
        excluded, blocked, co_allowed = _anti_base_exclusion(
            shape, census, label_dicts, n_groups
        )
        domains = None
        if anti_keys:
            # Combined-value accounting so EVERY key's cap holds (a
            # first-key-only split can put two replicas in one domain
            # of a coarser key, r3 code review): eligible groups bucket
            # by (co-key values, anti-key values); within each co
            # bucket, greedily select anti domains such that no two
            # share ANY key's value; the co bucket with the most
            # selected domains wins — the workload's co-location keys
            # pin ALL its replicas to that one bucket (a per-domain
            # independent assignment could split replicas across co
            # domains the scheduler forces together). Deterministic:
            # sorted iteration, count-then-lexicographic choice.
            buckets: Dict[tuple, Dict[tuple, list]] = {}
            for t, labels in enumerate(label_dicts):
                if excluded[t]:
                    continue
                co_vec = tuple(labels[k] for k in co_keys)
                anti_vec = tuple(labels[k] for k in anti_keys)
                buckets.setdefault(co_vec, {}).setdefault(
                    anti_vec, []
                ).append(t)
            best: Optional[tuple] = None
            for co_vec in sorted(buckets):
                # domains an EXISTING replica occupies are spent: seed
                # the per-key used sets so no new replica shares any
                # key's value with a pod already placed
                used: List[set] = [
                    set(blocked.get(key, ())) for key in anti_keys
                ]
                selected = []
                for anti_vec in sorted(buckets[co_vec]):
                    if any(
                        value in used[i]
                        for i, value in enumerate(anti_vec)
                    ):
                        continue
                    for i, value in enumerate(anti_vec):
                        used[i].add(value)
                    selected.append(buckets[co_vec][anti_vec])
                if best is None or len(selected) > len(best[1]):
                    best = (co_vec, selected)
            domains = best[1] if best is not None else []
        elif co_keys and sid_rows[int(s)] > 1:
            # co-location-only workload split across request-distinct
            # rows (mid-VPA): whole-row-to-one-group no longer pins ONE
            # domain, so pin all the workload's rows to a single
            # deterministic co bucket (_co_pin — the same choice the
            # spread caps anticipated); single-row workloads keep full
            # group freedom
            excluded = _co_pin(excluded, label_dicts, co_keys, n_groups)
        plan[int(s)] = (domains, excluded, bool(hostname_excl))

    def row_spread_view(i):
        """Partition view + shared ledger for an anti-split row's SKIPPED
        spread shape: the anti hand-out decides the anti domains, but
        every spread entry still binds through the same water-fill
        partition the spread path uses (r3; zero-cap exclusion alone let
        a workload concentrate onto one rack — soundness fuzz)."""
        if (
            live_spread is None
            or live_spread[i] == 0
            or spread_shapes is None
        ):
            return None, None
        spread_sid = int(live_spread[i])
        row_filter = (
            _row_node_filter(snap, row_idx[i])
            if census is not None
            else (None, None)
        )
        key = (spread_sid, row_filter[0])
        view = spread_view_memo.get(key)
        if view is None:
            view = _spread_partition_view(
                spread_shapes[spread_sid], row_filter, label_dicts,
                census, n_groups,
            )
            spread_view_memo[key] = view
        # the LEDGER is per WORKLOAD (per spread sid), never per filter
        # token: rows with different node selectors must spend one
        # budget (r3 code review)
        return view, spread_ledgers.setdefault(spread_sid, {})

    # hand out domains per workload in canonical content order; a
    # domain dead for one row (its spread capacity spent, or every
    # group of it excluded) is SKIPPED, not consumed — a later row may
    # still use it, while consumption stays GLOBAL per workload so no
    # two rows ever share a domain (the no-doubling invariant)
    picks: Dict[int, list] = {}
    row_views: Dict[int, tuple] = {}
    rows_by_sid: Dict[int, list] = {}
    for i, sid in enumerate(live_ids):
        entry = plan.get(int(sid))
        if entry is not None and entry[0] is not None:
            rows_by_sid.setdefault(int(sid), []).append(i)
    for sid, rows_i in rows_by_sid.items():
        domain_list = plan[sid][0]
        if len(rows_i) > 1:
            rows_i = sorted(
                rows_i,
                key=lambda i: _canonical_row_key(snap, row_idx[i]),
            )
        consumed = [False] * len(domain_list)
        for i in rows_i:
            view, ledger = row_spread_view(i)
            if view is not None:
                row_views[i] = (view, ledger)
            dead = view["dead"] if view is not None else None
            need = int(row_weight[i])
            mine = []
            for rank, groups in enumerate(domain_list):
                if len(mine) >= need:
                    break
                if consumed[rank]:
                    continue
                if dead is not None and all(dead[t] for t in groups):
                    continue
                consumed[rank] = True
                mine.append(rank)
            picks[i] = mine

    out_idx, out_weight, out_forbidden, out_exclusive = [], [], [], []
    for i, sid in enumerate(live_ids):
        prior = (
            prior_forbidden[i]
            if prior_forbidden is not None
            else np.zeros(n_groups, bool)
        )
        entry = plan.get(int(sid))
        if entry is None:
            out_idx.append(row_idx[i])
            out_weight.append(row_weight[i])
            out_forbidden.append(prior)
            out_exclusive.append(False)
            continue
        domains, excluded, hostname_excl = entry
        excluded = excluded | prior
        if i in row_views and row_views[i][0]["dead"] is not None:
            # partial-dead domains stay usable through their live
            # groups; the mask forbids the spent ones
            excluded |= row_views[i][0]["dead"]
        weight = int(row_weight[i])
        if domains is None:
            # hostname/co-location only: no split, mask + flag ride along
            out_idx.append(row_idx[i])
            out_weight.append(row_weight[i])
            out_forbidden.append(excluded)
            out_exclusive.append(hostname_excl)
            continue
        mine = picks[i]
        view_ledger = row_views.get(i)
        placed = 0
        # content-keyed, invariant across this row's ranks (arena
        # numbering must not steer the partition)
        content_sum = int(
            np.ascontiguousarray(snap.requests[row_idx[i]])
            .view(np.uint8)
            .sum()
        )
        for rank in mine:
            forbidden = np.ones(n_groups, bool)
            forbidden[domains[rank]] = False
            forbidden |= excluded
            if view_ledger is None:
                placed += 1
                out_idx.append(row_idx[i])
                out_weight.append(np.int32(1))
                out_forbidden.append(forbidden)
                out_exclusive.append(hostname_excl)
                continue
            # the SKIPPED spread shape still binds: partition this
            # weight-1 sub-row across every spread entry's domains
            # against the workload-shared ledger (picking e.g. the
            # rack with remaining balance, not whichever group the
            # solver tries first)
            view, ledger = view_ledger
            seed = rank + content_sum
            pieces = _partition_chunks(
                np.array([1], np.int64), [forbidden], view, ledger,
                n_groups, seed,
            )
            for _rank0, count, extra in pieces:
                placed += count
                sub = forbidden
                if extra is not None:
                    # view["dead"] already rode in through `excluded`
                    sub = sub | extra
                out_idx.append(row_idx[i])
                out_weight.append(np.int32(count))
                out_forbidden.append(sub)
                out_exclusive.append(hostname_excl)
        if weight > placed:
            # beyond the usable domain count / spread capacity:
            # unschedulable by anti-affinity — keep the excess as a
            # forbidden-everywhere row so it COUNTS
            out_idx.append(row_idx[i])
            out_weight.append(np.int32(weight - placed))
            out_forbidden.append(np.ones(n_groups, bool))
            out_exclusive.append(hostname_excl)
    return (
        np.asarray(out_idx, np.intp),
        np.asarray(out_weight, np.int32),
        np.stack(out_forbidden) if out_forbidden else None,
        np.asarray(out_exclusive, bool),
    )


def _score_rows(  # lint: allow-complexity — one block per scoring plugin, the kube-scheduler's score composition in one place
    snap, profiles, row_idx, label_dicts_fn, census, n_pods, n_groups
):
    """The kube-scheduler's scoring plugins over candidate groups ->
    the solver's pod_group_score operand (argmax among feasible, index
    tie-break). Three plugins, combined with the scheduler's default
    weights after per-row min-max normalization to 0..100 (min-max is
    monotone, so a fleet using only ONE plugin keeps exactly the raw
    scores' argmax and tie-break order):

    - NodeAffinity (weight 1): preferred-term weight sums
      (api/core.preference_score).
    - PodTopologySpread (weight 2): ScheduleAnyway constraints prefer
      domains with FEWER existing matching pods (DomainCensus counts);
      groups missing the key rank below every keyed group, matching
      the scoring plugin's treatment of keyless nodes.
    - InterPodAffinity (weight 1): preferred self-(anti-)affinity
      terms add sign x weight per existing matching pod in the
      group's domain.

    Returns None when no live row carries any preference — the common
    fleet skips the score operand entirely. census=None (hand-built
    snapshots) scores with zero counts: spread still ranks keyless
    groups last; inter-pod terms contribute nothing.
    """
    hi = len(row_idx)
    if hi == 0:
        return None
    n_real = len(profiles)
    pieces = []  # (plugin weight, raw[hi, n_real])

    shapes = snap.preferred_shapes
    live = (
        snap.preferred_id[row_idx]
        if snap.preferred_id is not None and shapes is not None
        else None
    )
    if live is not None and (live != 0).any():
        raw = np.zeros((len(shapes), n_real), np.float32)
        for s in np.unique(live):
            shape = shapes[s]
            if not shape:
                continue
            for t, labels in enumerate(label_dicts_fn()):
                raw[s, t] = preference_score(labels, shape)
        pieces.append((1.0, raw[live]))

    shapes = snap.soft_spread_shapes
    live = (
        snap.soft_spread_id[row_idx]
        if snap.soft_spread_id is not None and shapes is not None
        else None
    )
    if live is not None and (live != 0).any():
        raw = np.zeros((len(shapes), n_real), np.float32)
        for s in np.unique(live):
            shape = shapes[s]
            if not shape:
                continue
            namespace, entries = shape
            for key, sel in entries:
                counts = (
                    census.domain_counts(namespace, sel, key)
                    if census is not None and sel is not None
                    else {}
                )
                # keyless groups rank strictly below every keyed one
                worst = float(max(counts.values(), default=0)) + 1.0
                for t, labels in enumerate(label_dicts_fn()):
                    value = labels.get(key)
                    raw[s, t] -= (
                        float(counts.get(value, 0))
                        if value is not None
                        else worst
                    )
        pieces.append((2.0, raw[live]))

    shapes = snap.soft_anti_shapes
    live = (
        snap.soft_anti_id[row_idx]
        if snap.soft_anti_id is not None and shapes is not None
        else None
    )
    if live is not None and (live != 0).any() and census is not None:
        raw = np.zeros((len(shapes), n_real), np.float32)
        for s in np.unique(live):
            shape = shapes[s]
            if not shape:
                continue
            namespace, entries = shape
            for sign, weight, key, sel in entries:
                counts = census.domain_counts(namespace, sel, key)
                for t, labels in enumerate(label_dicts_fn()):
                    value = labels.get(key)
                    if value is not None:
                        raw[s, t] += (
                            sign * weight * float(counts.get(value, 0))
                        )
        if raw.any():
            pieces.append((1.0, raw[live]))

    if not pieces:
        return None
    acc = np.zeros((hi, n_real), np.float32)
    for weight, raw in pieces:
        lo = raw.min(axis=1, keepdims=True)
        rng = raw.max(axis=1, keepdims=True) - lo
        safe = np.where(rng > 0, rng, 1.0)
        acc += weight * np.where(rng > 0, (raw - lo) / safe * 100.0, 0.0)
    total = np.zeros((n_pods, n_groups), np.float32)
    total[:hi, :n_real] = acc
    return total


def _encode_from_cache(snap, profiles, with_rows: bool = False, census=None):  # lint: allow-complexity — THE single encoder; splitting would smear the output-equality invariant
    """Snapshot (store/columnar.PendingSnapshot) -> solver inputs, with
    rows DEDUPLICATED into distinct pod shapes + multiplicities
    (pod_weight) — see _dedup_rows. Every solve path (feed, pod_cache,
    oracle store.list) flows through here, so outputs stay identical
    across paths by construction.

    All per-pod work here is bulk numpy (column gathers, row gathers by
    toleration-shape id); the only Python loops left are over universes —
    resources, group profiles, taints, distinct toleration shapes — whose
    cardinalities are fleet-scale constants, not pod counts.
    """
    # group label dicts: built at most once, shared by the spread
    # expansion and the affinity/preferred evaluation blocks below
    label_dicts_box: list = []

    def group_label_dicts():
        if not label_dicts_box:
            label_dicts_box.append(
                [dict(labels) for _, labels, _ in profiles]
            )
        return label_dicts_box[0]

    row_idx, row_weight = _dedup_rows(snap)
    # hard topology spread: constrained rows split into balanced
    # per-domain sub-rows (same source row gathered more than once, each
    # chunk masked to its domain's groups) — the device program is
    # unchanged, spread rides the existing forbidden-mask operand
    row_idx, row_weight, spread_forbidden = _expand_spread_rows(
        snap, profiles, row_idx, row_weight, group_label_dicts,
        census=census,
    )
    # required self pod-(anti-)affinity: hostname rows flag the
    # pod_exclusive operand, domain keys cap one replica per domain
    # (further sub-row expansion; the spread mask rides through)
    row_idx, row_weight, spread_forbidden, row_exclusive = (
        _expand_anti_rows(
            snap, profiles, row_idx, row_weight, spread_forbidden,
            group_label_dicts, census=census,
        )
    )
    hi = len(row_idx)

    extended = {
        r for r in snap.resources
        if r not in RESOURCES_BASE and r != RESOURCE_PODS
    }
    for alloc, _, _ in profiles:
        extended |= {
            r for r in alloc
            if r not in RESOURCES_BASE and r != RESOURCE_PODS
        }
    resources = [*RESOURCES_BASE, *sorted(extended), RESOURCE_PODS]
    n_resources = _pad(len(resources), RESOURCE_PAD)
    resource_index = {r: idx for idx, r in enumerate(resources)}
    pod_slot = resources.index(RESOURCE_PODS)

    taint_universe: Dict[tuple, int] = {}
    for _, _, taints in profiles:
        for taint in sorted(taints):
            if taint not in taint_universe:
                taint_universe[taint] = len(taint_universe)
    label_universe = {item: l for l, item in enumerate(snap.labels)}

    n_pods = _pad(hi, POD_PAD)
    n_groups = _pad(len(profiles), GROUP_PAD)
    n_taints = _pad(len(taint_universe), TAINT_PAD)
    n_labels = _pad(len(label_universe), LABEL_PAD)

    pod_requests = np.zeros((n_pods, n_resources), np.float32)
    pod_valid = np.zeros(n_pods, bool)
    pod_required = np.zeros((n_pods, n_labels), bool)
    pod_intolerant = np.zeros((n_pods, n_taints), bool)
    pod_weight = np.zeros(n_pods, np.int32)  # padding rows weigh nothing
    if hi:
        valid = snap.valid[row_idx]
        cols = np.array(
            [resource_index[r] for r in snap.resources], np.intp
        )
        pod_requests[:hi, cols] = snap.requests[row_idx]
        pod_requests[:hi, pod_slot] = valid.astype(np.float32)
        pod_valid[:hi] = valid
        pod_weight[:hi] = row_weight
        if snap.labels:
            pod_required[:hi, : len(snap.labels)] = snap.required[row_idx]
        if snap.shape_tolerations:
            taint_objects = {
                k: Taint(key=taint[0], value=taint[1], effect=taint[2])
                for taint, k in taint_universe.items()
            }
            rows = np.zeros((len(snap.shape_tolerations), n_taints), bool)
            for s, tolerations in enumerate(snap.shape_tolerations):
                for k, taint in taint_objects.items():
                    rows[s, k] = not any(
                        tol.tolerates(taint) for tol in tolerations
                    )
            pod_intolerant[:hi] = rows[snap.shape_id[row_idx]]

    group_allocatable, group_taints, group_labels = _group_arrays(
        profiles, resources, taint_universe, label_universe,
        n_groups, n_resources, n_taints, n_labels,
    )

    # Required node affinity: matchExpression semantics (In/NotIn/Exists/
    # DoesNotExist/Gt/Lt, OR'd terms) don't factor into the conjunctive
    # required-label bitset, so each DISTINCT affinity shape is evaluated
    # host-side against each group's label assignment (the profile label
    # set — the INTERSECTION of node labels, i.e. the same conservative
    # single-node shape the min-allocatable uses; heterogeneous groups may
    # over-admit negative operators, the caveat _group_profile documents
    # for resources) and the S_a x T verdicts gather to rows. None when no
    # pod constrains affinity — the common fleet pays nothing.
    pod_group_forbidden = None
    shapes = snap.affinity_shapes
    live_affinity_ids = (
        snap.affinity_id[row_idx]
        if hi and snap.affinity_id is not None and shapes is not None
        else None
    )
    # gate on LIVE rows (shape id 0 = unconstrained): the shape registry
    # retains entries until compaction, and a long-gone affinity Job must
    # not keep the whole fleet on the masked (extra-operand) kernel path
    if live_affinity_ids is not None and (live_affinity_ids != 0).any():
        allowed = np.ones((len(shapes), n_groups), bool)
        for s in np.unique(live_affinity_ids):  # only shapes in live use
            shape = shapes[s]
            if not shape:
                continue
            for t, labels in enumerate(group_label_dicts()):
                allowed[s, t] = matches_affinity_shape(labels, shape)
        pod_group_forbidden = np.zeros((n_pods, n_groups), bool)
        pod_group_forbidden[:hi] = ~allowed[live_affinity_ids]

    # Topology spread + self pod-(anti-)affinity: OR the per-sub-row
    # masks into the same forbidden operand the affinity path uses
    # (padding groups are all-zero allocatable and already infeasible,
    # so mask width T_real suffices)
    if spread_forbidden is not None:
        if pod_group_forbidden is None:
            pod_group_forbidden = np.zeros((n_pods, n_groups), bool)
        pod_group_forbidden[:hi, : len(profiles)] |= spread_forbidden

    # hostname self-anti-affinity rows take a whole node each — absent
    # unless some live pod actually carries the constraint
    pod_exclusive = None
    if row_exclusive is not None and row_exclusive.any():
        pod_exclusive = np.zeros(n_pods, bool)
        pod_exclusive[:hi] = row_exclusive

    # Scoring operand (ops/binpack.py pod_group_score): the kube-
    # scheduler's scoring plugins modeled over groups — preferred node
    # affinity, ScheduleAnyway spread, preferred self pod-(anti-)
    # affinity — absent unless some live pod actually prefers
    pod_group_score = _score_rows(
        snap, profiles, row_idx, group_label_dicts, census,
        n_pods, n_groups,
    )

    inputs = B.BinPackInputs(
        pod_requests=pod_requests,
        pod_valid=pod_valid,
        pod_intolerant=pod_intolerant,
        pod_required=pod_required,
        group_allocatable=group_allocatable,
        group_taints=group_taints,
        group_labels=group_labels,
        pod_weight=pod_weight,
        pod_group_forbidden=pod_group_forbidden,
        pod_group_score=pod_group_score,
        pod_exclusive=pod_exclusive,
    )
    if with_rows:
        # the simulation API maps per-row solver outputs back to pods:
        # row i of `inputs` gathers snapshot row row_idx[i] (an arena
        # slot) with multiplicity row_weight[i]
        return inputs, row_idx, row_weight
    return inputs


def _publish_census(registry: GaugeRegistry, census) -> None:
    """karpenter_runtime_census_refresh_total: occupancy-census epoch
    recomputes (bound-pod / node churn between constrained solves).
    karpenter_runtime_census_view_evictions_total: materialized-view
    LRU evictions — a rising rate means more live (namespace, selector)
    pairs than ScheduledOccupancy.VIEW_CAP, and each re-build is a
    group scan (the silent-thrash signal, r3 code review).
    Delta-published so the persistent feed census and the per-solve
    oracle census report the same way."""
    if census is None:
        return
    delta = census.refreshes - census.published
    if delta:
        registry.register(
            "runtime", "census_refresh_total", kind="counter"
        ).inc("-", "-", delta)
        census.published = census.refreshes
    evictions = getattr(census._occupancy, "view_evictions", 0)
    delta = evictions - census.evictions_published
    if delta:
        registry.register(
            "runtime", "census_view_evictions_total", kind="counter"
        ).inc("-", "-", delta)
        census.evictions_published = evictions


def _count_cache(registry: GaugeRegistry, outcome: str) -> None:
    """karpenter_runtime_encode_cache_total{name=hit|miss}: how often the
    tick-collapse encode memo spares a re-encode + device re-upload."""
    registry.register("runtime", "encode_cache_total", kind="counter").inc(
        outcome, "-"
    )


_pack_outputs_jit = None


def _pack_outputs(assigned_count, nodes_needed, lp_bound, unschedulable):
    """Jitted on first use: concat the per-group outputs + the scalar into
    one vector so the host fetch is a single device round-trip."""
    global _pack_outputs_jit
    if _pack_outputs_jit is None:
        import jax
        import jax.numpy as jnp

        _pack_outputs_jit = jax.jit(
            lambda a, n, l, u: jnp.concatenate(
                [a, n, l, u.astype(a.dtype)[None]]
            )
        )
    return _pack_outputs_jit(
        assigned_count, nodes_needed, lp_bound, unschedulable
    )


def _dispatch_and_record(
    inputs, targets, registry, solver, errors=None, cached_outputs=None
):
    """Solve + one host fetch + status/gauge writes. Returns the host
    output tuple (assigned_count, nodes_needed, lp_bound, unschedulable)
    so callers can memoize it; `cached_outputs` short-circuits the solve
    for identical inputs (the memo-hit path)."""
    if cached_outputs is not None:
        assigned_count, nodes_needed, lp_bound, unschedulable = cached_outputs
    else:
        if solver is None:
            solver = B.solve
        # numpy arrays go straight through: the in-process jitted solve
        # device-puts them itself, and a remote solver serializes host
        # bytes — wrapping in jnp here would force a device round-trip
        # (and JAX init) in the control-plane process the sidecar split
        # exists to relieve
        with solver_trace("pendingcapacity.solve"):
            out = solver(inputs)

        # ONE device->host fetch for all four outputs: device_get still
        # issues a round-trip PER leaf (measured ~35 ms each through the
        # network tunnel), so the four outputs are first concatenated ON
        # DEVICE into a single i32[3T+1] vector — one transfer total.
        # Plain numpy outputs (sidecar path) pass through untouched.
        import jax

        if isinstance(out.assigned_count, jax.Array):
            packed = np.asarray(
                _pack_outputs(
                    out.assigned_count, out.nodes_needed, out.lp_bound,
                    out.unschedulable,
                )
            )
            n = out.assigned_count.shape[0]
            assigned_count = packed[:n]
            nodes_needed = packed[n : 2 * n]
            lp_bound = packed[2 * n : 3 * n]
            unschedulable = int(packed[3 * n])
        else:
            assigned_count, nodes_needed, lp_bound = (
                np.asarray(out.assigned_count),
                np.asarray(out.nodes_needed),
                np.asarray(out.lp_bound),
            )
            unschedulable = int(out.unschedulable)

    register_gauges(registry)
    gauge = lambda g: registry.gauge(SUBSYSTEM, g)
    for t, (namespace, name, mp, *_rest) in enumerate(targets):
        if errors and (namespace, name) in errors:
            # poisoned row: keep its last-good status/gauges rather than
            # publishing the placeholder all-infeasible solve
            continue
        if mp is not None:  # due: status lands on the persisted instance
            mp.status.pending_capacity = PendingCapacityStatus(
                pending_pods=int(assigned_count[t]),
                additional_nodes_needed=int(nodes_needed[t]),
                lp_lower_bound=int(lp_bound[t]),
                unschedulable_pods=unschedulable,
            )
        gauge(PENDING_PODS).set(name, namespace, float(assigned_count[t]))
        gauge(ADDITIONAL_NODES_NEEDED).set(name, namespace, float(nodes_needed[t]))
        gauge(LP_LOWER_BOUND).set(name, namespace, float(lp_bound[t]))
        gauge(UNSCHEDULABLE_PODS).set(name, namespace, float(unschedulable))
    return (assigned_count, nodes_needed, lp_bound, unschedulable)


class PendingCapacityProducer:
    """Single-producer path; the controller batches when it can."""

    def __init__(
        self,
        mp,
        store,
        registry: Optional[GaugeRegistry] = None,
        solver=None,
        feed=None,
        template_resolver=None,
    ):
        self.mp = mp
        self.store = store
        self.registry = registry if registry is not None else default_registry()
        self.solver = solver
        self.feed = feed
        self.template_resolver = template_resolver
        register_gauges(self.registry)

    def reconcile(self) -> None:
        outcomes = solve_pending(
            self.store, [self.mp], self.registry, solver=self.solver,
            feed=self.feed, template_resolver=self.template_resolver,
        )
        error = outcomes.get(
            (self.mp.metadata.namespace, self.mp.metadata.name)
        )
        if error is not None:
            raise error
