"""ReservedCapacity producer: committed vs allocatable resources per node group.

reference: pkg/metrics/producers/reservedcapacity/{producer,reservations,gauges}.go —
lists nodes by selector, filters ready+schedulable, sums container requests of
pods on each node (via the spec.nodeName index) against allocatable, and emits
9 gauges (cpu/memory/pods × reserved/capacity/utilization) plus human-readable
status strings like "15.54%, 7600m/48900m".

Status strings use exact Quantity arithmetic (host) for bit-identical output;
gauges carry the float values the autoscaler consumes. At fleet scale the
batched aggregation path (ops) subsumes this per-producer loop.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

from karpenter_tpu.api.core import (
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
    is_ready_and_schedulable,
)
from karpenter_tpu.metrics.registry import GaugeRegistry, default_registry
from karpenter_tpu.utils.quantity import Quantity, parse_quantity

SUBSYSTEM = "reserved_capacity"
RESERVED = "reserved"
CAPACITY = "capacity"
UTILIZATION = "utilization"

RESOURCES = (RESOURCE_PODS, RESOURCE_CPU, RESOURCE_MEMORY)
METRIC_TYPES = (RESERVED, CAPACITY, UTILIZATION)

_ONE = parse_quantity("1")


class Reservations:
    """Accumulator (reference: reservations.go:24-56)."""

    def __init__(self):
        self.reserved: Dict[str, Quantity] = {r: Quantity() for r in RESOURCES}
        self.capacity: Dict[str, Quantity] = {r: Quantity() for r in RESOURCES}

    def add(self, node, pods) -> None:
        for pod in pods:
            self.reserved[RESOURCE_PODS] = self.reserved[RESOURCE_PODS].add(_ONE)
            for container in pod.spec.containers:
                for resource in (RESOURCE_CPU, RESOURCE_MEMORY):
                    q = container.requests.get(resource)
                    if q is not None:
                        self.reserved[resource] = self.reserved[resource].add(q)
        for resource in RESOURCES:
            q = node.status.allocatable.get(resource)
            if q is not None:
                self.capacity[resource] = self.capacity[resource].add(q)


def register_gauges(registry: GaugeRegistry) -> None:
    """reference: gauges.go:34-44"""
    for resource in RESOURCES:
        for metric_type in METRIC_TYPES:
            registry.register(SUBSYSTEM, f"{resource}_{metric_type}")


class ReservedCapacityProducer:
    def __init__(
        self,
        mp,
        store,
        registry: Optional[GaugeRegistry] = None,
        reservations=None,
        node_mirror=None,
    ):
        self.mp = mp
        self.store = store
        self.registry = registry if registry is not None else default_registry()
        # incremental feed (store/columnar.ReservationsCache + NodeMirror):
        # O(nodes-in-group) per tick instead of O(nodes + pods); None runs
        # the oracle list path the property tests compare against
        self.reservations = reservations
        self.node_mirror = node_mirror
        register_gauges(self.registry)

    def reconcile(self) -> None:
        selector = self.mp.spec.reserved_capacity.node_selector
        if self.node_mirror is not None:
            nodes = self.node_mirror.nodes(selector)
        else:
            nodes = self.store.list("Node", label_selector=selector)
        # Only ready+schedulable nodes count, to avoid diluting the
        # denominator and triggering premature scale-down
        # (reference: producer.go:46-48).
        ready = [n for n in nodes if is_ready_and_schedulable(n)]
        reservations = Reservations()
        if self.reservations is not None:
            totals = self.reservations.reserved_on(
                node.metadata.name for node in ready
            )
            for resource in RESOURCES:
                cached = totals.get(resource)
                if cached is not None:
                    reservations.reserved[resource] = cached
            for node in ready:
                reservations.add(node, ())  # capacity side only
        else:
            for node in ready:
                pods = self.store.pods_on_node(node.metadata.name)
                reservations.add(node, pods)
        self._record(reservations)

    def _record(self, reservations: Reservations) -> None:
        """reference: producer.go:63-86

        Display canonicalization: Quantity.add adopts the first non-zero
        operand's format, so the reserved sum's format depends on pod
        event/iteration order — which differs between the incremental
        ReservationsCache path and the oracle list path. The capacity sum
        is order-stable (same ready-node list either way), so reserved is
        re-rendered in capacity's format: both paths emit bit-identical
        status strings, this module's stated goal.
        """
        for resource in RESOURCES:
            reserved_q = reservations.reserved[resource]
            capacity_q = reservations.capacity[resource]
            if reserved_q.value != 0 and capacity_q.value != 0:
                reserved_q = Quantity(reserved_q.value, capacity_q.format)
            reserved = reserved_q.to_float()
            capacity = capacity_q.to_float()
            utilization = reserved / capacity if capacity != 0 else math.nan

            name, namespace = self.mp.metadata.name, self.mp.metadata.namespace
            gauge = lambda t: self.registry.gauge(SUBSYSTEM, f"{resource}_{t}")
            gauge(UTILIZATION).set(name, namespace, utilization)
            gauge(RESERVED).set(name, namespace, reserved)
            gauge(CAPACITY).set(name, namespace, capacity)

            percent = utilization * 100
            rendered = "NaN" if math.isnan(percent) else f"{percent:.2f}"
            self.mp.status.reserved_capacity[resource] = (
                f"{rendered}%, {reserved_q}/{capacity_q}"
            )
