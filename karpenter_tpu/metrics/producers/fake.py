"""Fake producer returning a configured error.

reference: pkg/metrics/producers/fake/types.go:23-27.
"""

from __future__ import annotations

from typing import Optional

NOT_IMPLEMENTED_ERROR = RuntimeError("provider is not implemented")


class FakeProducer:
    def __init__(self, want_err: Optional[Exception] = None):
        self.want_err = want_err

    def reconcile(self) -> None:
        if self.want_err is not None:
            raise self.want_err
