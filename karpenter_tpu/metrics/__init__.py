"""Metrics layer: gauge registry, producers, clients.

reference: pkg/metrics/ (gauge.go, types.go, producers/, clients/).
"""

from karpenter_tpu.metrics.registry import (
    GaugeRegistry,
    default_registry,
    reset_default_registry,
)
from karpenter_tpu.metrics.types import Metric, MetricsClient, Producer

__all__ = [
    "GaugeRegistry",
    "default_registry",
    "reset_default_registry",
    "Metric",
    "MetricsClient",
    "Producer",
]
