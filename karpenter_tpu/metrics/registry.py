"""Metric registry with Prometheus naming and text exposition.

reference: pkg/metrics/gauge.go:22-50 — gauges named
karpenter_<subsystem>_<name>, labeled {name, namespace}, registered into the
controller-runtime /metrics endpoint and scraped by Prometheus. Here the
registry doubles as the metrics STORE: the in-process metrics client reads
gauge values directly (no scrape hop), while the /metrics text exposition
(karpenter_tpu.observability) keeps drop-in Prometheus compatibility for
external scrapers.

Beyond the reference's gauges the registry carries counters and NATIVE
HISTOGRAMS (`kind="histogram"`, per-vec bucket ladders): cumulative
`_bucket{le=...}` series, `_sum`/`_count`, and `+Inf` always present —
the shape promtool expects, pinned by the exposition-conformance tests.
The solver stage latencies, coalesce batch sizes, and the end-to-end
`karpenter_reconcile_e2e_seconds` lead time (docs/observability.md)
export through it as real histograms, so Prometheus
`histogram_quantile()` works instead of the pre-histogram p50/p99 gauge
snapshots.
"""

from __future__ import annotations

import bisect
import math
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

METRIC_NAMESPACE = "karpenter"
LABEL_NAME = "name"
LABEL_NAMESPACE = "namespace"

# default histogram ladder (seconds): sub-ms device dispatches through
# multi-second cloud actuations
DEFAULT_HISTOGRAM_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


def escape_label_value(value: str) -> str:
    """Prometheus text-format label escaping: backslash, double quote,
    and newline must be escaped inside label values — an unescaped
    quote in an object name would corrupt every series after it."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _format_value(value: float) -> str:
    if math.isnan(value):
        return "NaN"
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    return repr(value)


def _format_le(bound: float) -> str:
    """Bucket bounds render promtool-style: '+Inf', integers bare,
    floats shortest ('0.005', not '0.005000000000000001')."""
    if math.isinf(bound):
        return "+Inf"
    return f"{bound:g}"


def _render_labels(labels: Dict[str, str]) -> str:
    return ",".join(
        f'{k}="{escape_label_value(v)}"'
        for k, v in sorted(labels.items())
    )


@dataclass
class GaugeSample:
    labels: Dict[str, str]
    value: float


class GaugeVec:
    """A named gauge (or counter: kind="counter") parameterized by
    {name, namespace} labels."""

    def __init__(self, full_name: str, help_text: str, kind: str = "gauge"):
        self.full_name = full_name
        self.help = help_text
        self.kind = kind  # Prometheus TYPE line: "gauge" or "counter"
        self._samples: Dict[Tuple[str, str], float] = {}
        self._lock = threading.Lock()

    def set(self, name: str, namespace: str, value: float) -> None:
        with self._lock:
            self._samples[(name, namespace)] = float(value)

    def inc(self, name: str, namespace: str, delta: float = 1.0) -> None:
        """Atomic increment under the vec lock (counters must never lose
        increments to concurrent read-modify-write)."""
        with self._lock:
            key = (name, namespace)
            self._samples[key] = self._samples.get(key, 0.0) + delta

    def get(self, name: str, namespace: str) -> Optional[float]:
        with self._lock:
            return self._samples.get((name, namespace))

    def remove(self, name: str, namespace: str) -> None:
        with self._lock:
            self._samples.pop((name, namespace), None)

    def samples(self):
        with self._lock:
            return [
                GaugeSample({LABEL_NAME: n, LABEL_NAMESPACE: ns}, v)
                for (n, ns), v in sorted(self._samples.items())
            ]

    def expose_lines(self) -> List[str]:
        lines = [
            f"# HELP {self.full_name} {self.help}",
            f"# TYPE {self.full_name} {self.kind}",
        ]
        for sample in self.samples():
            lines.append(
                f"{self.full_name}{{{_render_labels(sample.labels)}}} "
                f"{_format_value(sample.value)}"
            )
        return lines


class HistogramVec:
    """A native Prometheus histogram parameterized by {name, namespace}
    labels: per-series bucket counts + sum, exposed as cumulative
    `_bucket{le=...}` / `_sum` / `_count` with `+Inf` always present.

    Buckets are upper bounds, strictly increasing; `+Inf` is implicit
    (and stripped if passed). observe() is O(log buckets) under the vec
    lock — cumulation happens at exposition, not on the hot path."""

    def __init__(self, full_name: str, help_text: str, buckets=None):
        self.full_name = full_name
        self.help = help_text
        self.kind = "histogram"
        bounds = sorted(
            float(b) for b in (buckets or DEFAULT_HISTOGRAM_BUCKETS)
            if not math.isinf(float(b))
        )
        if not bounds:
            raise ValueError(f"{full_name}: histogram needs finite buckets")
        if len(set(bounds)) != len(bounds):
            raise ValueError(f"{full_name}: duplicate histogram buckets")
        self.buckets: Tuple[float, ...] = tuple(bounds)
        # per series: [per-bucket counts..., +Inf overflow count], sum
        self._counts: Dict[Tuple[str, str], List[int]] = {}
        self._sums: Dict[Tuple[str, str], float] = {}
        self._lock = threading.Lock()

    def observe(self, name: str, namespace: str, value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            key = (name, namespace)
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.buckets) + 1)
                self._sums[key] = 0.0
            counts[idx] += 1
            self._sums[key] += value

    def get(self, name: str, namespace: str) -> Optional[float]:
        """Vec-protocol read (the in-process metrics client resolves
        metric names through the registry): a histogram reads as its
        observation COUNT — the only scalar that is well-defined."""
        with self._lock:
            counts = self._counts.get((name, namespace))
            return None if counts is None else float(sum(counts))

    def count(self, name: str, namespace: str) -> int:
        with self._lock:
            counts = self._counts.get((name, namespace))
            return 0 if counts is None else sum(counts)

    def sum(self, name: str, namespace: str) -> float:
        with self._lock:
            return self._sums.get((name, namespace), 0.0)

    def percentile(self, name: str, namespace: str, q: float):
        """Estimate the q-th percentile (q in [0, 100]) for one series
        from the bucket counts — the same linear-within-bucket
        interpolation Prometheus's histogram_quantile() applies, so the
        simulator reports and bench output quote the number an operator
        would read off a dashboard. None for an empty series; samples
        beyond the last finite bucket clamp to that bound (+Inf has no
        upper edge to interpolate toward)."""
        with self._lock:
            counts = self._counts.get((name, namespace))
            if counts is None:
                return None
            counts = list(counts)
        total = sum(counts)
        if total == 0:
            return None
        rank = (q / 100.0) * total
        cumulative = 0.0
        lower = 0.0
        for idx, count in enumerate(counts):
            upper = (
                self.buckets[idx]
                if idx < len(self.buckets)
                else self.buckets[-1]  # +Inf bucket clamps to last bound
            )
            if cumulative + count >= rank:
                if idx >= len(self.buckets) or count == 0:
                    return float(upper)
                fraction = (rank - cumulative) / count
                return float(lower + (upper - lower) * fraction)
            cumulative += count
            lower = upper
        return float(self.buckets[-1])

    def le_totals(self, bound: float) -> Tuple[int, int]:
        """(samples <= bound, total samples) summed across ALL series —
        the self-SLO monitor's good/total pair (observability/selfslo).
        `bound` should sit on a bucket boundary for exactness; an
        off-ladder bound conservatively counts only the buckets wholly
        at or below it (samples between the ladder rung and the bound
        count as BAD, never silently as good)."""
        idx = bisect.bisect_right(self.buckets, float(bound))
        good = total = 0
        with self._lock:
            for counts in self._counts.values():
                good += sum(counts[:idx])
                total += sum(counts)
        return good, total

    def remove(self, name: str, namespace: str) -> None:
        with self._lock:
            self._counts.pop((name, namespace), None)
            self._sums.pop((name, namespace), None)

    def series(self) -> List[Tuple[str, str]]:
        with self._lock:
            return sorted(self._counts)

    def samples(self):
        """Vec-protocol view (the in-process metrics client iterates
        samples()): one sample per series, valued at its observation
        count — same scalar get() reports."""
        with self._lock:
            return [
                GaugeSample(
                    {LABEL_NAME: n, LABEL_NAMESPACE: ns},
                    float(sum(counts)),
                )
                for (n, ns), counts in sorted(self._counts.items())
            ]

    def expose_lines(self) -> List[str]:
        lines = [
            f"# HELP {self.full_name} {self.help}",
            f"# TYPE {self.full_name} histogram",
        ]
        with self._lock:
            snapshot = [
                (key, list(counts), self._sums[key])
                for key, counts in sorted(self._counts.items())
            ]
        bounds = [*self.buckets, math.inf]
        for (name, namespace), counts, total in snapshot:
            base = {LABEL_NAME: name, LABEL_NAMESPACE: namespace}
            cumulative = 0
            for bound, count in zip(bounds, counts):
                cumulative += count
                labels = _render_labels({**base, "le": _format_le(bound)})
                lines.append(
                    f"{self.full_name}_bucket{{{labels}}} {cumulative}"
                )
            labels = _render_labels(base)
            lines.append(
                f"{self.full_name}_sum{{{labels}}} "
                f"{_format_value(total)}"
            )
            lines.append(f"{self.full_name}_count{{{labels}}} {cumulative}")
        return lines


class GaugeRegistry:
    def __init__(self):
        self._gauges: Dict[str, Dict[str, object]] = {}
        self._lock = threading.Lock()

    def register(
        self, subsystem: str, name: str, kind: str = "gauge", buckets=None
    ):
        """reference: gauge.go:35-50 (RegisterNewGauge); kind="histogram"
        (optionally with a `buckets` ladder) registers a HistogramVec."""
        full = f"{METRIC_NAMESPACE}_{subsystem}_{name}"
        with self._lock:
            sub = self._gauges.setdefault(subsystem, {})
            vec = sub.get(name)
            if vec is None:
                if kind == "histogram":
                    vec = sub[name] = HistogramVec(
                        full,
                        "Metric computed by a karpenter metrics producer "
                        "corresponding to name and namespace labels",
                        buckets=buckets,
                    )
                else:
                    vec = sub[name] = GaugeVec(
                        full,
                        "Metric computed by a karpenter metrics producer "
                        "corresponding to name and namespace labels",
                        kind=kind,
                    )
            elif kind == "histogram" and vec.kind == "histogram":
                # the bucket ladder is decided at first registration
                # like the TYPE line: a second caller silently landing
                # observations in a ladder it never chose would skew
                # histogram_quantile() with no error anywhere
                if buckets is not None and tuple(
                    sorted(float(b) for b in buckets
                           if not math.isinf(float(b)))
                ) != vec.buckets:
                    raise ValueError(
                        f"{full} already registered with buckets "
                        f"{vec.buckets}; conflicting ladder "
                        f"{tuple(buckets)}"
                    )
            elif vec.kind != kind:
                # the TYPE line is decided at first registration; a silent
                # mismatch would expose a counter as a gauge (or vice
                # versa) and corrupt rate()/increase() semantics
                raise ValueError(
                    f"{full} already registered as {vec.kind}, not {kind}"
                )
            return vec

    def gauge(self, subsystem: str, name: str):
        with self._lock:
            return self._gauges[subsystem][name]

    def remove_series(self, subsystem: str, name: str, namespace: str) -> None:
        """Drop one {name, namespace} series from EVERY vec registered
        under `subsystem` — the per-object retirement hook deletion
        paths call so a deleted object's series cannot freeze on
        /metrics. Covers vecs added to the subsystem later without the
        caller having to enumerate metric names (the reserved_capacity
        family alone is resources x metric-types wide)."""
        with self._lock:
            vecs = list(self._gauges.get(subsystem, {}).values())
        for vec in vecs:
            vec.remove(name, namespace)

    def lookup_by_full_name(self, full_name: str):
        with self._lock:
            for sub in self._gauges.values():
                for vec in sub.values():
                    if vec.full_name == full_name:
                        return vec
        return None

    def expose_text(self) -> str:
        """Prometheus text exposition format of all samples."""
        with self._lock:
            vecs = [v for sub in self._gauges.values() for v in sub.values()]
        lines: List[str] = []
        for vec in sorted(vecs, key=lambda v: v.full_name):
            lines.extend(vec.expose_lines())
        return "\n".join(lines) + "\n"


_default = GaugeRegistry()


def default_registry() -> GaugeRegistry:
    return _default


def reset_default_registry() -> GaugeRegistry:
    """Swap in a fresh default registry (test isolation)."""
    global _default
    _default = GaugeRegistry()
    return _default
