"""Gauge registry with Prometheus naming and text exposition.

reference: pkg/metrics/gauge.go:22-50 — gauges named
karpenter_<subsystem>_<name>, labeled {name, namespace}, registered into the
controller-runtime /metrics endpoint and scraped by Prometheus. Here the
registry doubles as the metrics STORE: the in-process metrics client reads
gauge values directly (no scrape hop), while the /metrics text exposition
(karpenter_tpu.observability) keeps drop-in Prometheus compatibility for
external scrapers.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

METRIC_NAMESPACE = "karpenter"
LABEL_NAME = "name"
LABEL_NAMESPACE = "namespace"


@dataclass
class GaugeSample:
    labels: Dict[str, str]
    value: float


class GaugeVec:
    """A named gauge (or counter: kind="counter") parameterized by
    {name, namespace} labels."""

    def __init__(self, full_name: str, help_text: str, kind: str = "gauge"):
        self.full_name = full_name
        self.help = help_text
        self.kind = kind  # Prometheus TYPE line: "gauge" or "counter"
        self._samples: Dict[Tuple[str, str], float] = {}
        self._lock = threading.Lock()

    def set(self, name: str, namespace: str, value: float) -> None:
        with self._lock:
            self._samples[(name, namespace)] = float(value)

    def inc(self, name: str, namespace: str, delta: float = 1.0) -> None:
        """Atomic increment under the vec lock (counters must never lose
        increments to concurrent read-modify-write)."""
        with self._lock:
            key = (name, namespace)
            self._samples[key] = self._samples.get(key, 0.0) + delta

    def get(self, name: str, namespace: str) -> Optional[float]:
        with self._lock:
            return self._samples.get((name, namespace))

    def remove(self, name: str, namespace: str) -> None:
        with self._lock:
            self._samples.pop((name, namespace), None)

    def samples(self):
        with self._lock:
            return [
                GaugeSample({LABEL_NAME: n, LABEL_NAMESPACE: ns}, v)
                for (n, ns), v in sorted(self._samples.items())
            ]


class GaugeRegistry:
    def __init__(self):
        self._gauges: Dict[str, Dict[str, GaugeVec]] = {}
        self._lock = threading.Lock()

    def register(
        self, subsystem: str, name: str, kind: str = "gauge"
    ) -> GaugeVec:
        """reference: gauge.go:35-50 (RegisterNewGauge)."""
        full = f"{METRIC_NAMESPACE}_{subsystem}_{name}"
        with self._lock:
            sub = self._gauges.setdefault(subsystem, {})
            vec = sub.get(name)
            if vec is None:
                vec = sub[name] = GaugeVec(
                    full,
                    "Metric computed by a karpenter metrics producer "
                    "corresponding to name and namespace labels",
                    kind=kind,
                )
            elif vec.kind != kind:
                # the TYPE line is decided at first registration; a silent
                # mismatch would expose a counter as a gauge (or vice
                # versa) and corrupt rate()/increase() semantics
                raise ValueError(
                    f"{full} already registered as {vec.kind}, not {kind}"
                )
            return vec

    def gauge(self, subsystem: str, name: str) -> GaugeVec:
        with self._lock:
            return self._gauges[subsystem][name]

    def lookup_by_full_name(self, full_name: str) -> Optional[GaugeVec]:
        with self._lock:
            for sub in self._gauges.values():
                for vec in sub.values():
                    if vec.full_name == full_name:
                        return vec
        return None

    def expose_text(self) -> str:
        """Prometheus text exposition format of all samples."""
        lines = []
        with self._lock:
            vecs = [v for sub in self._gauges.values() for v in sub.values()]
        for vec in sorted(vecs, key=lambda v: v.full_name):
            lines.append(f"# HELP {vec.full_name} {vec.help}")
            lines.append(f"# TYPE {vec.full_name} {vec.kind}")
            for sample in vec.samples():
                labels = ",".join(
                    f'{k}="{v}"' for k, v in sorted(sample.labels.items())
                )
                value = sample.value
                if math.isnan(value):
                    rendered = "NaN"
                elif math.isinf(value):
                    rendered = "+Inf" if value > 0 else "-Inf"
                else:
                    rendered = repr(value)
                lines.append(f"{vec.full_name}{{{labels}}} {rendered}")
        return "\n".join(lines) + "\n"


_default = GaugeRegistry()


def default_registry() -> GaugeRegistry:
    return _default


def reset_default_registry() -> GaugeRegistry:
    """Swap in a fresh default registry (test isolation)."""
    global _default
    _default = GaugeRegistry()
    return _default
