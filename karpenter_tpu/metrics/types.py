"""Metric value + producer/client protocols (reference: pkg/metrics/types.go:28-38)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Protocol


@dataclass
class Metric:
    """Current value of a metric."""

    name: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    value: float = 0.0


class Producer(Protocol):
    def reconcile(self) -> None:
        """Compute and publish the producer's current metric values."""


class MetricsClient(Protocol):
    def get_current_value(self, metric_spec) -> Metric:
        """Return the current value for the specified metric source."""
