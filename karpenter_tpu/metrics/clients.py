"""Metrics clients: how the autoscaler reads metric values.

reference: pkg/metrics/clients/client.go:26-53 and prometheus.go:35-55 — a
factory dispatching on the metric's source type, and a Prometheus client that
issues an instant query and requires an instant vector of length 1.

The TPU build ships two client backends:
- RegistryMetricsClient: reads the in-process gauge registry directly,
  evaluating the same `metric_name{label="value",...}` instant-selector
  queries the reference writes against Prometheus (docs/examples/*.yaml).
  This removes the produce→scrape→query latency hops (≈10s) for in-cluster
  signals while keeping query strings source-compatible.
- PrometheusMetricsClient: a real HTTP instant query against a Prometheus
  server for drop-in parity when signals live outside the process.
"""

from __future__ import annotations

import json
import re
import urllib.parse
import urllib.request
from typing import Dict, Optional, Tuple

from karpenter_tpu.controllers.errors import RetryableError
from karpenter_tpu.faults import inject
from karpenter_tpu.metrics.registry import GaugeRegistry, default_registry
from karpenter_tpu.metrics.types import Metric
from karpenter_tpu.observability import default_tracer
from karpenter_tpu.utils.log import invariant_violated

_SELECTOR_RE = re.compile(
    r"^\s*(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)\s*"
    r"(?:\{(?P<labels>[^}]*)\})?\s*$"
)
_LABEL_RE = re.compile(
    r'\s*(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*"(?P<value>[^"]*)"\s*'
)


class MetricQueryError(RetryableError):
    """A metric read that failed NOW but may succeed later: network
    blips against Prometheus, and metrics that simply don't exist YET
    (a producer that hasn't ticked, an HA created before its signal).
    RETRYABLE in the controller taxonomy — the engine must keep
    requeueing (with backoff) rather than deactivate the autoscaler,
    because the metric can appear without any watch event on the HA
    object to revive it."""

    def __init__(self, message: str):
        super().__init__(message, code="MetricQuery", retryable=True)


def parse_instant_selector(query: str) -> Tuple[str, Dict[str, str]]:
    """Parse `metric_name{k="v",...}` into (name, labels)."""
    m = _SELECTOR_RE.match(query)
    if m is None:
        raise MetricQueryError(f"unsupported query syntax: {query!r}")
    labels: Dict[str, str] = {}
    raw = m.group("labels")
    if raw and raw.strip():
        # consume `k="v"` segments sequentially; anything unconsumed (gaps,
        # bad separators) is a syntax error, never silently dropped
        pos = 0
        while True:
            lm = _LABEL_RE.match(raw, pos)
            if lm is None or lm.start() != pos:
                raise MetricQueryError(
                    f"unsupported label syntax in query: {query!r}"
                )
            labels[lm.group("key")] = lm.group("value")
            pos = lm.end()
            if pos >= len(raw):
                break
            if raw[pos] != ",":
                raise MetricQueryError(
                    f"unsupported label syntax in query: {query!r}"
                )
            pos += 1
    return m.group("name"), labels


class RegistryMetricsClient:
    """Instant-selector evaluation against the in-process gauge registry.

    `observer` (any (Metric) -> None callable) sees every successful
    read — the forecast subsystem's metric-history hook
    (forecast/history.py): client-path observations feed the query-keyed
    warm pool that seeds a fresh HorizontalAutoscaler's history."""

    def __init__(
        self,
        registry: Optional[GaugeRegistry] = None,
        observer=None,
    ):
        self.registry = registry if registry is not None else default_registry()
        self.observer = observer

    def get_current_value(self, metric_spec) -> Metric:
        query = metric_spec.prometheus.query
        # inject sits INSIDE the span so a latency/hang chaos plan at
        # metrics.query shows up as metrics.query time in the trace,
        # not as an unexplained gap in the parent reconcile span
        with default_tracer().span("metrics.query", query=query):
            inject("metrics.query")
            return self._evaluate(query)

    def _evaluate(self, query: str) -> Metric:
        name, labels = parse_instant_selector(query)
        vec = self.registry.lookup_by_full_name(name)
        if vec is None:
            raise MetricQueryError(f"no metric named {name!r} for query {query!r}")
        matches = [
            s
            for s in vec.samples()
            if all(s.labels.get(k) == v for k, v in labels.items())
        ]
        # instant vector of exactly 1, matching the reference's response
        # validation (prometheus.go:46-55)
        if len(matches) != 1:
            raise MetricQueryError(
                f"expected instant vector of length 1 for query {query!r}, "
                f"got {len(matches)} series"
            )
        metric = Metric(
            name=name, labels=matches[0].labels, value=matches[0].value
        )
        if self.observer is not None:
            self.observer(metric)
        return metric


class PrometheusMetricsClient:
    """HTTP instant query (reference: prometheus.go:35-55). `observer`
    as on RegistryMetricsClient."""

    def __init__(
        self, uri: str, timeout_seconds: float = 5.0, observer=None
    ):
        self.uri = uri.rstrip("/")
        self.timeout = timeout_seconds
        self.observer = observer

    def get_current_value(self, metric_spec) -> Metric:
        query = metric_spec.prometheus.query
        # the HTTP query is the metrics path with REAL network latency —
        # exactly what the trace must attribute
        with default_tracer().span(
            "metrics.query", query=query, backend="prometheus"
        ):
            inject("metrics.query")
            return self._query(query)

    def _query(self, query: str) -> Metric:
        data = urllib.parse.urlencode({"query": query}).encode()
        request = urllib.request.Request(
            f"{self.uri}/api/v1/query",
            data=data,
            headers={"Content-Type": "application/x-www-form-urlencoded"},
        )
        try:
            with urllib.request.urlopen(request, timeout=self.timeout) as resp:
                payload = json.loads(resp.read())
        except OSError as e:
            raise MetricQueryError(f"request failed for query {query!r}, {e}")
        if payload.get("status") != "success":
            raise MetricQueryError(f"query {query!r} failed: {payload}")
        result = payload.get("data", {})
        if result.get("resultType") != "vector":
            raise MetricQueryError(
                f"expected vector and got {result.get('resultType')!r}"
            )
        vector = result.get("result", [])
        if len(vector) != 1:
            raise MetricQueryError(
                f"expected instant vector of length 1 for {query!r}, "
                f"got {len(vector)}"
            )
        metric = Metric(
            name=query, labels=vector[0].get("metric", {}),
            value=float(vector[0]["value"][1]),
        )
        if self.observer is not None:
            self.observer(metric)
        return metric


class MetricsClientFactory:
    """Dispatch on the metric's one-of source type (reference: client.go:40-53)."""

    def __init__(
        self,
        registry: Optional[GaugeRegistry] = None,
        prometheus_uri: Optional[str] = None,
        observer=None,
    ):
        self._registry_client = RegistryMetricsClient(
            registry, observer=observer
        )
        self._prometheus_client = (
            PrometheusMetricsClient(prometheus_uri, observer=observer)
            if prometheus_uri
            else None
        )

    def for_metric(self, metric_spec):
        if metric_spec.prometheus is not None:
            # external Prometheus takes precedence when configured; default
            # is the in-process registry (same query strings)
            if self._prometheus_client is not None:
                return self._prometheus_client
            return self._registry_client
        invariant_violated(
            "Failed to instantiate metrics client, no metric type specified"
        )
