"""Core object model: metadata, Nodes, Pods.

These mirror the slices of the k8s core/v1 API the reference consumes — Node
readiness/allocatable (reference: pkg/utils/node/predicates.go:18-25,
pkg/metrics/producers/reservedcapacity/reservations.go:45-56) and Pod
nodeName/requests, extended with the scheduling-constraint fields
(tolerations, nodeSelector, affinity) that the pending-capacity bin-pack
solver consumes (reference design: docs/designs/DESIGN.md "Pending Pods").
"""

from __future__ import annotations

import itertools
import time as _time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

from karpenter_tpu.utils.quantity import Quantity, parse_quantity

_uid_counter = itertools.count(1)
_process_id = uuid.uuid4().hex[:8]

RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_PODS = "pods"


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    uid: str = ""
    # int when minted by the local store; may be an opaque string when
    # sourced from a real apiserver (k8s API conventions) — compare only
    # for equality
    resource_version: Union[int, str] = 0
    creation_timestamp: float = 0.0

    def ensure_identity(self):
        if not self.uid:
            # process-unique prefix: a restarted control plane resuming a
            # durable store must never mint a uid already held by a
            # recovered object (the k8s uid contract distinguishes object
            # incarnations)
            self.uid = f"uid-{_process_id}-{next(_uid_counter)}"
        if not self.creation_timestamp:
            self.creation_timestamp = _time.time()


def resource_list(**kwargs) -> Dict[str, Quantity]:
    """Build a {resource: Quantity} map from keyword strings, e.g.
    resource_list(cpu="1100m", memory="1Gi")."""
    return {k: parse_quantity(v) for k, v in kwargs.items()}


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" tolerates all effects

    def tolerates(self, taint: "Taint") -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


@dataclass
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass
class Container:
    name: str = "main"
    requests: Dict[str, Quantity] = field(default_factory=dict)


@dataclass
class PodSpec:
    node_name: str = ""
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    # RuntimeClass pod overhead (core/v1 PodSpec.overhead): added on top of
    # the container maximum by the scheduler's fit check
    overhead: Dict[str, Quantity] = field(default_factory=dict)
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Toleration] = field(default_factory=list)


@dataclass
class PodStatus:
    phase: str = "Pending"


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    KIND = "Pod"

    def requests(self) -> Dict[str, Quantity]:
        """Sum of container resource requests (container-level only, matching
        reference reservations.go:45-56 — no init containers or overhead).
        This is the RESERVED-CAPACITY accounting semantics; the scheduler's
        fit-check semantics is effective_requests()."""
        totals: Dict[str, Quantity] = {}
        for container in self.spec.containers:
            for name, quantity in container.requests.items():
                totals[name] = totals.get(name, Quantity()).add(quantity)
        return totals

    def effective_requests(self) -> Dict[str, Quantity]:
        """The Kubernetes scheduler's effective resource request, per
        resource: max(sum over containers, max over init containers) +
        pod overhead. Init containers run sequentially BEFORE the main
        containers, so the pod needs the larger of the two phases; the
        RuntimeClass overhead rides on top unconditionally (upstream
        k8s.io/kubernetes resource helpers' PodRequests semantics,
        restartable-sidecar cases excluded — init restartPolicy isn't
        modeled). Used by the pending-pods bin-pack (OUR signal — the
        reference stubs it, pendingcapacity/producer.go:29-31 — so
        fidelity here follows the real scheduler, not reservations.go).
        """
        totals = self.requests()
        for container in self.spec.init_containers:
            for name, quantity in container.requests.items():
                current = totals.get(name)
                if current is None or quantity.value > current.value:
                    totals[name] = quantity
        for name, quantity in self.spec.overhead.items():
            current = totals.get(name)
            totals[name] = quantity if current is None else current.add(quantity)
        return totals


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: List[Taint] = field(default_factory=list)


@dataclass
class NodeCondition:
    type: str
    status: str


@dataclass
class NodeStatus:
    allocatable: Dict[str, Quantity] = field(default_factory=dict)
    conditions: List[NodeCondition] = field(default_factory=list)


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    KIND = "Node"


def is_ready_and_schedulable(node: Node) -> bool:
    """reference: pkg/utils/node/predicates.go:18-25"""
    for condition in node.status.conditions:
        if condition.type == "Ready":
            return condition.status == "True" and not node.spec.unschedulable
    return False


def matches_selector(labels: Dict[str, str], selector: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())
