"""Core object model: metadata, Nodes, Pods.

These mirror the slices of the k8s core/v1 API the reference consumes — Node
readiness/allocatable (reference: pkg/utils/node/predicates.go:18-25,
pkg/metrics/producers/reservedcapacity/reservations.go:45-56) and Pod
nodeName/requests, extended with the scheduling-constraint fields
(tolerations, nodeSelector, affinity) that the pending-capacity bin-pack
solver consumes (reference design: docs/designs/DESIGN.md "Pending Pods").
"""

from __future__ import annotations

import itertools
import time as _time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

from karpenter_tpu.utils.quantity import Quantity, parse_quantity

_uid_counter = itertools.count(1)
_process_id = uuid.uuid4().hex[:8]

RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_PODS = "pods"


@dataclass(slots=True)
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    uid: str = ""
    # int when minted by the local store; may be an opaque string when
    # sourced from a real apiserver (k8s API conventions) — compare only
    # for equality
    resource_version: Union[int, str] = 0
    creation_timestamp: float = 0.0

    def ensure_identity(self):
        if not self.uid:
            # process-unique prefix: a restarted control plane resuming a
            # durable store must never mint a uid already held by a
            # recovered object (the k8s uid contract distinguishes object
            # incarnations)
            self.uid = f"uid-{_process_id}-{next(_uid_counter)}"
        if not self.creation_timestamp:
            self.creation_timestamp = _time.time()


def resource_list(**kwargs) -> Dict[str, Quantity]:
    """Build a {resource: Quantity} map from keyword strings, e.g.
    resource_list(cpu="1100m", memory="1Gi")."""
    return {k: parse_quantity(v) for k, v in kwargs.items()}


@dataclass(slots=True)
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" tolerates all effects

    def tolerates(self, taint: "Taint") -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


@dataclass(slots=True)
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass(slots=True)
class NodeSelectorRequirement:
    """One matchExpression (core/v1): key OPERATOR values. Operators are
    the scheduler's set: In, NotIn, Exists, DoesNotExist, Gt, Lt (Gt/Lt
    compare the label value and values[0] as integers)."""

    key: str = ""
    operator: str = "In"
    values: List[str] = field(default_factory=list)


@dataclass(slots=True)
class NodeSelectorTerm:
    # matchFields (metadata.name selection) is not modeled: node groups,
    # not individual nodes, are the scale-up unit here
    match_expressions: List[NodeSelectorRequirement] = field(
        default_factory=list
    )


@dataclass(slots=True)
class NodeSelector:
    node_selector_terms: List[NodeSelectorTerm] = field(default_factory=list)


@dataclass(slots=True)
class PreferredSchedulingTerm:
    weight: int = 1  # 1-100 (core/v1)
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass(slots=True)
class NodeAffinity:
    required_during_scheduling_ignored_during_execution: Optional[
        NodeSelector
    ] = None
    # soft ordering: never makes an infeasible group feasible, but among
    # feasible groups the solver assigns each pod to its highest-scoring
    # group (weight-sum of matching preferences), index tie-break — the
    # kube-scheduler's NodeAffinity scoring plugin semantics
    preferred_during_scheduling_ignored_during_execution: List[
        PreferredSchedulingTerm
    ] = field(default_factory=list)


@dataclass(slots=True)
class LabelSelector:
    """metav1.LabelSelector: matchLabels AND matchExpressions (In/NotIn/
    Exists/DoesNotExist over LABEL values — no Gt/Lt here, matching the
    k8s API). An empty selector matches everything; None (field absent)
    matches nothing in the affinity contexts that use it."""

    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[NodeSelectorRequirement] = field(
        default_factory=list
    )

    def matches(self, labels: Dict[str, str]) -> bool:
        if any(labels.get(k) != v for k, v in self.match_labels.items()):
            return False
        for e in self.match_expressions:
            if e.operator in ("Gt", "Lt"):
                return False  # invalid in label selectors: never matches
            if not _requirement_matches(labels, e.key, e.operator, e.values):
                return False
        return True


@dataclass(slots=True)
class PodAffinityTerm:
    """core/v1 PodAffinityTerm: pods matching label_selector in the
    namespace scope, co-/anti-located by topology_key. namespaces=[]
    means the pod's own namespace (the k8s default).
    matchLabelKeys/mismatchLabelKeys (k8s >= 1.29) merge the INCOMING
    pod's values for those keys into the selector as In/NotIn
    requirements before shape canonicalization — the per-revision
    anti-affinity pattern (pod-template-hash): a mismatch key on the
    pod's own labels turns a self-matching selector into a foreign one
    automatically."""

    label_selector: Optional[LabelSelector] = None
    topology_key: str = ""
    namespaces: List[str] = field(default_factory=list)
    namespace_selector: Optional[LabelSelector] = None
    match_label_keys: List[str] = field(default_factory=list)
    mismatch_label_keys: List[str] = field(default_factory=list)


@dataclass(slots=True)
class WeightedPodAffinityTerm:
    weight: int = 1
    pod_affinity_term: PodAffinityTerm = field(
        default_factory=PodAffinityTerm
    )


@dataclass(slots=True)
class PodAntiAffinity:
    required_during_scheduling_ignored_during_execution: List[
        PodAffinityTerm
    ] = field(default_factory=list)
    # soft anti-affinity is a scheduler preference: the self-matching
    # slice is SCORED (soft_pod_affinity_shape -> pod_group_score),
    # never constrained
    preferred_during_scheduling_ignored_during_execution: List[
        WeightedPodAffinityTerm
    ] = field(default_factory=list)


@dataclass(slots=True)
class PodAffinity:
    required_during_scheduling_ignored_during_execution: List[
        PodAffinityTerm
    ] = field(default_factory=list)
    preferred_during_scheduling_ignored_during_execution: List[
        WeightedPodAffinityTerm
    ] = field(default_factory=list)


@dataclass(slots=True)
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    # inter-pod (anti-)affinity: the SELF-matching required slice is
    # constrained (pod_affinity_shape), the self-matching preferred
    # slice scored (soft_pod_affinity_shape), and required FOREIGN
    # selectors enforced against SCHEDULED state through the occupancy
    # census (_foreign_terms); only pending-vs-pending interactions and
    # namespaceSelector terms stay decode-only (docs/OPERATIONS.md
    # 'Scheduling fidelity')
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


@dataclass(slots=True)
class TopologySpreadConstraint:
    """core/v1 TopologySpreadConstraint. The solver honors DoNotSchedule
    constraints via water-filled domain splitting against the EXISTING
    matching-pod counts per domain — labelSelector (refined by
    matchLabelKeys with the pod's own values) drives the census
    (producers/pendingcapacity.DomainCensus) exactly as the scheduler's
    skew check counts it. ScheduleAnyway is a scheduler preference:
    scored against the same census (soft_spread_shape ->
    pod_group_score), never constrained (docs/OPERATIONS.md
    'Scheduling fidelity')."""

    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = "DoNotSchedule"
    label_selector: Optional[dict] = None
    min_domains: Optional[int] = None
    node_affinity_policy: str = ""
    node_taints_policy: str = ""
    match_label_keys: List[str] = field(default_factory=list)


# hostname spread = at most maxSkew more pods than the emptiest node; a
# fresh scale-up places balanced across the nodes it adds, so the
# constraint is satisfiable at any node count the pack chooses (see
# spread_shape below) — it neither splits nor excludes groups
HOSTNAME_TOPOLOGY_KEY = "kubernetes.io/hostname"


def raw_selector_form(raw: Optional[dict]) -> Optional[tuple]:
    """Canonical hashable form of a RAW (manifest-shaped) LabelSelector
    dict — the TopologySpreadConstraint.label_selector dialect
    (matchLabels/matchExpressions, camelCase keys). Same form as
    _selector_form so both dialects share selector_form_matches. None
    when the field is absent: a spread constraint without a selector
    counts no pods (metav1 semantics: nil selector selects nothing)."""
    if raw is None or not isinstance(raw, dict):
        return None
    return (
        tuple(sorted((raw.get("matchLabels") or {}).items())),
        tuple(
            sorted(
                (
                    e.get("key", ""),
                    e.get("operator", ""),
                    tuple(sorted(e.get("values") or ())),
                )
                for e in (raw.get("matchExpressions") or [])
            )
        ),
    )


def selector_form_matches(form: tuple, labels: Dict[str, str]) -> bool:
    """Evaluate a canonical selector form (_selector_form /
    raw_selector_form) against a label set — LabelSelector.matches
    semantics: matchLabels AND matchExpressions, empty selector matches
    everything, Gt/Lt invalid in label selectors (never match)."""
    match_labels, expressions = form
    if any(labels.get(k) != v for k, v in match_labels):
        return False
    for key, operator, values in expressions:
        if operator in ("Gt", "Lt"):
            return False
        if not _requirement_matches(labels, key, operator, values):
            return False
    return True


def spread_shape(
    constraints: Optional[list],
    namespace: str = "",
    labels: Optional[Dict[str, str]] = None,
) -> tuple:
    """Canonical hashable form of a pod's HARD topology spread:
    (namespace, entries) where entries are sorted (topologyKey, maxSkew,
    minDomains, selectorForm, selfMatch, honorAffinity) tuples for
    DoNotSchedule constraints on non-hostname keys (per (key, selector,
    policy): smallest skew and largest minDomains win — the most
    restrictive combination; differing policies stay separate entries
    since each is enforced independently). () = unconstrained. The namespace and
    the constraint's labelSelector (raw_selector_form; None = counts
    nothing) scope the EXISTING-pod domain counts
    (producers/pendingcapacity.DomainCensus) that the split honors;
    selfMatch records whether the POD ITSELF matches the selector (the
    kube-scheduler's selfMatchNum): only then do placed replicas
    accumulate into the skew the next placement sees. honorAffinity is
    the constraint's nodeAffinityPolicy (default Honor): with Ignore,
    ALL live nodes exposing the key define domains and counts, not just
    the ones passing the pod's nodeSelector + required affinity.

    hostname-keyed constraints are dropped here by design: domains are
    individual nodes, and balanced placement across the nodes a scale-up
    adds satisfies any maxSkew >= 1 — whereas zone/region-like keys bind
    the GROUP choice, which is what the bin-pack decides. ScheduleAnyway
    is soft (scheduler preference), never a constraint."""
    if not constraints:
        return ()
    binding = _bind_spread_constraints(constraints, labels)
    if not binding:
        return ()
    entries = tuple(
        (
            key,
            skew,
            min_domains,
            sel,
            sel is not None and selector_form_matches(sel, labels or {}),
            honor,
        )
        for (key, sel, honor), (skew, min_domains) in sorted(
            binding.items(),
            # None sorts apart from tuple selector forms
            key=lambda kv: (
                kv[0][0],
                kv[0][1] is not None,
                kv[0][1] or (),
                kv[0][2],
            ),
        )
    )
    return (namespace, entries)


def _bind_spread_constraints(
    constraints: list, labels: Optional[Dict[str, str]]
) -> Dict[tuple, Tuple[int, int]]:
    """(key, selectorForm, honorAffinity) -> (maxSkew, minDomains) for the
    binding (DoNotSchedule, non-hostname) constraints.

    Identity is (key, selector, affinityPolicy): constraints differing in
    ANY of those are enforced independently by the scheduler, so they must
    stay separate entries — merging a Honor and an Ignore view of the same
    selector could loosen the caps either view enforces alone (r3 code
    review). Within one identity, smallest skew and largest minDomains
    win — the most restrictive combination."""
    binding: Dict[tuple, Tuple[int, int]] = {}
    for c in constraints:
        if (
            c.when_unsatisfiable == "DoNotSchedule"
            and c.topology_key
            and c.topology_key != HOSTNAME_TOPOLOGY_KEY
        ):
            skew = max(1, int(c.max_skew))
            min_domains = max(0, int(c.min_domains or 0))
            honor = c.node_affinity_policy != "Ignore"
            sel = _spread_selector(c, labels)
            prev = binding.get((c.topology_key, sel, honor))
            if prev is not None:
                skew = min(prev[0], skew)
                min_domains = max(prev[1], min_domains)
            binding[(c.topology_key, sel, honor)] = (skew, min_domains)
    return binding


def _refine_term(term: "PodAffinityTerm", labels: Dict[str, str]):
    """PodAffinityTerm with matchLabelKeys/mismatchLabelKeys (k8s >=
    1.29) merged into the selector: the incoming pod's value for each
    present key becomes an In (match) or NotIn (mismatch) requirement;
    keys the pod doesn't carry are ignored, and the API forbids the
    fields without a labelSelector. Everything downstream (self/foreign
    classification, census matching) then sees the effective
    selector."""
    if not (term.match_label_keys or term.mismatch_label_keys):
        return term
    if term.label_selector is None:
        return term
    extra = []
    for key in sorted(set(term.match_label_keys)):
        if key in labels:
            extra.append(
                NodeSelectorRequirement(
                    key=key, operator="In", values=[labels[key]]
                )
            )
    for key in sorted(set(term.mismatch_label_keys)):
        if key in labels:
            extra.append(
                NodeSelectorRequirement(
                    key=key, operator="NotIn", values=[labels[key]]
                )
            )
    if not extra:
        return term
    return PodAffinityTerm(
        label_selector=LabelSelector(
            match_labels=dict(term.label_selector.match_labels),
            match_expressions=[
                *term.label_selector.match_expressions,
                *extra,
            ],
        ),
        topology_key=term.topology_key,
        namespaces=list(term.namespaces or []),
        namespace_selector=term.namespace_selector,
    )


def _self_matching_terms(
    terms: list,
    labels: Dict[str, str],
    namespace: str,
    assume_ns_selector: bool = False,
) -> list:
    """The PodAffinityTerms whose selector matches the POD'S OWN labels
    with the pod's own namespace in scope — the replica-spread /
    replica-co-location pattern, the only inter-pod slice a group-level
    scale-up signal can honor without pairwise pod state.

    assume_ns_selector (the ANTI call): a namespaceSelector term whose
    selector matches the pod's own labels is ALSO treated as self —
    whether the own namespace's labels match can't be known at shape
    build, and assuming they do only adds the 1-per-domain restriction
    among the pending replicas, which is conservative for an
    anti-affinity. The CO call must NOT assume it: own-in-scope would
    grant the first-replica bootstrap the scheduler may not give."""
    out = []
    for term in terms:
        if term.label_selector is None or not term.topology_key:
            continue
        if term.namespace_selector is not None and not assume_ns_selector:
            continue
        if (
            term.namespace_selector is None
            and term.namespaces
            and namespace not in term.namespaces
        ):
            continue
        if term.label_selector.matches(labels):
            out.append(term)
    return out


def pod_affinity_shape(
    affinity: Optional[Affinity],
    labels: Dict[str, str],
    namespace: str,
) -> tuple:
    """Canonical hashable form of a pod's REQUIRED inter-pod
    (anti-)affinity, restricted to the SELF-matching slice the solver
    models (docs/OPERATIONS.md 'Scheduling fidelity'):

    - anti-affinity on kubernetes.io/hostname -> one replica per node
      (the pod_exclusive solver operand);
    - anti-affinity on zone/region-like keys -> at most one replica per
      topology domain (per-domain cap-1 row expansion);
    - affinity (co-location) on non-hostname keys -> all replicas in
      ONE domain: groups must expose the key single-valued, and the
      solver's whole-row-to-one-group assignment provides the rest.
      hostname co-location (all replicas on one NODE) is modeled
      CONSERVATIVELY: with a matching scheduled pod anywhere in scope,
      new replicas are pinned to its existing node — honestly
      unschedulable on a scale-up (the sign +2 projection below); with
      none, the first-replica bootstrap admits ONE promised replica
      and the rest are reported unschedulable, since replicas beyond
      the first must join the first's node and a group-level pack
      cannot promise single-node co-residence.

    Returns () when unconstrained, else
    (flags, anti_keys, co_keys, ident, foreign) where flags bit 0 is
    hostname anti (exclusive rows) and bit 1 hostname co, and where
    ident is the WORKLOAD IDENTITY: the pod's namespace plus the
    canonical forms of the self-matching domain-relevant selectors. Two
    pods share an anti-group iff they match each other's selectors;
    replicas of one workload share the selector even when their LABELS
    differ per pod (StatefulSets stamp
    statefulset.kubernetes.io/pod-name on each replica — raw labels
    would fragment the group, r3 code review), and two workloads whose
    pods all match one selector genuinely are one mutual anti-group.
    `foreign` is the required terms whose selectors match OTHER
    workloads' pods (_foreign_terms), enforced against SCHEDULED state
    through the occupancy census. Preferred (soft) foreign terms are
    decoded, never constrained.
    """
    if affinity is None:
        return ()
    anti = affinity.pod_anti_affinity
    co = affinity.pod_affinity
    # matchLabelKeys/mismatchLabelKeys refine every term FIRST, so the
    # self/foreign split and the census all see the effective selector
    def refined_required(block):
        if block is None:
            return []
        return [
            _refine_term(t, labels)
            for t in block.required_during_scheduling_ignored_during_execution
        ]

    anti_required = refined_required(anti)
    co_required = refined_required(co)
    anti_terms = _self_matching_terms(
        anti_required, labels, namespace, assume_ns_selector=True
    )
    co_terms = _self_matching_terms(co_required, labels, namespace)
    flags = _hostname_flags(anti_terms, co_terms)
    anti_keys = _domain_keys(anti_terms)
    co_keys = _domain_keys(co_terms)
    foreign = _foreign_terms(
        anti_required, co_required, namespace, anti_terms, co_terms
    )
    if not flags and not anti_keys and not co_keys and not foreign:
        return ()
    ident = _workload_ident(namespace, anti_keys, co_keys, anti_terms, co_terms)
    return (flags, anti_keys, co_keys, ident, foreign)


def _hostname_flags(anti_terms: list, co_terms: list) -> int:
    """shape[0] is a FLAGS field: bit 0 = hostname ANTI (one replica per
    node, the pod_exclusive operand), bit 1 = hostname CO (all replicas
    on one node — census-pinned via the sign +2 foreign projection,
    bootstrap capped to one promised replica)."""
    flags = int(
        any(t.topology_key == HOSTNAME_TOPOLOGY_KEY for t in anti_terms)
    )
    if any(t.topology_key == HOSTNAME_TOPOLOGY_KEY for t in co_terms):
        flags |= 2
    return flags


def _workload_ident(
    namespace: str, anti_keys, co_keys, anti_terms, co_terms
) -> tuple:
    """The WORKLOAD IDENTITY: the pod's namespace plus the canonical
    forms of the self-matching domain-relevant selectors (see
    pod_affinity_shape docstring for why selectors, not raw labels)."""
    if not (anti_keys or co_keys):
        return ()
    return (
        namespace,
        tuple(
            sorted(
                {
                    _selector_form(t.label_selector)
                    for t in (*anti_terms, *co_terms)
                    if t.topology_key != HOSTNAME_TOPOLOGY_KEY
                }
            )
        ),
    )


def _term_ns_scope(t, listed: tuple):
    """The tagged ("selector", ...) scope for a namespaceSelector term;
    None when the term scopes by explicit names / own namespace only."""
    if t.namespace_selector is not None:
        return ("selector", _selector_form(t.namespace_selector), listed)
    return None


def _resolved_scope(scope, listed: tuple, namespace: str):
    """Resolve the k8s default at build time: an empty namespaces list
    means the POD'S OWN namespace."""
    if scope is not None:
        return scope
    return ("names", listed or (namespace,))


def _own_term_entries(sign, t, scope, listed, namespace):
    """Foreign-mask entries projected for a SELF-matching term.

    The self-matching slice is modeled by the self machinery for the
    pod's OWN namespace — but a term reaching ADDITIONAL namespaces (an
    explicit list or a namespaceSelector) also binds on matching pods
    THERE, which only the census-backed foreign mask can enforce (r3
    code review). An anti term blocks their domains (sign -1). A CO term
    with extra namespaces is pinned by them too: matching pods in a
    foreign in-scope namespace restrict placement to their domains even
    when the own namespace is empty — admitting only own-namespace
    evidence then grants a first-replica bootstrap the scheduler does
    not give (r3 advisor). It projects with sign +2 (bootstrap-eligible
    co) over the FULL scope: the pod itself is in scope, so an empty
    census keeps the scheduler's first-replica grace, unlike a true
    foreign co term. Self co terms never carry a namespaceSelector
    (_self_matching_terms filters those for CO), so the +2 scope is
    always an explicit name list. Hostname CO keys ALWAYS project (even
    with no extra namespaces): a matching pod anywhere in scope pins new
    replicas to its EXISTING node, which a scale-up's fresh nodes can
    never satisfy — the census handler marks the row honestly
    unschedulable, while an empty census keeps the first-replica grace
    (the bootstrap itself is capped to ONE promised replica by the anti
    expansion — replicas beyond the first must join the first's node,
    which a group-level pack cannot promise)."""
    extra = tuple(ns for ns in listed if ns != namespace)
    sel = _selector_form(t.label_selector)
    if sign < 0:
        if scope is not None:
            return [(sign, t.topology_key, sel, scope)]
        if extra:
            return [(sign, t.topology_key, sel, ("names", extra))]
        return []
    if extra or t.topology_key == HOSTNAME_TOPOLOGY_KEY:
        return [
            (2, t.topology_key, sel,
             ("names", tuple(sorted((namespace, *extra)))))
        ]
    return []


def _foreign_terms(anti_required, co_required, namespace, anti_terms, co_terms):
    """Canonical FOREIGN required (anti-)affinity terms — selectors that
    do NOT match the pod's own labels, i.e. constraints against OTHER
    workloads' pods. The solver enforces them against SCHEDULED state
    (the occupancy census): an anti term forbids the domains existing
    matching pods occupy; a co term requires one (no first-replica
    bootstrap for foreign selectors — if no matching pod exists, the
    pod is genuinely unschedulable, exactly the scheduler's rule).
    Interactions with the matching workload's PENDING pods (placed in
    the same solve) still need pairwise pod state and remain out of
    scope (docs/OPERATIONS.md). Returns sorted (sign, topologyKey,
    selectorForm, scope) tuples, sign -1 anti / +1 co. The scope is
    TAGGED: ("names", namesTuple) — the term's explicit list, or the
    pod's own namespace when empty — or ("selector", nsSelectorForm,
    explicitNames): namespaceSelector terms resolve to the matching
    namespaces at ENCODE time against the live Namespace set, unioned
    with any explicit list (the k8s combination rule). The tag makes
    the two shapes self-describing — discrimination must never lean on
    namespace-name syntax. Skipped (never constrained): hostname ANTI
    terms — a scale-up's fresh nodes host nothing, so they can never
    be blocked. Hostname CO terms are kept: a fresh node can never
    satisfy "must run beside an existing pod on one node", so the row
    is honestly unschedulable."""
    out = set()
    own_anti = set(map(id, anti_terms))
    own_co = set(map(id, co_terms))
    for sign, terms, own in (
        (-1, anti_required, own_anti),
        (1, co_required, own_co),
    ):
        for t in terms:
            if t.label_selector is None or not t.topology_key:
                continue
            if sign < 0 and t.topology_key == HOSTNAME_TOPOLOGY_KEY:
                continue
            listed = tuple(sorted(t.namespaces or ()))
            scope = _term_ns_scope(t, listed)
            if id(t) in own:
                out.update(
                    _own_term_entries(sign, t, scope, listed, namespace)
                )
                continue
            out.add(
                (
                    sign,
                    t.topology_key,
                    _selector_form(t.label_selector),
                    _resolved_scope(scope, listed, namespace),
                )
            )
    return tuple(sorted(out))


def soft_spread_shape(
    constraints: Optional[list],
    namespace: str = "",
    labels: Optional[Dict[str, str]] = None,
) -> tuple:
    """Canonical hashable form of a pod's SOFT topology spread
    (whenUnsatisfiable=ScheduleAnyway, non-hostname keys): (namespace,
    sorted (topologyKey, selectorForm) pairs). () = none. The kube-
    scheduler SCORES these — domains with fewer matching pods rank
    higher, nodes missing the key rank lowest — so the solver models
    them as a pod_group_score contribution (PodTopologySpread scoring
    plugin, default weight 2), never as a constraint. The selector is
    refined by matchLabelKeys exactly like the hard shape."""
    if not constraints:
        return ()
    pairs = {
        (c.topology_key, _spread_selector(c, labels))
        for c in constraints
        if c.when_unsatisfiable == "ScheduleAnyway"
        and c.topology_key
        and c.topology_key != HOSTNAME_TOPOLOGY_KEY
    }
    if not pairs:
        return ()
    entries = tuple(
        sorted(pairs, key=lambda p: (p[0], p[1] is not None, p[1] or ()))
    )
    return (namespace, entries)


def soft_pod_affinity_shape(
    affinity: Optional[Affinity],
    labels: Dict[str, str],
    namespace: str,
) -> tuple:
    """Canonical hashable form of a pod's PREFERRED inter-pod
    (anti-)affinity, restricted to the SELF-matching slice (the
    spread-replicas-apart / pack-replicas-together preferences):
    (namespace, sorted (sign, weight, topologyKey, selectorForm)
    entries), sign +1 for affinity, -1 for anti-affinity. () = none.
    The kube-scheduler SCORES these (InterPodAffinity plugin, default
    weight 1): each existing matching pod in a candidate's domain adds
    sign x weight — the solver models the same sum over the census.
    Hostname-keyed terms are dropped: a scale-up's new nodes are fresh
    hostnames, so their domains hold no existing pods either way."""
    if affinity is None:
        return ()
    entries = []
    for sign, block in (
        (1, affinity.pod_affinity),
        (-1, affinity.pod_anti_affinity),
    ):
        if block is None:
            continue
        for wt in block.preferred_during_scheduling_ignored_during_execution:
            term = _refine_term(wt.pod_affinity_term, labels)
            if (
                term.topology_key
                and term.topology_key != HOSTNAME_TOPOLOGY_KEY
                and _self_matching_terms([term], labels, namespace)
            ):
                entries.append(
                    (
                        sign,
                        max(1, min(100, int(wt.weight))),
                        term.topology_key,
                        _selector_form(term.label_selector),
                    )
                )
    if not entries:
        return ()
    return (namespace, tuple(sorted(entries)))


def _spread_selector(c, labels: Optional[Dict[str, str]]) -> Optional[tuple]:
    """A spread constraint's canonical selector form, refined by
    matchLabelKeys (k8s >= 1.27): the incoming pod's values for those
    keys are ANDed into the selector (the pod-template-hash
    per-revision-spread pattern). Keys the pod doesn't carry are
    ignored, and the API forbids matchLabelKeys without labelSelector."""
    sel = raw_selector_form(c.label_selector)
    if c.match_label_keys and sel is not None and labels:
        extra = tuple(
            (k, labels[k])
            for k in sorted(set(c.match_label_keys))
            if k in labels
        )
        if extra:
            sel = (tuple(sorted({*sel[0], *extra})), sel[1])
    return sel


def _domain_keys(terms: list) -> tuple:
    """Sorted distinct non-hostname topology keys of PodAffinityTerms."""
    return tuple(
        sorted(
            {
                t.topology_key
                for t in terms
                if t.topology_key != HOSTNAME_TOPOLOGY_KEY
            }
        )
    )


def _selector_form(sel: "LabelSelector") -> tuple:
    """Canonical hashable form of a label selector — the workload
    identity unit for pod_affinity_shape's ident."""
    return (
        tuple(sorted(sel.match_labels.items())),
        tuple(
            sorted(
                (e.key, e.operator, tuple(sorted(e.values)))
                for e in sel.match_expressions
            )
        ),
    )


def affinity_shape(affinity: Optional[Affinity]) -> tuple:
    """Canonical hashable form of a pod's REQUIRED node affinity: a tuple
    of terms, each a sorted tuple of (key, operator, sorted values). () =
    unconstrained. The dedup/encode layers key on this (two pods with the
    same shape are interchangeable to the solver)."""
    if affinity is None or affinity.node_affinity is None:
        return ()
    required = (
        affinity.node_affinity.required_during_scheduling_ignored_during_execution
    )
    if required is None or not required.node_selector_terms:
        return ()
    return tuple(
        tuple(
            sorted(
                (e.key, e.operator, tuple(sorted(e.values)))
                for e in term.match_expressions
            )
        )
        for term in required.node_selector_terms
    )


def preferred_shape(affinity: Optional[Affinity]) -> tuple:
    """Canonical hashable form of a pod's PREFERRED node affinity: sorted
    (weight, term) pairs where term is the same canonical tuple
    affinity_shape uses. () = no preferences. Terms with no expressions
    are dropped (they can never match, k8s empty-term semantics)."""
    if affinity is None or affinity.node_affinity is None:
        return ()
    preferred = (
        affinity.node_affinity.preferred_during_scheduling_ignored_during_execution
    )
    if not preferred:
        return ()
    shape = []
    for p in preferred:
        term = tuple(
            sorted(
                (e.key, e.operator, tuple(sorted(e.values)))
                for e in p.preference.match_expressions
            )
        )
        if term:
            shape.append((int(p.weight), term))
    return tuple(sorted(shape))


def preference_score(labels: Dict[str, str], shape: tuple) -> int:
    """Weight-sum of matching preference terms (the NodeAffinity scoring
    plugin's per-node sum, before normalization — ordering is all the
    solver needs)."""
    return sum(
        weight
        for weight, term in shape
        if all(
            _requirement_matches(labels, key, operator, values)
            for key, operator, values in term
        )
    )


def _numeric_requirement(labels, key, operator, values) -> bool:
    """Gt/Lt: integer comparison; missing key, empty values, or
    non-integer text never match (upstream nodeaffinity semantics)."""
    if key not in labels or not values:
        return False
    try:
        have, want = int(labels[key]), int(values[0])
    except ValueError:
        return False
    return have > want if operator == "Gt" else have < want


def _requirement_matches(labels: Dict[str, str], key, operator, values) -> bool:
    present = key in labels
    if operator == "In":
        return present and labels[key] in values
    if operator == "NotIn":
        # k8s semantics: a missing key satisfies NotIn
        return not present or labels[key] not in values
    if operator == "Exists":
        return present
    if operator == "DoesNotExist":
        return not present
    if operator in ("Gt", "Lt"):
        return _numeric_requirement(labels, key, operator, values)
    return False  # unknown operator: never matches (validation's job)


def matches_affinity_shape(labels: Dict[str, str], shape: tuple) -> bool:
    """Scheduler semantics over a label assignment: terms are ORed; the
    matchExpressions within a term are ANDed; an empty term matches
    nothing (upstream nodeaffinity helpers). () = no constraint."""
    if not shape:
        return True
    return any(
        term
        and all(
            _requirement_matches(labels, key, operator, values)
            for key, operator, values in term
        )
        for term in shape
    )


@dataclass(slots=True)
class Container:
    name: str = "main"
    requests: Dict[str, Quantity] = field(default_factory=dict)


@dataclass(slots=True)
class PodSpec:
    node_name: str = ""
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    # RuntimeClass pod overhead (core/v1 PodSpec.overhead): added on top of
    # the container maximum by the scheduler's fit check
    overhead: Dict[str, Quantity] = field(default_factory=dict)
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Toleration] = field(default_factory=list)
    # required node affinity (matchExpressions); ANDs with node_selector,
    # exactly as the kube-scheduler treats the two fields
    affinity: Optional[Affinity] = None
    # hard spread constraints partition the pending weight across topology
    # domains (producers/pendingcapacity balanced split); soft ones are
    # decoded but not constrained
    topology_spread_constraints: List[TopologySpreadConstraint] = field(
        default_factory=list
    )
    # PriorityClass value resolved by admission (core/v1 PodSpec.priority);
    # None = unresolved — effective_priority() then falls back to the
    # named class (well-known system classes) or the fleet default
    priority: Optional[int] = None
    priority_class_name: str = ""


@dataclass(slots=True)
class PodStatus:
    phase: str = "Pending"


@dataclass(slots=True)
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    KIND = "Pod"

    def requests(self) -> Dict[str, Quantity]:
        """Sum of container resource requests (container-level only, matching
        reference reservations.go:45-56 — no init containers or overhead).
        This is the RESERVED-CAPACITY accounting semantics; the scheduler's
        fit-check semantics is effective_requests()."""
        totals: Dict[str, Quantity] = {}
        for container in self.spec.containers:
            for name, quantity in container.requests.items():
                current = totals.get(name)
                # first occurrence aliases the (immutable) quantity
                # instead of Quantity(0).add(q): same value AND format
                # (a zero receiver adopts the operand's format anyway),
                # minus two Fraction constructions per pod resource —
                # this runs for every watch-delivered pod
                totals[name] = (
                    quantity if current is None else current.add(quantity)
                )
        return totals

    def effective_requests(self) -> Dict[str, Quantity]:
        """The Kubernetes scheduler's effective resource request, per
        resource: max(sum over containers, max over init containers) +
        pod overhead. Init containers run sequentially BEFORE the main
        containers, so the pod needs the larger of the two phases; the
        RuntimeClass overhead rides on top unconditionally (upstream
        k8s.io/kubernetes resource helpers' PodRequests semantics,
        restartable-sidecar cases excluded — init restartPolicy isn't
        modeled). Used by the pending-pods bin-pack (OUR signal — the
        reference stubs it, pendingcapacity/producer.go:29-31 — so
        fidelity here follows the real scheduler, not reservations.go).
        """
        totals = self.requests()
        for container in self.spec.init_containers:
            for name, quantity in container.requests.items():
                current = totals.get(name)
                if current is None or quantity.value > current.value:
                    totals[name] = quantity
        for name, quantity in self.spec.overhead.items():
            current = totals.get(name)
            totals[name] = quantity if current is None else current.add(quantity)
        return totals


@dataclass(slots=True)
class NodeSpec:
    unschedulable: bool = False
    taints: List[Taint] = field(default_factory=list)


@dataclass(slots=True)
class NodeCondition:
    type: str
    status: str


@dataclass(slots=True)
class NodeStatus:
    allocatable: Dict[str, Quantity] = field(default_factory=dict)
    conditions: List[NodeCondition] = field(default_factory=list)


@dataclass(slots=True)
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    KIND = "Node"


@dataclass(slots=True)
class Namespace:
    """core/v1 Namespace (metadata only): the labels resolve
    namespaceSelector terms in inter-pod (anti-)affinity — which
    namespaces' pods a foreign term censuses."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    KIND = "Namespace"


# Well-known PriorityClass values (the kube-scheduler's built-in system
# classes); any other named class without a resolved spec.priority falls
# back to the caller-supplied fleet default.
SYSTEM_PRIORITY_CLASSES = {
    "system-cluster-critical": 2_000_000_000,
    "system-node-critical": 2_000_001_000,
}


def effective_priority(pod: Pod, default: int = 0) -> int:
    """The pod's scheduling priority — the value the packing/eviction
    kernels compare (ops/binpack pod_priority, ops/preempt): a resolved
    spec.priority always wins, then the well-known system classes by
    name, then `default` (the --default-priority knob) for pods NAMING
    an unknown class. Class-less pods are plain priority 0 — the
    default must never lift the whole fleet into nonzero-priority
    encoding (that would disable the encoder's delta path and make
    every pending pod a preemption candidate)."""
    if pod.spec.priority is not None:
        return int(pod.spec.priority)
    name = pod.spec.priority_class_name
    if not name:
        return 0
    return SYSTEM_PRIORITY_CLASSES.get(name, default)


# Capacity-tier node labels every major provider stamps on
# spot/preemptible capacity; the packing kernels treat any match as
# tier 1 (preemptible — ops/binpack group_tier, ops/preempt node_tier).
PREEMPTIBLE_CAPACITY_LABELS = frozenset(
    {
        ("karpenter.sh/capacity-type", "spot"),
        ("cloud.google.com/gke-spot", "true"),
        ("cloud.google.com/gke-preemptible", "true"),
        ("eks.amazonaws.com/capacityType", "SPOT"),
        ("kubernetes.azure.com/scalesetpriority", "spot"),
    }
)


def capacity_tier_of(labels) -> int:
    """0 = on-demand, 1 = preemptible/spot, from a node/group label set
    (a dict or an iterable of (key, value) items — group profiles carry
    the latter)."""
    items = labels.items() if isinstance(labels, dict) else labels
    return (
        1
        if any(item in PREEMPTIBLE_CAPACITY_LABELS for item in items)
        else 0
    )


# Zone topology label (the well-known key kube schedulers spread on) and
# the reservation label the constraint plane fences reserved capacity
# with (same label-precedent family as karpenter.sh/capacity-type above).
ZONE_LABEL = "topology.kubernetes.io/zone"
RESERVATION_LABEL = "karpenter.sh/reservation"


def domain_of(labels, topology_key: str) -> str:
    """Topology-domain name for an ARBITRARY node label axis — the value
    of `topology_key` in a node/group label set (a dict or an iterable
    of (key, value) items — group profiles carry the latter); "" when
    the label is absent. The spread constraint plane balances over
    whatever axis the spec names (zone, hostname, rack, ...); zone is
    merely the default key."""
    items = labels.items() if isinstance(labels, dict) else labels
    for key, value in items:
        if key == topology_key:
            return value
    return ""


def zone_of(labels) -> str:
    """Zone name from a node/group label set; "" when the group carries
    no zone label (capacity_tier_of idiom)."""
    return domain_of(labels, ZONE_LABEL)


def reservation_of(labels) -> str:
    """Reservation name a node/group is fenced under ("" = unreserved),
    from the karpenter.sh/reservation label (dict or (key, value)
    items)."""
    items = labels.items() if isinstance(labels, dict) else labels
    for key, value in items:
        if key == RESERVATION_LABEL:
            return value
    return ""


def is_ready_and_schedulable(node: Node) -> bool:
    """reference: pkg/utils/node/predicates.go:18-25"""
    for condition in node.status.conditions:
        if condition.type == "Ready":
            return condition.status == "True" and not node.spec.unschedulable
    return False


def matches_selector(labels: Dict[str, str], selector: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())
