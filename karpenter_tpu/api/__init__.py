"""API group autoscaling.karpenter.sh/v1alpha1, TPU-native build.

reference: pkg/apis/autoscaling/v1alpha1/doc.go:28-58, pkg/apis/apis.go:27-33.
"""

from karpenter_tpu.api import conditions
from karpenter_tpu.api.core import (
    Node,
    ObjectMeta,
    Pod,
    is_ready_and_schedulable,
    matches_selector,
    resource_list,
)
from karpenter_tpu.api.horizontalautoscaler import HorizontalAutoscaler
from karpenter_tpu.api.metricsproducer import MetricsProducer
from karpenter_tpu.api.scalablenodegroup import ScalableNodeGroup

GROUP = "autoscaling.karpenter.sh"
VERSION = "v1alpha1"

# Kinds registered in the scheme (reference: pkg/apis/autoscaling/v1alpha1/doc.go:54-58)
KINDS = {
    HorizontalAutoscaler.KIND: HorizontalAutoscaler,
    MetricsProducer.KIND: MetricsProducer,
    ScalableNodeGroup.KIND: ScalableNodeGroup,
}

__all__ = [
    "GROUP",
    "VERSION",
    "KINDS",
    "conditions",
    "HorizontalAutoscaler",
    "MetricsProducer",
    "ScalableNodeGroup",
    "Node",
    "Pod",
    "ObjectMeta",
    "resource_list",
    "is_ready_and_schedulable",
    "matches_selector",
]
