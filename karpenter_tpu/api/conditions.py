"""Status conditions with knative living-condition-set semantics.

The reference manages status through knative's ConditionManager (reference:
pkg/apis/autoscaling/v1alpha1/horizontalautoscaler_status.go:89-95 and
metricsproducer_status.go / scalablenodegroup_status.go): each resource
declares a set of dependent condition types, all of "true-happy" polarity,
plus a derived top-level Ready condition that is True iff every dependent is
True. Tests converge on "happy" = all conditions True
(pkg/test/expectations/expectations.go:51-61).
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass
from typing import Dict, List, Optional

TRUE = "True"
FALSE = "False"
UNKNOWN = "Unknown"

READY = "Ready"
# Shared condition types (reference: pkg/apis/autoscaling/v1alpha1/doc.go and
# the per-resource *_status.go files).
ACTIVE = "Active"
ABLE_TO_SCALE = "AbleToScale"
SCALING_UNBOUNDED = "ScalingUnbounded"
STABILIZED = "Stabilized"

# Forecasting: set only on HorizontalAutoscalers whose spec opts into
# predictive scaling (behavior.forecast, docs/forecasting.md). True =
# forecasts are blending into scale-up decisions; False = degraded to
# reactive-only, with the reason naming why (warming up, skill below
# the confidence floor, forecast path unavailable). NOT a dependent of
# Ready — a degraded forecast is a posture, not a failure.
FORECASTING = "Forecasting"

# Structured condition REASONS (machine-readable; the message carries the
# human detail). ActuationCircuitOpen: the per-node-group actuation
# circuit breaker is open after repeated provider failures — the message
# threads the last RetryableError.code and the next-probe ETA
# (docs/resilience.md "Circuit breaker").
ACTUATION_CIRCUIT_OPEN = "ActuationCircuitOpen"


@dataclass(slots=True)
class Condition:
    type: str
    status: str = UNKNOWN
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


class ConditionManager:
    """Living condition set over a resource's .conditions list."""

    def __init__(self, dependents: List[str], conditions: List[Condition]):
        self.dependents = list(dependents)
        self.conditions = conditions

    def _index(self) -> Dict[str, Condition]:
        return {c.type: c for c in self.conditions}

    def get(self, condition_type: str) -> Optional[Condition]:
        return self._index().get(condition_type)

    def initialize(self) -> None:
        index = self._index()
        for t in self.dependents + [READY]:
            if t not in index:
                self.conditions.append(Condition(type=t, status=UNKNOWN))

    def _set(self, condition_type: str, status: str, reason: str, message: str):
        index = self._index()
        existing = index.get(condition_type)
        if existing is None:
            existing = Condition(type=condition_type)
            self.conditions.append(existing)
        if (existing.status, existing.reason, existing.message) != (
            status,
            reason,
            message,
        ):
            existing.status = status
            existing.reason = reason
            existing.message = message
            existing.last_transition_time = _time.time()
        self._recompute_ready()

    def _recompute_ready(self):
        index = self._index()
        status = TRUE
        reason, message = "", ""
        for t in self.dependents:
            dep = index.get(t)
            if dep is None or dep.status == UNKNOWN:
                status = UNKNOWN
            elif dep.status == FALSE:
                status, reason, message = FALSE, dep.reason, dep.message
                break
        ready = index.get(READY)
        if ready is None:
            ready = Condition(type=READY)
            self.conditions.append(ready)
        if (ready.status, ready.reason, ready.message) != (status, reason, message):
            ready.status = status
            ready.reason = reason
            ready.message = message
            ready.last_transition_time = _time.time()

    def mark_true(self, condition_type: str) -> None:
        self._set(condition_type, TRUE, "", "")

    def mark_false(self, condition_type: str, reason: str = "", message: str = ""):
        self._set(condition_type, FALSE, reason, message)

    def mark_unknown(self, condition_type: str, reason: str = "", message: str = ""):
        self._set(condition_type, UNKNOWN, reason, message)

    def is_happy(self) -> bool:
        """True iff every condition on the resource is True."""
        if not self.conditions:
            return False
        return all(c.status == TRUE for c in self.conditions)
