"""ScalableNodeGroup resource: scale-subresource shim over cloud node groups.

reference: pkg/apis/autoscaling/v1alpha1/scalablenodegroup.go:24-66,
scalablenodegroup_status.go:21-63, scalablenodegroup_validation.go:39-56.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from karpenter_tpu.api.conditions import (
    ABLE_TO_SCALE,
    ACTIVE,
    STABILIZED,
    Condition,
    ConditionManager,
)
from karpenter_tpu.api.core import ObjectMeta

# Provider node-group types. AWS types kept for spec parity with the
# reference; the TPU-native deployment uses the tpu pod-slice pool type.
AWS_EC2_AUTO_SCALING_GROUP = "AWSEC2AutoScalingGroup"
AWS_EKS_NODE_GROUP = "AWSEKSNodeGroup"
TPU_POD_SLICE_POOL = "TPUPodSlicePool"
FAKE_NODE_GROUP = "FakeNodeGroup"


@dataclass(slots=True)
class WarmPoolSpec:
    """Pre-provisioned headroom (docs/cost.md "Warm pools"): the group
    keeps `warm` spare nodes on top of its desired replicas — sized each
    reconcile between [minWarm, maxWarm] by the cost subsystem's
    forecast-risk headroom signal (minWarm with no signal) — so a demand
    rise lands on capacity that already exists instead of waiting out
    the provider's provisioning latency (the BLITZSCALE lead-time
    attack; the reduction is measured by `--simulate --cost` against the
    karpenter_reconcile_e2e_seconds story). The warm target actuates
    through the ordinary ScalableNodeGroup controller door — fenced,
    journaled, breaker-guarded — never a side channel."""

    min_warm: int = 0
    max_warm: int = 0

    def validate(self) -> None:
        if self.min_warm < 0:
            raise ValueError(
                f"warmPool minWarm must be >= 0, got {self.min_warm}"
            )
        if self.max_warm < self.min_warm:
            raise ValueError(
                "warmPool maxWarm cannot be less than minWarm "
                f"({self.max_warm} < {self.min_warm})"
            )


@dataclass(slots=True)
class ScalableNodeGroupSpec:
    replicas: Optional[int] = None
    type: str = ""
    id: str = ""
    # capacity tier: True marks the whole group preemptible/spot —
    # its pods are evictable-by-contract to the eviction planner
    # (docs/preemption.md), independent of the per-node capacity-type
    # labels the packing tier is derived from (api/core.capacity_tier_of)
    preemptible: bool = False
    # PDB-style disruption budget: max CONCURRENT preemption evictions
    # charged against this group's nodes in one HOLD window (the
    # engine's hold_s, 120s — charges expire with the hold, not the
    # 30s plan cadence); None = the engine-level --preempt-budget
    # default
    eviction_budget: Optional[int] = None
    # pre-provisioned warm headroom (docs/cost.md "Warm pools"); None =
    # no warm pool, byte-identical to the pre-cost controller behavior
    warm_pool: Optional[WarmPoolSpec] = None


@dataclass(slots=True)
class ScalableNodeGroupStatus:
    replicas: Optional[int] = None
    conditions: List[Condition] = field(default_factory=list)


@dataclass(slots=True)
class ScalableNodeGroup:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ScalableNodeGroupSpec = field(default_factory=ScalableNodeGroupSpec)
    status: ScalableNodeGroupStatus = field(default_factory=ScalableNodeGroupStatus)

    KIND = "ScalableNodeGroup"

    def status_conditions(self) -> ConditionManager:
        return ConditionManager(
            [ACTIVE, ABLE_TO_SCALE, STABILIZED], self.status.conditions
        )

    def validate(self) -> None:
        if self.spec.warm_pool is not None:
            self.spec.warm_pool.validate()
        validator = _validators.get(self.spec.type)
        if validator is None:
            raise ValueError(f"Unexpected type {self.spec.type}")
        validator(self.spec)

    def default(self) -> None:
        pass


# Pluggable per-provider validators
# (reference: scalablenodegroup_validation.go:39-56)
_validators = {}


def register_scalable_node_group_validator(node_group_type: str, validator) -> None:
    _validators[node_group_type] = validator
