"""YAML/dict codec for the API types — kubectl-manifest fidelity.

reference: the CRD YAML shapes in docs/examples/*.yaml and the kubebuilder
JSON tags on the Go structs (e.g. pkg/apis/autoscaling/v1alpha1/
horizontalautoscaler.go:33-58 `json:"scaleTargetRef"`, metricsproducer.go:
22-44 `json:"scheduleSpec"`). The reference gets (de)serialization from the
apiserver + controller-gen; here a reflective codec hydrates the Python
dataclasses from the SAME manifests, so the reference's docs/examples drive
this framework's tests unchanged (the envtest pattern,
pkg/test/environment/namespace.go:57-83).

Key mapping is mechanical camelCase<->snake_case with per-field overrides
for the places the reference's JSON tag differs from the Go field
(`scheduleSpec` -> ScheduleSpec field `schedule`).
"""

from __future__ import annotations

import dataclasses
import re
import typing
from typing import Any, Dict, List, Type

import yaml

from karpenter_tpu.api.core import (
    Container,
    Namespace,
    Node,
    ObjectMeta,
    Pod,
)
from karpenter_tpu.api.horizontalautoscaler import HorizontalAutoscaler
from karpenter_tpu.api.metricsproducer import MetricsProducer
from karpenter_tpu.api.poolgroup import PoolGroup
from karpenter_tpu.api.scalablenodegroup import ScalableNodeGroup
from karpenter_tpu.utils.quantity import Quantity

API_VERSION = "autoscaling.karpenter.sh/v1alpha1"
CORE_API_VERSION = "v1"  # Node/Pod are core/v1 kinds
AUTOSCALING_KINDS = (
    "HorizontalAutoscaler",
    "MetricsProducer",
    "PoolGroup",
    "ScalableNodeGroup",
)

KINDS: Dict[str, type] = {
    "HorizontalAutoscaler": HorizontalAutoscaler,
    "MetricsProducer": MetricsProducer,
    "PoolGroup": PoolGroup,
    "ScalableNodeGroup": ScalableNodeGroup,
    # core kinds so test fixtures can be manifests too
    "Node": Node,
    "Pod": Pod,
    "Namespace": Namespace,
}

# YAML key -> dataclass field, where mechanical mapping doesn't hold
# (reference JSON tags vs field names)
_KEY_TO_FIELD = {
    "scheduleSpec": "schedule",
    "apiVersion": "api_version",
}
_FIELD_TO_KEY = {v: k for k, v in _KEY_TO_FIELD.items()}

_CAMEL_RE = re.compile(r"(?<!^)(?=[A-Z])")


def camel_to_snake(name: str) -> str:
    return _CAMEL_RE.sub("_", name).lower()


def snake_to_camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(part.capitalize() for part in rest)


def _field_types(cls: type) -> Dict[str, Any]:
    return typing.get_type_hints(cls)


def _unwrap_optional(tp: Any) -> Any:
    if typing.get_origin(tp) is typing.Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def _coerce(value: Any, tp: Any, lenient: bool = False) -> Any:
    tp = _unwrap_optional(tp)
    if value is None:
        return None
    if typing.get_origin(tp) is not None:
        return _coerce_generic(value, tp, lenient)
    return _coerce_scalar(value, tp, lenient)


def _coerce_generic(value: Any, tp: Any, lenient: bool) -> Any:
    """Containers and unions (types with a typing origin)."""
    origin = typing.get_origin(tp)
    if origin is typing.Union:
        # Union[int, str] (resourceVersion): numeric when locally minted,
        # opaque string from a real apiserver — prefer int, keep strings
        try:
            return int(value)
        except (TypeError, ValueError):
            return value
    if origin in (list, List):
        (item_tp,) = typing.get_args(tp) or (Any,)
        return [_coerce(v, item_tp, lenient=lenient) for v in value]
    if origin in (dict, Dict):
        args = typing.get_args(tp)
        val_tp = args[1] if len(args) == 2 else Any
        return {
            k: _coerce(v, val_tp, lenient=lenient) for k, v in value.items()
        }
    return value


def _coerce_scalar(value: Any, tp: Any, lenient: bool) -> Any:
    """One arm per wire type: Quantity, nested dataclass, primitives."""
    if tp is Quantity:
        return Quantity.parse(str(value))
    if dataclasses.is_dataclass(tp):
        return from_dict(tp, value, lenient=lenient)
    if tp is float:
        if lenient and isinstance(value, str):
            # apiserver timestamps are RFC3339 strings; our model keeps
            # epoch floats
            return _rfc3339_to_epoch(value)
        return float(value)
    if tp is int:
        return int(value)
    if tp is str:
        return str(value)
    if tp is bool:
        return bool(value)
    return value


def _rfc3339_to_epoch(value: str) -> float:
    import datetime as _dt

    text = value.replace("Z", "+00:00")
    return _dt.datetime.fromisoformat(text).timestamp()


def _flatten_container_resources(data: Dict[str, Any]) -> Dict[str, Any]:
    """Real-apiserver dialect: requests/limits nest under `resources`
    (core/v1 ResourceRequirements); our manifest dialect flattens to
    `requests`. Lenient (apiserver-read) decode accepts both; strict
    user manifests still hard-error on `resources` so misconfig never
    silently drops limits/requests."""
    nested = data.get("resources") or {}
    data = {k: v for k, v in data.items() if k != "resources"}
    if "requests" not in data and "requests" in nested:
        data["requests"] = nested["requests"]
    return data


def _resolve_field(cls: Type, key: str, field_names, lenient: bool):
    """Manifest key -> dataclass field name; None = skip this key."""
    if key in ("apiVersion", "kind") and "api_version" not in field_names:
        return None  # envelope keys on top-level kinds
    field = _KEY_TO_FIELD.get(key, camel_to_snake(key))
    if field in field_names:
        return field
    if lenient:
        return None
    raise ValueError(
        f"unknown field {key!r} for {cls.__name__} "
        f"(known: {sorted(field_names)})"
    )


def from_dict(cls: Type, data: Dict[str, Any], lenient: bool = False):
    """Hydrate dataclass `cls` from a manifest-shaped dict (camelCase keys).
    Unknown keys are an error — same posture as apiserver structural schemas
    (silently dropped config is misconfig that 'works').

    lenient=True skips unknown keys instead: the decode posture for objects
    COMING FROM a real apiserver, which carry dozens of standard fields
    (managedFields, generation, pod volumes, ...) this model deliberately
    doesn't track. User manifests stay strict."""
    if data is None:
        data = {}
    if lenient and cls is Container and "resources" in data:
        data = _flatten_container_resources(data)
    types = _field_types(cls)
    field_names = {f.name for f in dataclasses.fields(cls)}
    kwargs = {}
    for key, value in data.items():
        field = _resolve_field(cls, key, field_names, lenient)
        if field is not None:
            kwargs[field] = _coerce(value, types[field], lenient=lenient)
    return cls(**kwargs)


_META_INTERNAL = ("uid", "resource_version", "creation_timestamp")


def to_dict(obj, top_level: bool = True) -> Dict[str, Any]:
    """Manifest-shaped dict (camelCase, defaults and None dropped)."""
    assert dataclasses.is_dataclass(obj)
    out: Dict[str, Any] = {}
    if top_level and type(obj).__name__ in KINDS:
        kind = type(obj).__name__
        out["apiVersion"] = (
            API_VERSION if kind in AUTOSCALING_KINDS else CORE_API_VERSION
        )
        out["kind"] = kind
    for f in dataclasses.fields(obj):
        value = getattr(obj, f.name)
        if isinstance(obj, ObjectMeta) and f.name in _META_INTERNAL:
            continue
        if value is None or value == {} or value == []:
            continue
        key = _FIELD_TO_KEY.get(f.name, snake_to_camel(f.name))
        out[key] = _value_to_plain(value)
    return out


def _value_to_plain(value: Any) -> Any:
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return to_dict(value, top_level=False)
    if isinstance(value, Quantity):
        return str(value)
    if isinstance(value, list):
        return [_value_to_plain(v) for v in value]
    if isinstance(value, dict):
        return {k: _value_to_plain(v) for k, v in value.items()}
    return value


def from_manifest(doc: Dict[str, Any], lenient: bool = False):
    """One YAML document (with apiVersion/kind envelope) -> API object."""
    kind = doc.get("kind")
    if kind not in KINDS:
        raise ValueError(f"unknown kind {kind!r} (known: {sorted(KINDS)})")
    api_version = doc.get("apiVersion", "")
    if kind in AUTOSCALING_KINDS:
        if api_version != API_VERSION:
            raise ValueError(
                f"unsupported apiVersion {api_version!r} for {kind}"
            )
    elif api_version not in ("", CORE_API_VERSION):
        # core kinds: absent is tolerated (test fixtures), wrong rejected —
        # same symmetry as the v1 stamp to_dict emits
        raise ValueError(f"unsupported apiVersion {api_version!r} for {kind}")
    body = {k: v for k, v in doc.items() if k not in ("apiVersion", "kind")}
    return from_dict(KINDS[kind], body, lenient=lenient)


def load_yaml(text: str) -> List[Any]:
    """All documents in a (possibly multi-doc) YAML string -> API objects."""
    return [
        from_manifest(doc)
        for doc in yaml.safe_load_all(text)
        if doc is not None
    ]


def load_yaml_file(path: str) -> List[Any]:
    with open(path) as f:
        return load_yaml(f.read())


def dump_yaml(*objects) -> str:
    return yaml.safe_dump_all(
        [to_dict(o) for o in objects], sort_keys=False, default_flow_style=False
    )
