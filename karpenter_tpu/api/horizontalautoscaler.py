"""HorizontalAutoscaler resource: HPA-v2beta2-shaped spec, status, behavior.

reference: pkg/apis/autoscaling/v1alpha1/horizontalautoscaler.go:33-275 and
horizontalautoscaler_status.go:22-103. The behavior helpers here are the
host-side scalar semantics (defaults via merge, select policy, scaling rules,
stabilization window); they double as the golden oracle for the batched
device decision kernel.
"""

from __future__ import annotations

import math
import time as _time
from dataclasses import dataclass, field
from typing import List, Optional

from karpenter_tpu.api.conditions import (
    ABLE_TO_SCALE,
    ACTIVE,
    SCALING_UNBOUNDED,
    Condition,
    ConditionManager,
)
from karpenter_tpu.api.core import ObjectMeta
from karpenter_tpu.utils.functional import merge_into
from karpenter_tpu.utils.log import invariant_violated, logger

# Metric target types (reference: horizontalautoscaler.go:190-197)
UTILIZATION = "Utilization"
VALUE = "Value"
AVERAGE_VALUE = "AverageValue"

# Select policies (reference: horizontalautoscaler.go:118-127)
MAX_POLICY_SELECT = "Max"
MIN_POLICY_SELECT = "Min"
DISABLED_POLICY_SELECT = "Disabled"

# Scaling policy types (reference: horizontalautoscaler.go:131-138)
COUNT_SCALING_POLICY = "Count"
PERCENT_SCALING_POLICY = "Percent"


@dataclass(slots=True)
class CrossVersionObjectReference:
    kind: str = ""
    name: str = ""
    api_version: str = ""


@dataclass(slots=True)
class MetricTarget:
    type: str = VALUE
    value: Optional[float] = None
    average_value: Optional[float] = None
    average_utilization: Optional[int] = None

    def target_value(self) -> float:
        for v in (self.value, self.average_value, self.average_utilization):
            if v is not None:
                return float(v)
        return 0.0


@dataclass(slots=True)
class PrometheusMetricSource:
    query: str = ""
    target: MetricTarget = field(default_factory=MetricTarget)


@dataclass(slots=True)
class Metric:
    """One-of metric source (reference: horizontalautoscaler.go:158-163)."""

    prometheus: Optional[PrometheusMetricSource] = None

    def get_target(self) -> MetricTarget:
        """reference: horizontalautoscaler.go:204-210"""
        if self.prometheus is not None:
            return self.prometheus.target
        invariant_violated(
            f"Unrecognized metric type while retrieving target for {self}"
        )


@dataclass(slots=True)
class ScalingPolicy:
    type: str = COUNT_SCALING_POLICY
    value: int = 0
    period_seconds: int = 0

    def validate(self) -> None:
        """reference: horizontalautoscaler.go:137-146 — value must be
        greater than zero; periodSeconds greater than zero and at most
        1800 (30 min). The reference documents these bounds but never
        enforces or applies them (autoscaler.go:186-189 TODO)."""
        if self.type not in (COUNT_SCALING_POLICY, PERCENT_SCALING_POLICY):
            raise ValueError(
                f"scaling policy type must be Count or Percent, got "
                f"{self.type!r}"
            )
        if self.value <= 0:
            raise ValueError(
                f"scaling policy value must be greater than zero, got "
                f"{self.value}"
            )
        if not 0 < self.period_seconds <= 1800:
            raise ValueError(
                "scaling policy periodSeconds must be in (0, 1800], got "
                f"{self.period_seconds}"
            )


@dataclass(slots=True)
class ScalingRules:
    stabilization_window_seconds: Optional[int] = None
    select_policy: Optional[str] = None
    policies: Optional[List[ScalingPolicy]] = None

    def within_stabilization_window(
        self, last_scale_time: Optional[float], now: Optional[float] = None
    ) -> bool:
        """reference: horizontalautoscaler.go:267-275"""
        if last_scale_time is None or self.stabilization_window_seconds is None:
            return False
        now = _time.time() if now is None else now
        return (now - last_scale_time) < float(self.stabilization_window_seconds)

    def allowed_change(
        self,
        current_replicas: int,
        last_scale_time: Optional[float],
        now: Optional[float] = None,
    ) -> Optional[int]:
        """Replica-change budget this direction's policies currently permit;
        None means unlimited. The scalar oracle for the device kernel's
        policy clamp (ops/decision.py) — the reference models these
        policies (horizontalautoscaler.go:111-146) but leaves application
        a TODO (autoscaler.go:186-189).

        Semantics with the state the CRD carries (LastScaleTime only): a
        policy budgets `value` replicas (Count) or
        ceil(max(current,1)*value/100) (Percent — floored at one replica's
        worth so a Percent-only policy can still escape zero replicas) per
        periodSeconds window; a scale event within the trailing period is
        conservatively assumed to have spent the budget, so the policy
        contributes 0 until its period elapses. Multiple policies combine
        under this direction's select policy (Max = most permissive, Min =
        most restrictive). No policies or no scale history = unlimited.
        """
        if not self.policies or last_scale_time is None:
            return None
        now = _time.time() if now is None else now
        elapsed = now - last_scale_time
        budgets = []
        for policy in self.policies:
            if elapsed < policy.period_seconds:
                budgets.append(0)
            elif policy.type == PERCENT_SCALING_POLICY:
                budgets.append(
                    int(
                        math.ceil(
                            max(current_replicas, 1) * policy.value / 100.0
                        )
                    )
                )
            else:
                budgets.append(policy.value)
        if self.select_policy == MIN_POLICY_SELECT:
            return min(budgets)
        return max(budgets)


@dataclass(slots=True)
class ForecastSpec:
    """Proactive-scaling behavior (docs/forecasting.md): forecast every
    metric `horizonSeconds` ahead and scale up to max(reactive,
    predicted). Scale-DOWN stays reactive-only by construction — a
    forecast can only raise the recommendation (the blend monotonicity
    the decision kernel pins), so a wrong forecast costs headroom,
    never availability.

    The reference has no predictive surface at all; this spec is the
    declarative face of the forecast subsystem (forecast/), evaluated
    for the whole fleet in one device dispatch per tick.
    """

    # how far ahead to forecast; a node group should set this at or
    # above its node-provisioning latency so capacity lands before the
    # load does
    horizon_seconds: float = 60.0
    # "holt-winters" (level/trend/seasonal) or "linear" (robust trend)
    model: str = "holt-winters"
    # confidence floor: blending auto-disables while the online skill
    # score (EWMA of horizon-ago forecast error; docs/forecasting.md
    # "Skill gating") sits below this
    min_skill: float = 0.25
    # dominant load period for the seasonal component (0 = no
    # seasonality; converted to ring-buffer sample slots at runtime)
    season_seconds: float = 0.0
    # Holt-Winters smoothing factors
    alpha: float = 0.5
    beta: float = 0.1
    gamma: float = 0.3
    # history samples required before the first forecast is trusted
    min_samples: int = 6

    def validate(self) -> None:
        if self.horizon_seconds <= 0:
            raise ValueError(
                f"forecast horizonSeconds must be > 0, got "
                f"{self.horizon_seconds}"
            )
        if self.model not in ("holt-winters", "linear"):
            raise ValueError(
                "forecast model must be holt-winters or linear, got "
                f"{self.model!r}"
            )
        if not 0.0 <= self.min_skill <= 1.0:
            raise ValueError(
                f"forecast minSkill must be in [0, 1], got {self.min_skill}"
            )
        if self.season_seconds < 0:
            raise ValueError(
                f"forecast seasonSeconds must be >= 0, got "
                f"{self.season_seconds}"
            )
        for field_name in ("alpha", "beta", "gamma"):
            v = getattr(self, field_name)
            if not 0.0 < v <= 1.0:
                raise ValueError(
                    f"forecast {field_name} must be in (0, 1], got {v}"
                )
        if self.min_samples < 2:
            raise ValueError(
                f"forecast minSamples must be >= 2, got {self.min_samples}"
            )


@dataclass(slots=True)
class SLOMetricTarget:
    """Per-metric SLO target: one entry per index of spec.metrics.

    The cost kernel already evaluates violation risk per metric and
    takes the WORST CASE across them (ops/cost.py `max` over the metric
    axis); this spec lets each metric declare its own per-replica
    capacity instead of sharing the single spec-wide targetValue — a
    queue-depth metric and a p99-latency proxy rarely mean the same
    thing by "one replica's worth"."""

    # per-replica capacity for the metric at the SAME INDEX in
    # spec.metrics; None falls back to the spec-wide targetValue, then
    # to the metric's own HPA target value
    target_value: Optional[float] = None

    def validate(self) -> None:
        if self.target_value is not None and self.target_value <= 0:
            raise ValueError(
                f"slo metrics targetValue must be > 0, got "
                f"{self.target_value}"
            )


@dataclass(slots=True)
class SLOSpec:
    """Cost- and SLO-aware scaling behavior (docs/cost.md): opt a
    HorizontalAutoscaler into the fleet's multi-objective refinement —
    the batched decide gains a second pass (ops/cost.py, ONE device
    dispatch for the whole fleet) that weighs expected hourly cost
    against SLO-violation risk, using the forecast distribution
    (spec.behavior.forecast) as the risk input when present.

    The reference has no cost surface at all; absent this spec the
    decision pipeline is bit-identical to the cost-blind one
    (wire-compat pinned in tests/test_cost.py).
    """

    # per-replica capacity the SLO deems safe, in metric units (e.g.
    # queue items one replica absorbs within the latency objective);
    # 0/None falls back to each metric's own HPA target value
    target_value: Optional[float] = None
    # $/hour penalty at full violation risk: the exchange rate between
    # the two objectives — 0 keeps decisions cost-visible (gauges) but
    # never moves them
    violation_cost_weight: float = 0.0
    # hard budget: candidates above floor(maxHourlyCost / unitCost)
    # replicas are trimmed (never below minReplicas); 0 = uncapped
    max_hourly_cost: float = 0.0
    # OPTIONAL per-metric targets, positional against spec.metrics:
    # entry j overrides targetValue for metric j (worst-case risk
    # across metrics still feeds the kernel). Shorter lists leave the
    # remaining metrics on the spec-wide fallback chain.
    metrics: Optional[List[SLOMetricTarget]] = None

    def target_for(self, metric_index: int) -> Optional[float]:
        """The per-replica SLO capacity for one metric: its per-metric
        entry when declared, else the spec-wide targetValue, else None
        (the engine then falls back to the metric's own HPA target)."""
        if self.metrics is not None and metric_index < len(self.metrics):
            per_metric = self.metrics[metric_index].target_value
            if per_metric is not None:
                return per_metric
        return self.target_value

    def validate(self) -> None:
        if self.target_value is not None and self.target_value <= 0:
            raise ValueError(
                f"slo targetValue must be > 0, got {self.target_value}"
            )
        if self.violation_cost_weight < 0:
            raise ValueError(
                "slo violationCostWeight must be >= 0, got "
                f"{self.violation_cost_weight}"
            )
        if self.max_hourly_cost < 0:
            raise ValueError(
                f"slo maxHourlyCost must be >= 0, got "
                f"{self.max_hourly_cost}"
            )
        for entry in self.metrics or []:
            entry.validate()


@dataclass(slots=True)
class Behavior:
    scale_up: Optional[ScalingRules] = None
    scale_down: Optional[ScalingRules] = None
    # opt-in predictive scaling (docs/forecasting.md)
    forecast: Optional[ForecastSpec] = None
    # opt-in cost- and SLO-aware refinement (docs/cost.md)
    slo: Optional[SLOSpec] = None

    def validate(self) -> None:
        for rules in (self.scale_up, self.scale_down):
            if rules is None:
                continue
            if rules.stabilization_window_seconds is not None and not (
                0 <= rules.stabilization_window_seconds <= 3600
            ):
                raise ValueError(
                    "stabilizationWindowSeconds must be in [0, 3600], "
                    f"got {rules.stabilization_window_seconds}"
                )
            for policy in rules.policies or []:
                policy.validate()
        for sub in (self.forecast, self.slo):
            if sub is not None:
                sub.validate()

    def scale_up_rules(self) -> ScalingRules:
        """Defaults: no stabilization, Max select (reference:
        horizontalautoscaler.go:249-256)."""
        rules = ScalingRules(
            stabilization_window_seconds=0, select_policy=MAX_POLICY_SELECT
        )
        return merge_into(rules, self.scale_up)

    def scale_down_rules(self) -> ScalingRules:
        """Defaults: 300s stabilization, Max select (reference:
        horizontalautoscaler.go:258-265)."""
        rules = ScalingRules(
            stabilization_window_seconds=300, select_policy=MAX_POLICY_SELECT
        )
        return merge_into(rules, self.scale_down)

    def get_scaling_rules(
        self, replicas: int, recommendations: List[int]
    ) -> ScalingRules:
        """Pick up/down/disabled rules from the recommendation direction
        (reference: horizontalautoscaler.go:240-247)."""
        if any(r > replicas for r in recommendations):
            return self.scale_up_rules()
        if any(r < replicas for r in recommendations):
            return self.scale_down_rules()
        return ScalingRules(select_policy=DISABLED_POLICY_SELECT)

    def apply_select_policy(self, replicas: int, recommendations: List[int]) -> int:
        """reference: horizontalautoscaler.go:226-238"""
        policy = self.get_scaling_rules(replicas, recommendations).select_policy
        if policy == MAX_POLICY_SELECT:
            return max(recommendations)
        if policy == MIN_POLICY_SELECT:
            return min(recommendations)
        if policy != DISABLED_POLICY_SELECT:
            # unknown policy: log loudly but keep current replicas, matching
            # the reference's non-fatal handling (horizontalautoscaler.go:236-237)
            logger().error("unknown select policy: %s", policy)
        return replicas


@dataclass(slots=True)
class MetricValueStatus:
    value: Optional[float] = None
    average_value: Optional[float] = None
    average_utilization: Optional[int] = None


@dataclass(slots=True)
class PrometheusMetricStatus:
    query: str = ""
    current: MetricValueStatus = field(default_factory=MetricValueStatus)


@dataclass(slots=True)
class MetricStatus:
    prometheus: Optional[PrometheusMetricStatus] = None


@dataclass(slots=True)
class HorizontalAutoscalerSpec:
    scale_target_ref: CrossVersionObjectReference = field(
        default_factory=CrossVersionObjectReference
    )
    min_replicas: int = 0
    max_replicas: int = 0
    metrics: List[Metric] = field(default_factory=list)
    behavior: Behavior = field(default_factory=Behavior)


@dataclass(slots=True)
class HorizontalAutoscalerStatus:
    last_scale_time: Optional[float] = None
    current_replicas: Optional[int] = None
    desired_replicas: Optional[int] = None
    current_metrics: List[MetricStatus] = field(default_factory=list)
    conditions: List[Condition] = field(default_factory=list)


# Pluggable validation hooks (same pattern as the queue-validator registry,
# api/metricsproducer.py): upper layers register checks the API layer cannot
# know about — e.g. the autoscaler's algorithm registry validates the
# `autoscaling.karpenter.sh/algorithm` annotation at admission. Keeps the
# api package dependency-free.
_validation_hooks = []


def register_validation_hook(hook) -> None:
    """hook(ha) raises ValueError to reject the object at admission."""
    _validation_hooks.append(hook)


@dataclass(slots=True)
class HorizontalAutoscaler:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: HorizontalAutoscalerSpec = field(default_factory=HorizontalAutoscalerSpec)
    status: HorizontalAutoscalerStatus = field(
        default_factory=HorizontalAutoscalerStatus
    )

    KIND = "HorizontalAutoscaler"

    def status_conditions(self) -> ConditionManager:
        return ConditionManager(
            [ACTIVE, ABLE_TO_SCALE, SCALING_UNBOUNDED], self.status.conditions
        )

    def validate(self) -> None:
        for hook in _validation_hooks:
            hook(self)
        if self.spec.max_replicas < self.spec.min_replicas:
            raise ValueError(
                "maxReplicas cannot be less than minReplicas "
                f"({self.spec.max_replicas} < {self.spec.min_replicas})"
            )
        self.spec.behavior.validate()

    def default(self) -> None:
        """reference: horizontalautoscaler_defaults.go (no-op)."""
