"""MetricsProducer resource: one-of spec for scaling-signal producers.

reference: pkg/apis/autoscaling/v1alpha1/metricsproducer.go:22-122,
metricsproducer_status.go:24-79, metricsproducer_validation.go:47-166.
"""

from __future__ import annotations

import re
import zoneinfo
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karpenter_tpu.api.conditions import ACTIVE, Condition, ConditionManager
from karpenter_tpu.api.core import ObjectMeta
from karpenter_tpu.constraints.spec import (
    ConstraintGroup,
    validate_constraints,
)

AWS_SQS_QUEUE_TYPE = "AWSSQSQueue"
# TPU-native queue type: a pluggable in-cluster work queue (the reference's
# AWSSQSQueue analog for non-AWS deployments).
FAKE_QUEUE_TYPE = "FakeQueue"


@dataclass(slots=True)
class ReservedCapacitySpec:
    node_selector: Dict[str, str] = field(default_factory=dict)

    def validate(self) -> None:
        """reference: metricsproducer_validation.go:90-95"""
        if len(self.node_selector) != 1:
            raise ValueError(
                "reserved capacity must refer to exactly one node selector"
            )


@dataclass(slots=True)
class PendingCapacitySpec:
    node_selector: Dict[str, str] = field(default_factory=dict)
    # scale-from-zero: when node_selector matches NO nodes, profile the
    # group from the cloud provider's NodeTemplate for this
    # ScalableNodeGroup (same namespace). Live nodes always win —
    # observed truth over declared shape.
    node_group_ref: str = ""
    # declarative constraint groups (karpenter_tpu/constraints): pod
    # anti-affinity / compact placement / zone spread / reservation
    # claims compiled into the batched solve's masked integer operands.
    # Empty = today's unconstrained wire, byte-identical.
    constraints: List[ConstraintGroup] = field(default_factory=list)

    def validate(self) -> None:
        """reference: metricsproducer_validation.go:85-87, plus the
        constraint-group rules (constraints/spec.py)."""
        if self.constraints:
            validate_constraints(self.constraints)


@dataclass(slots=True)
class QueueSpec:
    type: str = ""
    id: str = ""


# Element-at-a-time regexes (reference: metricsproducer_validation.go:98-110)
_WEEKDAY_RE = re.compile(
    r"^((sun(day)?|0|7)|(mon(day)?|1)|(tue(sday)?|2)|(wed(nesday)?|3)"
    r"|(thu(rsday)?|4)|(fri(day)?|5)|(sat(urday)?|6))$"
)
_MONTH_RE = re.compile(
    r"^((jan(uary)?|1)|(feb(ruary)?|2)|(mar(ch)?|3)|(apr(il)?|4)|(may|5)"
    r"|(june?|6)|(july?|7)|(aug(ust)?|8)|(sep(tember)?|9)|((oct(ober)?)|(10))"
    r"|(nov(ember)?|(11))|(dec(ember)?|(12)))$"
)
_NUMBER_RE = re.compile(r"^\d+$")

# Numeric bounds per field, enforced at admission so a spec that validates
# can always be evaluated by utils.cron (the reference validated only \d+ and
# let robfig/cron reject out-of-range values at reconcile time — a spec
# accepted by its webhook could still fail every reconcile).
_FIELD_BOUNDS = {"days": (1, 31), "hours": (0, 23), "minutes": (0, 59)}


def _validate_field(value: Optional[str], pattern: re.Pattern, name: str) -> None:
    if value is None:
        return
    for elem in value.split(","):
        elem = elem.strip().lower()
        if not elem or not pattern.match(elem):
            raise ValueError(f"unable to parse: {value}")
        if name in _FIELD_BOUNDS and elem.isdigit():
            lo, hi = _FIELD_BOUNDS[name]
            if not lo <= int(elem) <= hi:
                raise ValueError(
                    f"{name} element {elem} out of range [{lo},{hi}]"
                )


@dataclass(slots=True)
class Pattern:
    """Strongly-typed crontab (reference: metricsproducer.go:70-83)."""

    minutes: Optional[str] = None
    hours: Optional[str] = None
    days: Optional[str] = None
    months: Optional[str] = None
    weekdays: Optional[str] = None

    def validate(self) -> None:
        _validate_field(self.weekdays, _WEEKDAY_RE, "weekdays")
        _validate_field(self.months, _MONTH_RE, "months")
        _validate_field(self.days, _NUMBER_RE, "days")
        _validate_field(self.hours, _NUMBER_RE, "hours")
        _validate_field(self.minutes, _NUMBER_RE, "minutes")

    def to_cron(self):
        """Compile to a utils.cron.Cron (reference: crontabs.go:33-49)."""
        from karpenter_tpu.utils.cron import Cron

        return Cron(
            minutes=self.minutes,
            hours=self.hours,
            days=self.days,
            months=self.months,
            weekdays=self.weekdays,
        )


@dataclass(slots=True)
class ScheduledBehavior:
    replicas: int = 0
    start: Optional[Pattern] = None
    end: Optional[Pattern] = None


@dataclass(slots=True)
class ScheduleSpec:
    behaviors: List[ScheduledBehavior] = field(default_factory=list)
    timezone: Optional[str] = None
    default_replicas: int = 0

    def validate(self) -> None:
        """reference: metricsproducer_validation.go:61-82"""
        for behavior in self.behaviors:
            for which, pattern in (("start", behavior.start), ("end", behavior.end)):
                if pattern is None:
                    raise ValueError(f"{which} pattern is required")
                try:
                    pattern.validate()
                except ValueError as e:
                    raise ValueError(f"{which} pattern could not be parsed, {e}")
            if behavior.replicas < 0:
                raise ValueError("behavior.replicas cannot be negative")
        if self.default_replicas < 0:
            raise ValueError("defaultReplicas cannot be negative")
        if self.timezone is not None:
            try:
                zoneinfo.ZoneInfo(self.timezone)
            except (zoneinfo.ZoneInfoNotFoundError, ValueError):
                raise ValueError("timezone region could not be parsed")


@dataclass(slots=True)
class MetricsProducerSpec:
    pending_capacity: Optional[PendingCapacitySpec] = None
    queue: Optional[QueueSpec] = None
    reserved_capacity: Optional[ReservedCapacitySpec] = None
    schedule: Optional[ScheduleSpec] = None


# Pluggable per-cloud queue validators
# (reference: metricsproducer_validation.go:146-166)
_queue_validators = {}


def register_queue_validator(queue_type: str, validator) -> None:
    _queue_validators[queue_type] = validator


def validate_queue(spec: QueueSpec) -> None:
    validator = _queue_validators.get(spec.type)
    if validator is None:
        raise ValueError(f"unexpected queue type {spec.type}")
    validator(spec)


@dataclass(slots=True)
class QueueStatus:
    length: int = 0
    oldest_message_age_seconds: int = 0


@dataclass(slots=True)
class ScheduledCapacityStatus:
    current_value: Optional[int] = None
    next_value_time: Optional[float] = None
    next_value: Optional[int] = None


@dataclass(slots=True)
class PendingCapacityStatus:
    """Per-node-group pending-pods signal. The reference's status struct is
    empty (metricsproducer_status.go:44-45); we surface the solver outputs."""

    pending_pods: int = 0  # pending pods this group would absorb
    additional_nodes_needed: int = 0  # shelf-BFD node count for those pods
    lp_lower_bound: int = 0  # LP-relaxation lower bound (diagnostic)
    unschedulable_pods: int = 0  # cluster-wide: pods no group can take


@dataclass(slots=True)
class MetricsProducerStatus:
    pending_capacity: Optional[PendingCapacityStatus] = None
    queue: Optional[QueueStatus] = None
    reserved_capacity: Dict[str, str] = field(default_factory=dict)
    scheduled_capacity: Optional[ScheduledCapacityStatus] = None
    conditions: List[Condition] = field(default_factory=list)


@dataclass(slots=True)
class MetricsProducer:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: MetricsProducerSpec = field(default_factory=MetricsProducerSpec)
    status: MetricsProducerStatus = field(default_factory=MetricsProducerStatus)

    KIND = "MetricsProducer"

    def status_conditions(self) -> ConditionManager:
        return ConditionManager([ACTIVE], self.status.conditions)

    def validate(self) -> None:
        """One-of dispatch (reference: metricsproducer_validation.go:47-58)."""
        for validator in (
            self.spec.pending_capacity,
            self.spec.reserved_capacity,
            self.spec.schedule,
        ):
            if validator is not None:
                validator.validate()
                return

    def default(self) -> None:
        pass
