"""PoolGroup resource: coordinated scaling across interdependent pools.

The reference plane (and PRs 1-19 here) scales each HorizontalAutoscaler
in isolation. Disaggregated serving workloads — prefill vs decode pools,
router vs worker — are coupled: each pool's useful capacity depends on
its siblings', and per-pool loops oscillate and strand capacity ("Taming
the Chaos", PAPERS.md). A PoolGroup names member HorizontalAutoscalers
and the coupling between them:

- cross-pool ratio bands as EXACT integer ratios (decode:prefill between
  2:1 and 4:1) — integers because the joint kernel enforces them by
  int32 cross-multiplication, bit-identical on device and host
- a shared hourly budget across the whole group
- per-pool bound tightening and capacity-tier preferences (a spot-heavy
  pool can be made cheaper-on-paper via tierPenalty on its siblings)

The joint allocation itself is ops/poolgroup.py (one batched device
dispatch for every group in the fleet); this module is only the
declarative face plus admission validation. The reference has no such
surface at all.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from karpenter_tpu.api.conditions import ACTIVE, Condition, ConditionManager
from karpenter_tpu.api.core import ObjectMeta

# Mirrors of the kernel's static limits (ops/poolgroup.py MAX_POOLS /
# RATIO_SLOTS / RATIO_BOUND — asserted equal at engine import so they
# cannot drift; duplicated because the api package must not import jax).
MAX_POOLS = 4
RATIO_SLOTS = 4
RATIO_BOUND = 1024


@dataclass(slots=True)
class PoolMember:
    """One member pool: a HorizontalAutoscaler in the group's namespace.

    minReplicas/maxReplicas optionally TIGHTEN the member HA's own
    bounds for joint allocation (they can never widen them); tierPenalty
    is a $/hour-per-replica score penalty folded into the joint
    objective — it steers the allocator toward preferred capacity tiers
    without touching the real-dollar budget math."""

    name: str = ""
    # freeform role label ratios may reference instead of the name
    # (e.g. "prefill", "decode") — purely descriptive aliasing
    role: str = ""
    min_replicas: Optional[int] = None
    max_replicas: Optional[int] = None
    tier_penalty: float = 0.0

    def validate(self) -> None:
        if not self.name:
            raise ValueError("pool member name must be set")
        if self.tier_penalty < 0:
            raise ValueError(
                f"pool {self.name!r} tierPenalty must be >= 0, got "
                f"{self.tier_penalty}"
            )
        if self.min_replicas is not None and self.min_replicas < 0:
            raise ValueError(
                f"pool {self.name!r} minReplicas must be >= 0, got "
                f"{self.min_replicas}"
            )
        if (
            self.min_replicas is not None
            and self.max_replicas is not None
            and self.max_replicas < self.min_replicas
        ):
            raise ValueError(
                f"pool {self.name!r} maxReplicas cannot be less than "
                f"minReplicas ({self.max_replicas} < {self.min_replicas})"
            )


@dataclass(slots=True)
class RatioConstraint:
    """numerator:denominator must stay inside the declared band:

        minNumerator/minDenominator <= num/den <= maxNumerator/maxDenominator

    minNumerator=0 disables the lower bound; maxNumerator=0 (with
    maxDenominator=0) disables the upper. Integers are capped at 1024 so
    the kernel's int32 cross products can never overflow."""

    numerator: str = ""  # member pool name or role
    denominator: str = ""
    min_numerator: int = 0
    min_denominator: int = 1
    max_numerator: int = 0
    max_denominator: int = 0

    def validate(self, pool_keys) -> None:
        for side in (self.numerator, self.denominator):
            if side not in pool_keys:
                raise ValueError(
                    f"ratio references unknown pool {side!r} "
                    f"(declared: {sorted(pool_keys)})"
                )
        if self.numerator == self.denominator:
            raise ValueError(
                f"ratio numerator and denominator must differ, both are "
                f"{self.numerator!r}"
            )
        for name in (
            "min_numerator",
            "min_denominator",
            "max_numerator",
            "max_denominator",
        ):
            v = getattr(self, name)
            if not 0 <= v <= RATIO_BOUND:
                raise ValueError(
                    f"ratio {name} must be in [0, {RATIO_BOUND}], got {v}"
                )
        if self.min_numerator > 0 and self.min_denominator < 1:
            raise ValueError(
                "ratio minDenominator must be >= 1 when minNumerator is set"
            )
        upper = self.max_numerator > 0
        if upper and self.max_denominator < 1:
            raise ValueError(
                "ratio maxDenominator must be >= 1 when maxNumerator is set"
            )
        if (
            upper
            and self.min_numerator > 0
            and self.min_numerator * self.max_denominator
            > self.max_numerator * self.min_denominator
        ):
            raise ValueError(
                "ratio band is empty: "
                f"{self.min_numerator}:{self.min_denominator} > "
                f"{self.max_numerator}:{self.max_denominator}"
            )


@dataclass(slots=True)
class PoolGroupSpec:
    pools: List[PoolMember] = field(default_factory=list)
    ratios: List[RatioConstraint] = field(default_factory=list)
    # shared budget across all member pools, $/hour; 0 = uncapped
    max_hourly_cost: float = 0.0


@dataclass(slots=True)
class PoolGroupStatus:
    # joint point satisfied every declared constraint last tick (False
    # while the solver serves the degraded independent ladder, or when
    # even the repair selection cannot reach the band this tick)
    coordinated: Optional[bool] = None
    # summed pool spend at the selected joint point, $/hour
    expected_hourly: Optional[float] = None
    conditions: List[Condition] = field(default_factory=list)


@dataclass(slots=True)
class PoolGroup:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PoolGroupSpec = field(default_factory=PoolGroupSpec)
    status: PoolGroupStatus = field(default_factory=PoolGroupStatus)

    KIND = "PoolGroup"

    def status_conditions(self) -> ConditionManager:
        return ConditionManager([ACTIVE], self.status.conditions)

    def validate(self) -> None:
        pools = self.spec.pools
        if not 2 <= len(pools) <= MAX_POOLS:
            raise ValueError(
                f"a PoolGroup needs 2..{MAX_POOLS} pools, got {len(pools)}"
            )
        names = [p.name for p in pools]
        if len(set(names)) != len(names):
            raise ValueError(f"pool names must be unique, got {names}")
        keys = set(names) | {p.role for p in pools if p.role}
        roles = [p.role for p in pools if p.role]
        if len(set(roles)) != len(roles):
            raise ValueError(f"pool roles must be unique, got {roles}")
        for pool in pools:
            pool.validate()
        if len(self.spec.ratios) > RATIO_SLOTS:
            raise ValueError(
                f"a PoolGroup supports at most {RATIO_SLOTS} ratio "
                f"constraints, got {len(self.spec.ratios)}"
            )
        for ratio in self.spec.ratios:
            ratio.validate(keys)
        if self.spec.max_hourly_cost < 0:
            raise ValueError(
                f"maxHourlyCost must be >= 0, got {self.spec.max_hourly_cost}"
            )

    def default(self) -> None:
        pass

    def member_index(self, key: str) -> int:
        """Position of the pool a ratio side references (name or role)."""
        for i, pool in enumerate(self.spec.pools):
            if pool.name == key or (pool.role and pool.role == key):
                return i
        raise KeyError(key)
