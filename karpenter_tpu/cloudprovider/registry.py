"""Runtime provider selection.

reference: pkg/cloudprovider/registry/{aws,fake}.go — the reference selects
its provider at COMPILE time via Go build tags (`-tags=aws`). The TPU build
selects at runtime by name (env KARPENTER_CLOUD_PROVIDER or explicit arg),
defaulting to the not-implemented fake exactly like the `!aws` build.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional

from karpenter_tpu.cloudprovider import Options
from karpenter_tpu.cloudprovider.fake import FakeFactory

_providers: Dict[str, Callable[[Options], object]] = {}


def register_provider(name: str, factory_fn: Callable[[Options], object]) -> None:
    _providers[name] = factory_fn


def new_factory(options: Optional[Options] = None, provider: Optional[str] = None):
    options = options or Options()
    name = provider or os.environ.get("KARPENTER_CLOUD_PROVIDER", "")
    if not name:
        return FakeFactory.not_implemented()
    factory_fn = _providers.get(name)
    if factory_fn is None:
        raise ValueError(
            f"unknown cloud provider {name!r}; registered: {sorted(_providers)}"
        )
    return factory_fn(options)


def _aws_factory(options: Options):
    from karpenter_tpu.cloudprovider.aws import AWSFactory

    # registry selection = the operator explicitly chose this provider, so
    # live SDK clients are wanted (reference: factory.go builds a session)
    return AWSFactory(options, sdk_autobind=True)


def _tpu_factory(options: Options):
    from karpenter_tpu.cloudprovider.tpu import TPUFactory

    return TPUFactory(options, sdk_autobind=True)


register_provider("fake", lambda options: FakeFactory(options))
register_provider("aws", _aws_factory)
register_provider("tpu", _tpu_factory)
