"""boto3 bindings for the AWS provider's duck-typed API seams.

reference: pkg/cloudprovider/aws/factory.go:41-76 — the reference
constructs a live SDK session at factory build time (region discovered
from the EC2 metadata service) and hands service clients to the node-group
and queue types. Here the SPI boundary is the Protocol trio in aws.py
(AutoscalingAPI / EKSAPI / SQSAPI); this module is the production binding:
thin adapters that translate call shapes and map botocore failures into
AWSAPIError so the provider's transient/terminal taxonomy (aws.py
transient_error, reference error.go:28-55) applies unchanged.

The SDK is OPTIONAL. Nothing here imports boto3 at module import; `bind`
returns None when boto3 is missing or a session cannot be built, and
AWSFactory then falls back to the fail-with-guidance stubs exactly as
before. Tests stub `boto3`/`botocore` in sys.modules — the adapters are
exercised against recorded call/response shapes, not the network.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from karpenter_tpu.cloudprovider.aws import AWSAPIError
from karpenter_tpu.utils.log import logger

# EC2 IMDSv2: the region source of last resort, like the reference's
# ec2metadata lookup (factory.go:71-76). Short timeouts: off-EC2 the
# link-local address is unroutable and must fail fast, not hang startup.
_IMDS_BASE = "http://169.254.169.254"
_IMDS_TIMEOUT = 2.0


def sdk_available() -> bool:
    import importlib.util

    return importlib.util.find_spec("boto3") is not None


def resolve_region(session=None) -> Optional[str]:
    """Region discovery order: explicit env, SDK config chain (profile /
    shared config), then EC2 instance metadata. None when undiscoverable —
    the caller degrades to the guidance stub rather than guessing."""
    region = os.environ.get("AWS_REGION") or os.environ.get(
        "AWS_DEFAULT_REGION"
    )
    if region:
        return region
    if session is not None and getattr(session, "region_name", None):
        return session.region_name
    return _imds_region()


def _imds_region() -> Optional[str]:
    import urllib.error
    import urllib.request

    try:
        token_req = urllib.request.Request(
            f"{_IMDS_BASE}/latest/api/token",
            method="PUT",
            headers={"X-aws-ec2-metadata-token-ttl-seconds": "60"},
        )
        with urllib.request.urlopen(token_req, timeout=_IMDS_TIMEOUT) as r:
            token = r.read().decode()
        region_req = urllib.request.Request(
            f"{_IMDS_BASE}/latest/meta-data/placement/region",
            headers={"X-aws-ec2-metadata-token": token},
        )
        with urllib.request.urlopen(region_req, timeout=_IMDS_TIMEOUT) as r:
            return r.read().decode().strip() or None
    except (urllib.error.URLError, OSError, ValueError):
        return None


def _translate_call(fn, *args, **kwargs):
    """Run one SDK call, mapping botocore failures into AWSAPIError so the
    provider's classifier (transient_error) sees the service error code;
    connection-level failures carry no code and are forced retryable."""
    import botocore.exceptions as bex

    try:
        return fn(*args, **kwargs)
    except bex.ClientError as e:
        error = (getattr(e, "response", None) or {}).get("Error", {})
        raise AWSAPIError(
            error.get("Message") or str(e), code=error.get("Code", "")
        ) from e
    except (
        # the connection-failure base classes, not a leaf enumeration:
        # ConnectionClosedError, ProxyConnectionError, SSLError,
        # ReadTimeoutError etc. all subclass one of these two — any of
        # them classified terminal would stop the controller requeueing
        # over a network blip
        bex.ConnectionError,
        bex.HTTPClientError,
    ) as e:
        raise AWSAPIError(str(e), retryable=True) from e


# Cluster-autoscaler's ASG tag convention for declaring the shape of
# scale-from-zero nodes; the standard way an operator annotates an ASG
# with what its nodes will look like before any exist.
_CAS_LABEL_TAG = "k8s.io/cluster-autoscaler/node-template/label/"
_CAS_TAINT_TAG = "k8s.io/cluster-autoscaler/node-template/taint/"


def _instance_type_allocatable(ec2, instance_type: str) -> Dict[str, str]:
    """DescribeInstanceTypes -> allocatable resource strings. Capacity,
    not true allocatable (kubelet reservations are deployment-specific);
    the solver treats templates as optimistic upper bounds already."""
    out = _translate_call(
        ec2.describe_instance_types, InstanceTypes=[instance_type]
    )
    infos = out.get("InstanceTypes") or []
    if not infos:
        return {}
    info = infos[0]
    allocatable: Dict[str, str] = {}
    vcpus = (info.get("VCpuInfo") or {}).get("DefaultVCpus")
    if vcpus:
        allocatable["cpu"] = str(vcpus)
    mib = (info.get("MemoryInfo") or {}).get("SizeInMiB")
    if mib:
        allocatable["memory"] = f"{mib}Mi"
    gpus = sum(
        g.get("Count", 0) for g in (info.get("GpuInfo") or {}).get("Gpus", [])
    )
    if gpus:
        allocatable["nvidia.com/gpu"] = str(gpus)
    return allocatable


def _labels_taints_from_tags(tags: Dict[str, str]):
    """Decode the cluster-autoscaler node-template tag convention into
    (labels, taints)."""
    labels: Dict[str, str] = {}
    taints = []
    for key, value in tags.items():
        if key.startswith(_CAS_LABEL_TAG):
            labels[key[len(_CAS_LABEL_TAG):]] = value
        elif key.startswith(_CAS_TAINT_TAG):
            taint_value, _, effect = value.partition(":")
            taints.append(
                {
                    "key": key[len(_CAS_TAINT_TAG):],
                    "value": taint_value,
                    "effect": effect,
                }
            )
    return labels, taints


class Boto3AutoscalingClient:
    """AutoscalingAPI over boto3 autoscaling (+ ec2 for templates)."""

    def __init__(self, autoscaling, ec2=None):
        self._autoscaling = autoscaling
        self._ec2 = ec2

    def describe_auto_scaling_groups(
        self, names: List[str], max_records: int
    ) -> List[dict]:
        out = _translate_call(
            self._autoscaling.describe_auto_scaling_groups,
            AutoScalingGroupNames=list(names),
            MaxRecords=max_records,
        )
        return [
            {
                "name": g.get("AutoScalingGroupName", ""),
                "desired_capacity": g.get("DesiredCapacity"),
                "instances": [
                    {
                        "health_status": i.get("HealthStatus", ""),
                        "lifecycle_state": i.get("LifecycleState", ""),
                    }
                    for i in g.get("Instances", [])
                ],
                "tags": {
                    t.get("Key", ""): t.get("Value", "")
                    for t in g.get("Tags", [])
                },
                "launch_template": g.get("LaunchTemplate")
                or (g.get("MixedInstancesPolicy") or {})
                .get("LaunchTemplate", {})
                .get("LaunchTemplateSpecification"),
                "overrides": (g.get("MixedInstancesPolicy") or {})
                .get("LaunchTemplate", {})
                .get("Overrides", []),
            }
            for g in out.get("AutoScalingGroups", [])
        ]

    def update_auto_scaling_group(
        self, name: str, desired_capacity: int
    ) -> None:
        _translate_call(
            self._autoscaling.update_auto_scaling_group,
            AutoScalingGroupName=name,
            DesiredCapacity=desired_capacity,
        )

    def _launch_template_instance_type(self, spec: dict) -> Optional[str]:
        """Instance type from a LaunchTemplateSpecification. Specs carry
        EITHER an id or a name (both shapes are returned by AWS); passing
        a None id would be a ParamValidationError."""
        if spec.get("LaunchTemplateId"):
            lt_ref = {"LaunchTemplateId": spec["LaunchTemplateId"]}
        elif spec.get("LaunchTemplateName"):
            lt_ref = {"LaunchTemplateName": spec["LaunchTemplateName"]}
        else:
            return None
        versions = _translate_call(
            self._ec2.describe_launch_template_versions,
            Versions=[spec.get("Version", "$Default")],
            **lt_ref,
        ).get("LaunchTemplateVersions") or []
        if not versions:
            return None
        return versions[0].get("LaunchTemplateData", {}).get("InstanceType")

    def describe_node_template(self, name: str) -> Optional[dict]:
        """Scale-from-zero template: instance type from the ASG's launch
        template (override first — mixed policies list the real types
        there), sized via DescribeInstanceTypes; labels/taints from the
        cluster-autoscaler node-template tag convention."""
        groups = self.describe_auto_scaling_groups([name], 1)
        if len(groups) != 1:
            return None
        group = groups[0]
        instance_type = None
        for override in group["overrides"]:
            if override.get("InstanceType"):
                instance_type = override["InstanceType"]
                break
        if instance_type is None and group["launch_template"] and self._ec2:
            instance_type = self._launch_template_instance_type(
                group["launch_template"]
            )
        if instance_type is None or self._ec2 is None:
            return None
        labels, taints = _labels_taints_from_tags(group["tags"])
        allocatable = _instance_type_allocatable(self._ec2, instance_type)
        if not allocatable:
            return None
        labels.setdefault("node.kubernetes.io/instance-type", instance_type)
        return {
            "allocatable": allocatable,
            "labels": labels,
            "taints": taints,
        }


class Boto3EKSClient:
    """EKSAPI over boto3 eks (+ ec2 for template sizing)."""

    def __init__(self, eks, ec2=None):
        self._eks = eks
        self._ec2 = ec2

    def update_nodegroup_config(
        self, cluster_name: str, nodegroup_name: str, desired_size: int
    ) -> None:
        _translate_call(
            self._eks.update_nodegroup_config,
            clusterName=cluster_name,
            nodegroupName=nodegroup_name,
            scalingConfig={"desiredSize": desired_size},
        )

    def describe_node_template(
        self, cluster_name: str, nodegroup_name: str
    ) -> Optional[dict]:
        nodegroup = _translate_call(
            self._eks.describe_nodegroup,
            clusterName=cluster_name,
            nodegroupName=nodegroup_name,
        ).get("nodegroup") or {}
        instance_types = nodegroup.get("instanceTypes") or []
        if not instance_types or self._ec2 is None:
            return None
        allocatable = _instance_type_allocatable(
            self._ec2, instance_types[0]
        )
        if not allocatable:
            return None
        labels = dict(nodegroup.get("labels") or {})
        labels.setdefault(
            "node.kubernetes.io/instance-type", instance_types[0]
        )
        return {
            "allocatable": allocatable,
            "labels": labels,
            # EKS spells effects NO_SCHEDULE etc.; node_template_from_raw
            # translates the enum dialect
            "taints": [
                {
                    "key": t.get("key", ""),
                    "value": t.get("value", ""),
                    "effect": t.get("effect", ""),
                }
                for t in nodegroup.get("taints") or []
            ],
        }


class Boto3SQSClient:
    """SQSAPI over boto3 sqs."""

    def __init__(self, sqs):
        self._sqs = sqs

    def get_queue_url(self, queue_name: str, account_id: str) -> str:
        return _translate_call(
            self._sqs.get_queue_url,
            QueueName=queue_name,
            QueueOwnerAWSAccountId=account_id,
        )["QueueUrl"]

    def get_queue_attributes(
        self, queue_url: str, attribute_names: List[str]
    ) -> Dict[str, str]:
        return (
            _translate_call(
                self._sqs.get_queue_attributes,
                QueueUrl=queue_url,
                AttributeNames=list(attribute_names),
            ).get("Attributes")
            or {}
        )

    def receive_message(
        self,
        queue_url: str,
        attribute_names: List[str],
        max_number_of_messages: int,
        visibility_timeout: int,
    ) -> List[Dict]:
        return (
            _translate_call(
                self._sqs.receive_message,
                QueueUrl=queue_url,
                AttributeNames=list(attribute_names),
                MaxNumberOfMessages=max_number_of_messages,
                VisibilityTimeout=visibility_timeout,
            ).get("Messages")
            or []
        )


# One session/region resolution (and one service client per name) per
# process: binding is called once per seam from AWSFactory.__init__, and
# both Session construction and client construction re-read config files /
# re-resolve endpoints each time. The ec2 client in particular is shared
# by the autoscaling and eks seams.
_bind_lock = threading.Lock()
_session_cache: Optional[tuple] = None  # (session, region) or (None, None)
_client_cache: Dict[str, object] = {}


def _session_and_region():
    global _session_cache
    with _bind_lock:
        if _session_cache is None:
            import boto3

            session = boto3.session.Session()
            region = resolve_region(session)
            if region is None:
                logger().warning(
                    "aws sdk present but no region discoverable "
                    "(env/config/IMDS); AWS clients stay unbound"
                )
                _session_cache = (None, None)
            else:
                _session_cache = (session, region)
        return _session_cache


def _service_client(session, region, name: str):
    with _bind_lock:
        client = _client_cache.get(name)
        if client is None:
            client = _client_cache[name] = session.client(
                name, region_name=region
            )
        return client


def bind(service: str):
    """Build the production client for one API seam, or None when the SDK
    is missing / unconfigured (caller falls back to the guidance stub).
    Never raises for a known seam: provider construction must succeed
    without AWS access — the control plane may be scaling only non-AWS
    resources. (botocore's InvalidRegionError subclasses ValueError, so
    the unknown-seam check sits OUTSIDE the degrade-to-None handler.)"""
    if service not in ("autoscaling", "eks", "sqs"):
        raise ValueError(f"unknown AWS service seam {service!r}")
    if not sdk_available():
        return None
    try:
        session, region = _session_and_region()
        if session is None:
            return None
        if service == "autoscaling":
            return Boto3AutoscalingClient(
                _service_client(session, region, "autoscaling"),
                _service_client(session, region, "ec2"),
            )
        if service == "eks":
            return Boto3EKSClient(
                _service_client(session, region, "eks"),
                _service_client(session, region, "ec2"),
            )
        return Boto3SQSClient(_service_client(session, region, "sqs"))
    except Exception as e:  # noqa: BLE001 — constructing clients must not
        # take down factory construction; actuation will fail with guidance
        logger().warning("aws sdk binding for %s failed: %s", service, e)
        return None


def reset_binding_cache() -> None:
    """Test hook: forget the cached session/region and clients."""
    global _session_cache
    with _bind_lock:
        _session_cache = None
        _client_cache.clear()
