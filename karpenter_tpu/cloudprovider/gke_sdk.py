"""google-cloud bindings for the TPU provider's duck-typed API seams.

reference: the AWS analog is pkg/cloudprovider/aws/factory.go:41-76 (live
SDK session built at factory construction). The TPU provider's seams are
ContainerAPI (GKE node pools, tpu.py:63-77) and PubSubMetricsAPI (queue
depth/age via Cloud Monitoring, tpu.py:221-230); this module is their
production binding over google-cloud-container / google-cloud-monitoring.

Both SDKs are OPTIONAL. Nothing imports google.cloud at module import;
`bind_container` / `bind_pubsub_metrics` return None when the library is
missing or client construction fails, and TPUFactory keeps its
fail-with-guidance stubs. google.api_core errors are translated into the
controller taxonomy (RetryableError with the transport's retryability) so
TPUPodSlicePool's `except RetryableError: raise` path preserves terminal
vs transient classification instead of blanket-transient wrapping.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from karpenter_tpu.controllers.errors import RetryableError
from karpenter_tpu.utils.log import logger


def container_sdk_available() -> bool:
    import importlib.util

    return importlib.util.find_spec("google.cloud.container_v1") is not None


def monitoring_sdk_available() -> bool:
    import importlib.util

    return importlib.util.find_spec("google.cloud.monitoring_v3") is not None


# google.api_core exception class names whose failures clear on retry;
# everything else (NotFound, PermissionDenied, InvalidArgument, ...) is
# terminal and must NOT keep the controller requeueing forever.
_RETRYABLE_GOOGLE_ERRORS = frozenset(
    {
        "ServiceUnavailable",
        "DeadlineExceeded",
        "TooManyRequests",
        "InternalServerError",
        "Aborted",
        "GatewayTimeout",
        "RetryError",
    }
)


def _translate_call(fn, *args, **kwargs):
    """Run one google-cloud call, mapping google.api_core exceptions into
    RetryableError with honest retryability and the exception class name
    as the condition code."""
    try:
        import google.api_core.exceptions as gex
    except ImportError:  # SDK half-installed: classify nothing
        return fn(*args, **kwargs)

    try:
        return fn(*args, **kwargs)
    except gex.GoogleAPIError as e:
        # GoogleAPIError, not GoogleAPICallError: RetryError (client-side
        # retry deadline exhausted) subclasses only the former and must
        # still be classified — it is in the retryable set
        name = type(e).__name__
        wrapped = RetryableError(
            str(e), code=name, retryable=name in _RETRYABLE_GOOGLE_ERRORS
        )
        raise wrapped from e
    except (ConnectionError, TimeoutError) as e:
        wrapped = RetryableError(str(e), code="ConnectionError", retryable=True)
        raise wrapped from e


def _pool_name(project: str, location: str, cluster: str, pool: str) -> str:
    return (
        f"projects/{project}/locations/{location}"
        f"/clusters/{cluster}/nodePools/{pool}"
    )


def _tpu_chips_per_host(machine_type: str) -> Optional[int]:
    """GKE TPU machine types encode chips-per-host in the trailing
    '<n>t' token (ct5lp-hightpu-4t, ct4p-hightpu-4t, ct6e-standard-8t).
    None for non-TPU machine types."""
    if not machine_type.startswith("ct"):
        return None
    tail = machine_type.rsplit("-", 1)[-1]
    if tail.endswith("t") and tail[:-1].isdigit():
        return int(tail[:-1])
    return None


class GKEContainerClient:
    """ContainerAPI over google.cloud.container_v1.ClusterManagerClient."""

    def __init__(self, client):
        self._client = client

    def set_node_pool_size(
        self, project: str, location: str, cluster: str, pool: str, size: int
    ) -> None:
        _translate_call(
            self._client.set_node_pool_size,
            request={
                "name": _pool_name(project, location, cluster, pool),
                "node_count": size,
            },
        )

    def pending_operations(
        self, project: str, location: str, cluster: str, pool: str
    ) -> List[str]:
        """Names of in-flight operations targeting the pool. GKE holds a
        per-cluster operation lock, so any running cluster-scoped resize
        also blocks ours — report operations whose target mentions the
        pool OR the cluster with no pool qualifier."""
        response = _translate_call(
            self._client.list_operations,
            request={"parent": f"projects/{project}/locations/{location}"},
        )
        pool_path = _pool_name(project, location, cluster, pool)
        cluster_path = (
            f"projects/{project}/locations/{location}/clusters/{cluster}"
        )
        pending = []
        for op in getattr(response, "operations", []) or []:
            status = getattr(op, "status", None)
            status_name = getattr(status, "name", str(status))
            if status_name in ("DONE", "ABORTING"):
                continue
            target = getattr(op, "target_link", "") or ""
            # suffix match only: a substring test would also catch sibling
            # pools sharing the name prefix (v5e vs v5e-large)
            if target.endswith(pool_path) or target.endswith(cluster_path):
                pending.append(getattr(op, "name", "") or "operation")
        return pending

    def node_pool_template(
        self, project: str, location: str, cluster: str, pool: str
    ) -> Optional[dict]:
        """Scale-from-zero template from nodePools.get: labels/taints from
        the pool config; google.com/tpu allocatable derived from the TPU
        machine-type's chips-per-host token. cpu/memory are left to live
        nodes — GKE machine shapes aren't in the API response and the
        slice-pool solver keys on the chip resource."""
        node_pool = _translate_call(
            self._client.get_node_pool,
            request={"name": _pool_name(project, location, cluster, pool)},
        )
        config = getattr(node_pool, "config", None)
        if config is None:
            return None
        labels = dict(getattr(config, "labels", {}) or {})
        taints = [
            {
                "key": getattr(t, "key", ""),
                "value": getattr(t, "value", ""),
                "effect": getattr(
                    getattr(t, "effect", None), "name", ""
                ),
            }
            for t in getattr(config, "taints", []) or []
        ]
        allocatable: Dict[str, str] = {}
        machine_type = getattr(config, "machine_type", "") or ""
        chips = _tpu_chips_per_host(machine_type)
        if chips:
            allocatable["google.com/tpu"] = str(chips)
        if not allocatable:
            # a template with empty allocatable reads as a zero-capacity
            # node (nothing fits, the pool never scales from zero); None
            # correctly means "no declared shape, profile a live node"
            return None
        if machine_type:
            labels.setdefault(
                "node.kubernetes.io/instance-type", machine_type
            )
        return {
            "allocatable": allocatable,
            "labels": labels,
            "taints": taints,
        }


class MonitoringPubSubClient:
    """PubSubMetricsAPI over google.cloud.monitoring_v3: latest point of
    the subscription backlog gauges. Metrics lag ~60s; the queue producer
    already treats depth as a sampled signal (tpu.py PubSubSubscriptionQueue)."""

    # how far back to look for the latest written point; backlog gauges
    # are written once a minute
    _WINDOW_SECONDS = 300

    def __init__(self, client, clock=time.time):
        self._client = client
        self._clock = clock

    def _latest_point(self, project: str, metric: str, subscription: str):
        from google.cloud import monitoring_v3

        now = self._clock()
        interval = monitoring_v3.TimeInterval(
            {
                "end_time": {"seconds": int(now)},
                "start_time": {"seconds": int(now) - self._WINDOW_SECONDS},
            }
        )
        # drain the pager INSIDE the translation wrapper: subsequent pages
        # are fetched lazily during iteration and their transport errors
        # must hit the same RetryableError classification as the first RPC
        series = _translate_call(
            lambda: list(
                self._client.list_time_series(
                    request={
                        "name": f"projects/{project}",
                        "filter": (
                            "metric.type = "
                            f'"pubsub.googleapis.com/subscription/{metric}"'
                            " AND resource.labels.subscription_id = "
                            f'"{subscription}"'
                        ),
                        "interval": interval,
                        "view": (
                            monitoring_v3.ListTimeSeriesRequest
                            .TimeSeriesView.FULL
                        ),
                    },
                )
            )
        )
        for ts in series:
            points = getattr(ts, "points", []) or []
            if points:
                # points come newest-first from the API
                return points[0].value.int64_value
        return 0

    def num_undelivered_messages(
        self, project: str, subscription: str
    ) -> int:
        return int(
            self._latest_point(
                project, "num_undelivered_messages", subscription
            )
        )

    def oldest_unacked_message_age_seconds(
        self, project: str, subscription: str
    ) -> int:
        return int(
            self._latest_point(
                project, "oldest_unacked_message_age", subscription
            )
        )


def bind_container():
    """Production ContainerAPI, or None (caller keeps the guidance stub).
    Never raises: factory construction must succeed without GCP access."""
    if not container_sdk_available():
        return None
    try:
        from google.cloud import container_v1

        return GKEContainerClient(container_v1.ClusterManagerClient())
    except Exception as e:  # noqa: BLE001 — missing ADC etc.: degrade
        logger().warning("gke container binding failed: %s", e)
        return None


def bind_pubsub_metrics():
    """Production PubSubMetricsAPI, or None."""
    if not monitoring_sdk_available():
        return None
    try:
        from google.cloud import monitoring_v3

        return MonitoringPubSubClient(monitoring_v3.MetricServiceClient())
    except Exception as e:  # noqa: BLE001
        logger().warning("gke monitoring binding failed: %s", e)
        return None
