"""AWS cloud provider: EC2 AutoScalingGroups, EKS ManagedNodeGroups, SQS.

reference: pkg/cloudprovider/aws/{factory,autoscalinggroup,managednodegroup,
sqsqueue,error}.go. Same semantics, different binding: the reference links
aws-sdk-go and picks the region from EC2 metadata at construction
(factory.go:71-76); here the three API clients are INJECTED duck-typed
protocols (AutoscalingAPI / EKSAPI / SQSAPI), so the provider logic — ARN
handling, healthy-replica counting, transient-error classification — is
fully testable without the SDK, and a deployment binds boto3 (or anything
else) at the edge. The reference's compile-time `-tags=aws` selection
(registry/aws.go:1) maps to runtime registration under the name "aws".
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass
from typing import Dict, List, Optional, Protocol, Tuple

from karpenter_tpu.api.core import is_ready_and_schedulable
from karpenter_tpu.api.metricsproducer import (
    AWS_SQS_QUEUE_TYPE,
    register_queue_validator,
)
from karpenter_tpu.api.scalablenodegroup import (
    AWS_EC2_AUTO_SCALING_GROUP,
    AWS_EKS_NODE_GROUP,
    register_scalable_node_group_validator,
)
from karpenter_tpu.cloudprovider import Options, node_template_from_raw
from karpenter_tpu.cloudprovider.fake import FakeFactory
from karpenter_tpu.controllers.errors import RetryableError
from karpenter_tpu.faults import inject

# Node label EKS applies to managed-node-group members
# (reference: managednodegroup.go NodeGroupLabel).
NODE_GROUP_LABEL = "eks.amazonaws.com/nodegroup"

# Error codes the AWS SDK retry classifier treats as transient
# (reference: error.go:45-47 delegates to request.IsErrorRetryable; this is
# the same family of codes, expressed directly).
RETRYABLE_CODES = frozenset(
    {
        "RequestError",
        "RequestTimeout",
        "RequestTimeoutException",
        "Throttling",
        "ThrottlingException",
        "ThrottledException",
        "RequestThrottled",
        "RequestThrottledException",
        "TooManyRequestsException",
        "ProvisionedThroughputExceededException",
        "TransactionInProgressException",
        "RequestLimitExceeded",
        "BandwidthLimitExceeded",
        "LimitExceededException",
        "SlowDown",
        "PriorRequestNotComplete",
        "EC2ThrottledException",
        "InternalFailure",
        "ServiceUnavailable",
    }
)


class AWSAPIError(RuntimeError):
    """An error from an AWS API call, carrying the service error code.

    Fakes (and a boto3 binding translating botocore ClientError) raise this;
    `retryable` overrides the code-based classification when the caller
    knows better (e.g. connection resets with no code).
    """

    def __init__(
        self, message: str, code: str = "", retryable: Optional[bool] = None
    ):
        super().__init__(message)
        self.code = code
        self.retryable = (
            retryable if retryable is not None else code in RETRYABLE_CODES
        )


def transient_error(err: Optional[BaseException]) -> Optional[RetryableError]:
    """Wrap an AWS error into the controller taxonomy (reference:
    error.go:28-55): retryability from the SDK classifier, code surfaced for
    status conditions. Returns None for None, mirroring TransientError."""
    if err is None:
        return None
    code = getattr(err, "code", "") or ""
    retryable = getattr(err, "retryable", None)
    if retryable is None:
        retryable = code in RETRYABLE_CODES
    wrapped = RetryableError(str(err), code=code, retryable=bool(retryable))
    wrapped.__cause__ = err
    return wrapped


# ---------------------------------------------------------------------------
# ARN handling
# ---------------------------------------------------------------------------


@dataclass
class Arn:
    partition: str
    service: str
    region: str
    account_id: str
    resource: str


def parse_arn(value: str) -> Arn:
    """arn:partition:service:region:account-id:resource (resource may itself
    contain colons)."""
    parts = value.split(":", 5)
    if len(parts) != 6 or parts[0] != "arn":
        raise ValueError(f"invalid ARN: {value!r}")
    return Arn(
        partition=parts[1],
        service=parts[2],
        region=parts[3],
        account_id=parts[4],
        resource=parts[5],
    )


def normalize_asg_id(id_: str) -> str:
    """ASG APIs take a NAME, but users paste ARNs in YAML: extract the name
    from an ASG ARN, pass non-ARNs through unchanged (they are either a
    valid name already or will fail at the API), and reject ARNs that are
    not ASG ARNs (reference: autoscalinggroup.go:56-76)."""
    try:
        arn = parse_arn(id_)
    except ValueError:
        return id_
    resource = arn.resource.split(":")
    if len(resource) < 3 or resource[0] != "autoScalingGroup":
        raise ValueError(f"{id_}: is not an autoScalingGroup ARN")
    name_specifier = resource[2].split("/")
    if len(name_specifier) != 2 or name_specifier[0] != "autoScalingGroupName":
        raise ValueError(f"{id_}: does not contain autoScalingGroupName")
    return name_specifier[1]


def parse_mng_id(id_: str) -> Tuple[str, str]:
    """(cluster, nodegroup) from an EKS node-group ARN, whose resource is
    nodegroup/<cluster>/<nodegroup>/<uuid> (reference:
    managednodegroup.go:69-84)."""
    arn = parse_arn(id_)  # raises ValueError on malformed ARNs
    components = arn.resource.split("/")
    if len(components) < 3:
        raise ValueError(f"invalid managed node group id {id_}")
    return components[1], components[2]


# ---------------------------------------------------------------------------
# API client protocols (duck-typed seams; fakes + real bindings implement)
# ---------------------------------------------------------------------------


class AutoscalingAPI(Protocol):
    def describe_auto_scaling_groups(
        self, names: List[str], max_records: int
    ) -> List[dict]:
        """Each dict: {"instances": [{"health_status", "lifecycle_state"}]}."""
        ...

    def update_auto_scaling_group(
        self, name: str, desired_capacity: int
    ) -> None: ...


class EKSAPI(Protocol):
    def update_nodegroup_config(
        self, cluster_name: str, nodegroup_name: str, desired_size: int
    ) -> None: ...


class SQSAPI(Protocol):
    def get_queue_url(self, queue_name: str, account_id: str) -> str: ...

    def get_queue_attributes(
        self, queue_url: str, attribute_names: List[str]
    ) -> Dict[str, str]: ...

    def receive_message(
        self,
        queue_url: str,
        attribute_names: List[str],
        max_number_of_messages: int,
        visibility_timeout: int,
    ) -> List[Dict]: ...


class _NotImplementedClient:
    """Default when no client is bound: every call fails with guidance —
    the analog of running the !aws build against AWS resources."""

    # the OPTIONAL template hook must read as absent (None), not as a
    # failing stub: the catch-all __getattr__ below would otherwise
    # defeat the getattr sentinel and turn "no declared shape" into a
    # per-tick error for every empty group
    describe_node_template = None

    def __init__(self, service: str):
        self._service = service

    def __getattr__(self, name):
        def fail(*args, **kwargs):
            raise RuntimeError(
                f"no {self._service} API client bound; inject one into "
                "AWSFactory (e.g. a boto3 binding) to actuate AWS resources"
            )

        return fail


# ---------------------------------------------------------------------------
# Node groups and queues
# ---------------------------------------------------------------------------


class AutoScalingGroup:
    """reference: autoscalinggroup.go:79-112."""

    def __init__(self, id_: str, client: AutoscalingAPI, fence=None):
        self.id = normalize_asg_id(id_)
        self.client = client
        # actuation fence (karpenter_tpu/recovery): the factory's shared
        # FenceValidator; None = unfenced (direct construction, tests)
        self.fence = fence
        # one describe per reconcile: the controller calls stabilized()
        # then get_replicas() on the same short-lived instance (a fresh
        # one per reconcile), so memoizing the first describe halves the
        # DescribeAutoScalingGroups volume without staleness
        self._describe_memo = None

    def _describe(self) -> List[dict]:
        if self._describe_memo is None:
            try:
                inject("cloud.get_replicas")
                self._describe_memo = self.client.describe_auto_scaling_groups(
                    names=[self.id], max_records=1
                )
            except Exception as e:  # noqa: BLE001 — classified, not swallowed
                raise transient_error(e) from e
        return self._describe_memo

    @staticmethod
    def _count_healthy(group: dict) -> int:
        return sum(
            1
            for instance in group.get("instances", [])
            if instance.get("health_status") == "Healthy"
            and instance.get("lifecycle_state") == "InService"
        )

    def get_replicas(self) -> int:
        groups = self._describe()
        if len(groups) == 0:
            # distinct from "zero instances": the describe found NO group
            # with this name, so the SNG points at something that doesn't
            # exist (deleted, typo, wrong region/account)
            raise RuntimeError(f"autoscaling group not found: {self.id}")
        if len(groups) > 1:
            raise RuntimeError(
                f"autoscaling group name is ambiguous "
                f"({len(groups)} groups matched): {self.id}"
            )
        return self._count_healthy(groups[0])

    def set_replicas(self, count: int, token=None) -> None:
        # fence verification BEFORE apply (karpenter_tpu/recovery): a
        # stale incarnation's stamp is rejected, never applied — and
        # never wrapped as transient (retrying a dead decision is the
        # exact failure fencing exists to stop)
        if self.fence is not None:
            self.fence.admit(token)
        try:
            inject("cloud.set_replicas")
            self.client.update_auto_scaling_group(
                name=self.id, desired_capacity=count
            )
        except Exception as e:  # noqa: BLE001
            raise transient_error(e) from e

    def stabilized(self) -> Tuple[bool, str]:
        """Stable iff every desired instance is Healthy+InService — the
        check the reference leaves TODO-true (autoscalinggroup.go:110).
        Clients that don't report desired_capacity (older fakes/bindings)
        keep the reference's always-stable behavior. (The SNG controller
        still actuates scale-DOWNS while unstable, so a group capped
        below desired by a capacity shortage can be shrunk out of it.)"""
        groups = self._describe()
        if len(groups) != 1:
            return True, ""  # unknown group surfaces via get_replicas
        desired = groups[0].get("desired_capacity")
        if desired is None:
            return True, ""
        healthy = self._count_healthy(groups[0])
        if healthy == desired:
            return True, ""
        return False, (
            f"{healthy}/{desired} instances healthy and in service"
        )

    def template(self):
        """Scale-from-zero NodeTemplate. The injected autoscaling client
        may implement the OPTIONAL `describe_node_template(name)` —
        a boto3 binding would combine the ASG's launch template instance
        type with DescribeInstanceTypes into {allocatable, labels,
        taints}. None (or no hook) = no declared shape; a live node is
        then required to profile the group."""
        template_fn = getattr(self.client, "describe_node_template", None)
        if template_fn is None:
            return None
        try:
            raw = template_fn(self.id)
        except Exception as e:  # noqa: BLE001 — same posture as reads
            raise transient_error(e) from e
        return node_template_from_raw(raw)


class ManagedNodeGroup:
    """reference: managednodegroup.go:86-114. Replica observation counts
    ready+schedulable nodes carrying the EKS node-group label — read from
    the object store (the apiserver analog), not the EKS API."""

    def __init__(self, id_: str, eks_client: EKSAPI, store, fence=None):
        try:
            self.cluster, self.node_group = parse_mng_id(id_)
        except ValueError:
            # invalid ARNs surface as reconcile errors, not constructor
            # failures (reference: managednodegroup.go:53-56)
            self.cluster, self.node_group = "", ""
        self.eks_client = eks_client
        self.store = store
        self.fence = fence  # shared FenceValidator, or None = unfenced

    def get_replicas(self) -> int:
        inject("cloud.get_replicas")
        nodes = self.store.list(
            "Node", label_selector={NODE_GROUP_LABEL: self.node_group}
        )
        return sum(1 for n in nodes if is_ready_and_schedulable(n))

    def set_replicas(self, count: int, token=None) -> None:
        if self.fence is not None:
            self.fence.admit(token)  # verified BEFORE apply; not transient
        try:
            inject("cloud.set_replicas")
            self.eks_client.update_nodegroup_config(
                cluster_name=self.cluster,
                nodegroup_name=self.node_group,
                desired_size=count,
            )
        except Exception as e:  # noqa: BLE001
            raise transient_error(e) from e

    def stabilized(self) -> Tuple[bool, str]:
        return True, ""  # reference leaves this TODO (managednodegroup.go:112)

    def template(self):
        """Scale-from-zero NodeTemplate via the OPTIONAL
        `describe_node_template(cluster, nodegroup)` hook on the injected
        EKS client (EKS describeNodegroup returns instanceTypes + labels
        + taints — with NO_SCHEDULE-style effect enums, converted here).
        The EKS node-group label is stamped so selectors over the group
        match the template."""
        template_fn = getattr(
            self.eks_client, "describe_node_template", None
        )
        if template_fn is None:
            return None
        try:
            raw = template_fn(self.cluster, self.node_group)
        except Exception as e:  # noqa: BLE001 — same posture as reads
            raise transient_error(e) from e
        return node_template_from_raw(
            raw, extra_labels={NODE_GROUP_LABEL: self.node_group}
        )


def _oldest_sent_ms(messages) -> Optional[int]:
    """Smallest (oldest) SentTimestamp in a sampled batch, epoch ms;
    None when the batch is empty or carries no parsable timestamps."""
    oldest_ms: Optional[int] = None
    for message in messages or []:
        raw = (message.get("Attributes") or {}).get("SentTimestamp")
        if raw is None:
            continue
        try:
            sent = int(raw)
        except ValueError:
            continue
        if oldest_ms is None or sent < oldest_ms:
            oldest_ms = sent
    return oldest_ms


class SQSQueue:
    """reference: sqsqueue.go:36-98."""

    def __init__(
        self,
        arn: str,
        client: SQSAPI,
        age_sample_interval: float = 60.0,
        clock=_time.time,
    ):
        self.arn = arn
        self.client = client
        self.age_sample_interval = age_sample_interval
        self.clock = clock
        self._cached_url: Optional[str] = None
        self._age_sampled_at: float = float("-inf")
        self._age_sample: int = 0
        self._age_saw_message: bool = False

    def name(self) -> str:
        return self.arn

    def length(self) -> int:
        url = self._url()
        try:
            attributes = self.client.get_queue_attributes(
                queue_url=url,
                attribute_names=["ApproximateNumberOfMessages"],
            )
        except Exception as e:  # noqa: BLE001
            raise RuntimeError(
                f"could not pull SQS queueAttributes with input URL: {e}"
            ) from e
        raw = attributes.get("ApproximateNumberOfMessages", "")
        try:
            return int(raw)
        except ValueError as e:
            raise RuntimeError(
                f"could not resolve SQS queueAttributes types, {raw!r}"
            ) from e

    def oldest_message_age_seconds(self) -> int:
        """The reference stubs this at 0 (sqsqueue.go:78-80) because SQS
        surfaces oldest-message age only as a CloudWatch metric.
        Implemented here by message-attribute sampling: peek a batch with
        visibility_timeout=0 and age the oldest SentTimestamp. A head
        sample is an approximation (SQS ordering is best-effort), but it
        turns a dead gauge into a usable scaling signal.

        Side-effect caveat: every ReceiveMessage increments the sampled
        messages' ApproximateReceiveCount even at visibility_timeout=0,
        which counts toward a redrive policy's maxReceiveCount. The
        sample is therefore cached for age_sample_interval (default 60 s
        vs the 5 s producer tick); on queues with an aggressive redrive
        policy, raise the interval or prefer the CloudWatch
        ApproximateAgeOfOldestMessage metric via the Prometheus path.
        The cached age is extrapolated by elapsed time between samples,
        so the gauge still climbs between refreshes."""
        now = self.clock()
        since = now - self._age_sampled_at
        if since < self.age_sample_interval:
            # a sampled EMPTY queue stays 0 between refreshes; a sampled
            # head climbs by elapsed time even when its age rounded to 0
            # at sample time (a fresh-but-stuck message must still age)
            if not self._age_saw_message:
                return 0
            return max(0, self._age_sample + int(since))
        url = self._url()
        try:
            messages = self.client.receive_message(
                queue_url=url,
                attribute_names=["SentTimestamp"],
                max_number_of_messages=10,
                visibility_timeout=0,
            )
        except Exception as e:  # noqa: BLE001
            raise RuntimeError(
                f"could not sample SQS messages for age: {e}"
            ) from e
        oldest_ms = _oldest_sent_ms(messages)
        self._age_sampled_at = now
        self._age_saw_message = oldest_ms is not None
        self._age_sample = (
            0 if oldest_ms is None else max(0, int(now - oldest_ms / 1000.0))
        )
        return self._age_sample

    def _url(self) -> str:
        # the ARN->URL mapping is immutable for this queue's lifetime;
        # resolve once instead of one extra SQS round-trip per poll
        if self._cached_url is not None:
            return self._cached_url
        arn = parse_arn(self.arn)
        try:
            self._cached_url = self.client.get_queue_url(
                queue_name=arn.resource, account_id=arn.account_id
            )
        except Exception as e:  # noqa: BLE001
            raise RuntimeError(f"could not get SQS queue URL {e}") from e
        return self._cached_url


# ---------------------------------------------------------------------------
# Factory + admission validators
# ---------------------------------------------------------------------------


class AWSFactory:
    """reference: factory.go:41-76. Client resolution order per seam:
    explicit injection, then — only when constructed through the registry
    (the operator explicitly selected KARPENTER_CLOUD_PROVIDER=aws, so a
    live session is wanted, like the reference's factory) — the boto3
    binding (aws_sdk.bind), then the fail-with-guidance stub. Direct
    construction defaults to injection-or-stub so tests and embedders
    never build live cloud clients (or do IMDS network I/O) as a side
    effect of an ambient SDK install."""

    def __init__(
        self,
        options: Optional[Options] = None,
        autoscaling_client: Optional[AutoscalingAPI] = None,
        eks_client: Optional[EKSAPI] = None,
        sqs_client: Optional[SQSAPI] = None,
        sdk_autobind: bool = False,
    ):
        options = options or Options()
        self.store = options.store
        if sdk_autobind:
            from karpenter_tpu.cloudprovider import aws_sdk

            autoscaling_client = autoscaling_client or aws_sdk.bind(
                "autoscaling"
            )
            eks_client = eks_client or aws_sdk.bind("eks")
            sqs_client = sqs_client or aws_sdk.bind("sqs")
        self.autoscaling_client = autoscaling_client or _NotImplementedClient(
            "autoscaling"
        )
        self.eks_client = eks_client or _NotImplementedClient("eks")
        self.sqs_client = sqs_client or _NotImplementedClient("sqs")
        self._fallback = FakeFactory.not_implemented()
        # one actuation fence per factory — the cloud is shared
        # infrastructure, so every controller incarnation races the
        # same highest-seen generation (karpenter_tpu/recovery)
        from karpenter_tpu.recovery.fence import FenceValidator

        self.fence_validator = FenceValidator()
        # queue objects are cached per ARN so the SQSQueue URL cache
        # actually spans polls (producers resolve queue_for every tick)
        self._queues: Dict[str, SQSQueue] = {}

    def node_group_for(self, spec):
        if spec.type == AWS_EC2_AUTO_SCALING_GROUP:
            return AutoScalingGroup(
                spec.id, self.autoscaling_client,
                fence=self.fence_validator,
            )
        if spec.type == AWS_EKS_NODE_GROUP:
            return ManagedNodeGroup(
                spec.id, self.eks_client, self.store,
                fence=self.fence_validator,
            )
        return self._fallback.node_group_for(spec)

    def queue_for(self, spec):
        if spec.type == AWS_SQS_QUEUE_TYPE:
            queue = self._queues.get(spec.id)
            if queue is None:
                queue = self._queues[spec.id] = SQSQueue(
                    spec.id, self.sqs_client
                )
            return queue
        return self._fallback.queue_for(spec)


def _validate_asg(spec) -> None:
    normalize_asg_id(spec.id)


def _validate_mng(spec) -> None:
    parse_mng_id(spec.id)


def _validate_sqs(spec) -> None:
    parse_arn(spec.id)


# The reference registers its ASG normalizer under the EKS type — an
# upstream slip (autoscalinggroup.go:43-48 registers AWSEKSNodeGroup with
# normalizeID). Here each type gets its own validator.
register_scalable_node_group_validator(AWS_EC2_AUTO_SCALING_GROUP, _validate_asg)
register_scalable_node_group_validator(AWS_EKS_NODE_GROUP, _validate_mng)
register_queue_validator(AWS_SQS_QUEUE_TYPE, _validate_sqs)
