"""Fake cloud provider for tests and the not-implemented default.

reference: pkg/cloudprovider/fake/{factory,nodegroup,queue,errors}.go —
in-memory node groups with injectable errors and a stability toggle, fake
queues, and a retryable-error helper.
"""

from __future__ import annotations

from typing import Dict, Optional

from karpenter_tpu.api.metricsproducer import FAKE_QUEUE_TYPE, register_queue_validator
from karpenter_tpu.api.scalablenodegroup import (
    FAKE_NODE_GROUP,
    register_scalable_node_group_validator,
)
from karpenter_tpu.cloudprovider import Options
from karpenter_tpu.controllers.errors import RetryableError
from karpenter_tpu.faults import inject
from karpenter_tpu.recovery.fence import FenceValidator

# Providers register admission validators for the types they serve
# (reference: pkg/cloudprovider/aws/sqsqueue.go:29-34 init pattern).
register_scalable_node_group_validator(FAKE_NODE_GROUP, lambda spec: None)
register_queue_validator(FAKE_QUEUE_TYPE, lambda spec: None)

NOT_IMPLEMENTED_ERROR = RuntimeError(
    "provider is not implemented. Are you running the correct release for "
    "your cloud provider?"
)

NODE_GROUP_MESSAGE = "fake factory message"


class FakeNodeGroup:
    def __init__(self, factory: "FakeFactory", group_id: str):
        self._factory = factory
        self._id = group_id

    def get_replicas(self) -> int:
        inject("cloud.get_replicas")
        if self._factory.want_err is not None:
            raise self._factory.want_err
        replicas = self._factory.node_replicas.get(self._id)
        if replicas is None:
            raise RuntimeError(
                "Replicas for FakeNodeGroup was unset; "
                "try setting FakeFactory.node_replicas."
            )
        return replicas

    def set_replicas(self, count: int, token=None) -> None:
        # actuation fence (karpenter_tpu/recovery): verified FIRST —
        # before fault injection, like the AWS/TPU providers — so a
        # stale incarnation's call is rejected without consuming a
        # chaos plan's injection budget, and chaos runs mixing fault
        # plans with fencing behave identically across providers.
        # Unstamped calls (token None) pass unchecked.
        self._factory.fence_validator.admit(token)
        # inject BEFORE applying: a failed provider call must be atomic
        # (no partially-applied resize), so retry-vs-duplicate actuation
        # is observable in chaos runs
        inject("cloud.set_replicas")
        if self._factory.want_err is not None:
            raise self._factory.want_err
        self._factory.node_replicas[self._id] = count

    def stabilized(self):
        if self._factory.node_group_stable:
            return True, ""
        return False, NODE_GROUP_MESSAGE

    def template(self):
        """Injectable node shape (cloudprovider.NodeTemplate) for
        scale-from-zero tests; None when unset, like a provider that
        can't know its instance shape."""
        if self._factory.want_err is not None:
            raise self._factory.want_err
        return self._factory.node_templates.get(self._id)


class FakeQueue:
    def __init__(self, queue_id: str, want_err: Optional[Exception], length: int = 0,
                 oldest_age: int = 0):
        self._id = queue_id
        self._want_err = want_err
        self.queue_length = length
        self.oldest_age = oldest_age

    def name(self) -> str:
        return self._id

    def length(self) -> int:
        if self._want_err is not None:
            raise self._want_err
        return self.queue_length

    def oldest_message_age_seconds(self) -> int:
        if self._want_err is not None:
            raise self._want_err
        return self.oldest_age


class FakeFactory:
    """In-memory provider with error + stability injection."""

    def __init__(self, options: Optional[Options] = None):
        self.want_err: Optional[Exception] = None
        self.node_replicas: Dict[str, int] = {}
        self.node_templates: Dict[str, object] = {}  # id -> NodeTemplate
        self.node_group_stable = True
        self.queue_lengths: Dict[str, int] = {}
        self.queue_oldest_ages: Dict[str, int] = {}
        # the cloud is shared infrastructure: every controller
        # incarnation actuating through this factory races one fence
        # (karpenter_tpu/recovery/fence.py)
        self.fence_validator = FenceValidator()

    @classmethod
    def not_implemented(cls) -> "FakeFactory":
        factory = cls()
        factory.want_err = NOT_IMPLEMENTED_ERROR
        return factory

    def node_group_for(self, spec) -> FakeNodeGroup:
        return FakeNodeGroup(self, spec.id)

    def queue_for(self, spec) -> FakeQueue:
        return FakeQueue(
            spec.id,
            self.want_err,
            length=self.queue_lengths.get(spec.id, 0),
            oldest_age=self.queue_oldest_ages.get(spec.id, 0),
        )


def retryable_error(message: str) -> RetryableError:
    """reference: fake/errors.go:30-32"""
    return RetryableError(message, code=message)
