"""Cloud provider SPI (reference: pkg/cloudprovider/types.go:23-55).

Providers plug in NodeGroup (get/set replicas, stabilization) and Queue
(length, oldest message age) implementations. Provider selection is runtime
(registry.py) rather than compile-time build tags.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol, Tuple


class Queue(Protocol):
    def name(self) -> str: ...

    def length(self) -> int: ...

    def oldest_message_age_seconds(self) -> int: ...


@dataclass
class NodeTemplate:
    """Shape of the node a group would add — what the provider knows about
    the instance type even when the group is scaled to ZERO. The pending-
    pods producer falls back to this for empty groups (spec.pendingCapacity
    .nodeGroupRef), fixing scale-from-zero: with no live node to profile,
    the bin-pack would otherwise see an empty shape and never signal.
    allocatable values are Quantities (e.g. {"cpu": 8, "google.com/tpu": 8});
    labels/taints as on the nodes the group stamps."""

    allocatable: Dict[str, object] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    taints: List[object] = field(default_factory=list)  # api.core.Taint


_EFFECT_DIALECT = {
    # cloud APIs (GKE nodePools.get, EKS describeNodegroup) spell taint
    # effects as enums where core/v1 uses camelCase
    "NO_SCHEDULE": "NoSchedule",
    "NO_EXECUTE": "NoExecute",
    "PREFER_NO_SCHEDULE": "PreferNoSchedule",
}


def node_template_from_raw(
    raw: Optional[dict], extra_labels: Optional[Dict[str, str]] = None
) -> Optional["NodeTemplate"]:
    """Cloud-API-shaped dict -> NodeTemplate: allocatable strings parse to
    Quantities, taint dicts become api.core.Taint with core/v1 effect
    spelling (enum dialects accepted). The one conversion every provider's
    template() shares. extra_labels fill in defaults (e.g. the pool/group
    label its nodes would carry) without overriding the API's."""
    if raw is None:
        return None
    from karpenter_tpu.api.core import Taint
    from karpenter_tpu.utils.quantity import parse_quantity

    labels = dict(raw.get("labels", {}))
    for key, value in (extra_labels or {}).items():
        labels.setdefault(key, value)
    taints = [
        Taint(
            key=t.get("key", ""),
            value=t.get("value", ""),
            effect=_EFFECT_DIALECT.get(
                t.get("effect", ""), t.get("effect", "")
            ),
        )
        for t in raw.get("taints", [])
    ]
    return NodeTemplate(
        allocatable={
            r: parse_quantity(str(v))
            for r, v in raw.get("allocatable", {}).items()
        },
        labels=labels,
        taints=taints,
    )


class NodeGroup(Protocol):
    def set_replicas(self, count: int, token=None) -> None:
        """Apply the desired replica count. `token` is an optional
        actuation fence stamp (recovery/fence.FenceToken): providers
        that enforce fencing verify it BEFORE applying and raise
        FenceRejectedError for a superseded generation; None (unfenced
        deployments) must always be accepted."""
        ...

    def get_replicas(self) -> int: ...

    def stabilized(self) -> Tuple[bool, str]:
        """(stable, message); message explains instability."""
        ...

    # OPTIONAL (resolved via getattr — older/simpler providers need not
    # implement it): the instance shape this group would add, or None when
    # the provider can't know (then scale-from-zero needs a live node).
    # def template(self) -> Optional[NodeTemplate]: ...


class CloudProviderFactory(Protocol):
    def node_group_for(self, spec) -> NodeGroup:
        """NodeGroup for a ScalableNodeGroupSpec."""
        ...

    def queue_for(self, spec) -> Queue:
        """Queue for a QueueSpec."""
        ...


@dataclass
class Options:
    """Injected into provider factories (reference: types.go:52-55)."""

    store: Optional[object] = None
