"""Cloud provider SPI (reference: pkg/cloudprovider/types.go:23-55).

Providers plug in NodeGroup (get/set replicas, stabilization) and Queue
(length, oldest message age) implementations. Provider selection is runtime
(registry.py) rather than compile-time build tags.
"""

from dataclasses import dataclass
from typing import Optional, Protocol, Tuple


class Queue(Protocol):
    def name(self) -> str: ...

    def length(self) -> int: ...

    def oldest_message_age_seconds(self) -> int: ...


class NodeGroup(Protocol):
    def set_replicas(self, count: int) -> None: ...

    def get_replicas(self) -> int: ...

    def stabilized(self) -> Tuple[bool, str]:
        """(stable, message); message explains instability."""
        ...


class CloudProviderFactory(Protocol):
    def node_group_for(self, spec) -> NodeGroup:
        """NodeGroup for a ScalableNodeGroupSpec."""
        ...

    def queue_for(self, spec) -> Queue:
        """Queue for a QueueSpec."""
        ...


@dataclass
class Options:
    """Injected into provider factories (reference: types.go:52-55)."""

    store: Optional[object] = None
