"""TPU pod-slice pool provider: the TPU-native ScalableNodeGroup.

The reference's "real" providers are AWS ASG/EKS node groups
(pkg/cloudprovider/aws/{autoscalinggroup,managednodegroup}.go). The TPU
deployment's replica unit is a GKE node pool of TPU pod slices: scaling the
pool by one adds one whole slice (a topology like 2x4), so replicas count
SLICES, not chips. Same SPI, same observation posture as the reference's
ManagedNodeGroup: observed replicas come from ready+schedulable nodes in
the store (the apiserver analog, managednodegroup.go:86-98), actuation goes
through an injected duck-typed container API (the UpdateNodegroupConfig
analog, managednodegroup.go:100-110).

Unlike the reference's TODO-true Stabilized (autoscalinggroup.go:110-112),
pools report unstable while a resize operation is in flight — the SNG
controller then holds actuation, which matters for TPU slices where a
partial slice is unusable.
"""

from __future__ import annotations

import re
from typing import List, Optional, Protocol, Tuple

from karpenter_tpu.api.core import is_ready_and_schedulable
from karpenter_tpu.api.metricsproducer import register_queue_validator
from karpenter_tpu.api.scalablenodegroup import (
    TPU_POD_SLICE_POOL,
    register_scalable_node_group_validator,
)
from karpenter_tpu.cloudprovider import Options, node_template_from_raw
from karpenter_tpu.cloudprovider.fake import FakeFactory
from karpenter_tpu.controllers.errors import RetryableError
from karpenter_tpu.faults import inject

# GKE labels node-pool members with the pool name
NODE_POOL_LABEL = "cloud.google.com/gke-nodepool"
# TPU nodes additionally carry accelerator/topology labels
TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"

_POOL_ID_RE = re.compile(
    r"^projects/(?P<project>[^/]+)/locations/(?P<location>[^/]+)"
    r"(?:/clusters/(?P<cluster>[^/]+))?/nodePools/(?P<pool>[^/]+)$"
)


def parse_pool_id(id_: str) -> Tuple[str, str, str, str]:
    """(project, location, cluster, pool) from a GKE-style resource name.
    Cluster is optional in the short form."""
    m = _POOL_ID_RE.match(id_)
    if m is None:
        raise ValueError(
            f"invalid node pool id {id_!r}; want "
            "projects/<p>/locations/<l>[/clusters/<c>]/nodePools/<name>"
        )
    return (
        m.group("project"),
        m.group("location"),
        m.group("cluster") or "",
        m.group("pool"),
    )


class ContainerAPI(Protocol):
    """Duck-typed GKE container API seam (bind a google-cloud client or a
    fake here)."""

    def set_node_pool_size(
        self, project: str, location: str, cluster: str, pool: str, size: int
    ) -> None: ...

    def pending_operations(
        self, project: str, location: str, cluster: str, pool: str
    ) -> List[str]:
        """Names of in-flight resize/repair operations for the pool."""
        ...


class _NotImplementedContainerAPI:
    def set_node_pool_size(self, project, location, cluster, pool, size):
        raise RuntimeError(
            "no container API client bound; inject one into TPUFactory to "
            "actuate node pools"
        )

    def pending_operations(self, project, location, cluster, pool):
        return []


class TPUPodSlicePool:
    def __init__(self, id_: str, api: ContainerAPI, store, fence=None):
        self.project, self.location, self.cluster, self.pool = parse_pool_id(
            id_
        )
        self.api = api
        self.store = store
        # actuation fence (karpenter_tpu/recovery): the factory's shared
        # FenceValidator; None = unfenced (direct construction, tests)
        self.fence = fence

    def get_replicas(self) -> int:
        """Ready slices = ready+schedulable nodes labeled with the pool name.
        For multi-host slices every host-node carries the pool label; ready
        hosts are divided by hosts-per-slice (conservative floor: a
        partially-ready slice is not a replica). Hosts-per-slice is derived
        from each node's OWN google.com/tpu allocatable (chips actually on
        that host) against the slice topology — hardware generations differ
        (4 chips/host on v4/v5p, 8 on single-host v5e/v6e shapes), so a
        constant would halve or double the count."""
        inject("cloud.get_replicas")
        nodes = self.store.list(
            "Node", label_selector={NODE_POOL_LABEL: self.pool}
        )
        ready = [n for n in nodes if is_ready_and_schedulable(n)]
        if not ready:
            return 0
        hosts_per_slice = max(
            (_hosts_per_slice(n) for n in ready), default=1
        )
        return len(ready) // max(hosts_per_slice, 1)

    def set_replicas(self, count: int, token=None) -> None:
        # fence verification BEFORE apply (karpenter_tpu/recovery): a
        # stale incarnation's stamp is rejected, never applied, and the
        # rejection is NOT wrapped as a transient resize race below —
        # retrying a dead decision is what fencing exists to stop
        if self.fence is not None:
            self.fence.admit(token)
        try:
            inject("cloud.set_replicas")
            self.api.set_node_pool_size(
                self.project, self.location, self.cluster, self.pool, count
            )
        except RetryableError:
            raise
        except Exception as e:  # noqa: BLE001 — resize races are transient
            wrapped = RetryableError(str(e), code="ResizeFailed")
            wrapped.__cause__ = e
            raise wrapped from e

    def template(self):
        """NodeTemplate for scale-from-zero (cloudprovider.NodeTemplate):
        the host shape a pool resize would add, sourced from the bound
        container API's node-pool config when it exposes one
        (`node_pool_template` is an OPTIONAL api method — google's
        nodePools.get returns the machine config this models). None when
        the API can't say; live nodes always take precedence anyway."""
        template_fn = getattr(self.api, "node_pool_template", None)
        if template_fn is None:
            return None
        try:
            raw = template_fn(
                self.project, self.location, self.cluster, self.pool
            )
        except RetryableError:
            raise
        except Exception as e:  # noqa: BLE001 — API blips are transient,
            # same posture as stabilized/set_replicas
            wrapped = RetryableError(str(e), code="TemplateReadFailed")
            raise wrapped from e
        return node_template_from_raw(
            raw, extra_labels={NODE_POOL_LABEL: self.pool}
        )

    def stabilized(self) -> Tuple[bool, str]:
        try:
            pending = self.api.pending_operations(
                self.project, self.location, self.cluster, self.pool
            )
        except RetryableError:
            raise
        except Exception as e:  # noqa: BLE001 — API blips are transient:
            # keep the resource Active (AbleToScale=false) like set_replicas
            wrapped = RetryableError(str(e), code="OperationPollFailed")
            raise wrapped from e
        if pending:
            return False, f"operations in flight: {', '.join(pending)}"
        return True, ""


# fallback when a node doesn't report google.com/tpu allocatable
_DEFAULT_CHIPS_PER_HOST = 4
# node allocatable resource name for TPU chips on GKE
TPU_CHIP_RESOURCE = "google.com/tpu"


def _hosts_per_slice(node) -> int:
    """Hosts spanned by the slice this node belongs to: topology chip count
    divided by the chips this host itself exposes (ceil — a remainder still
    needs a host)."""
    topology = node.metadata.labels.get(TPU_TOPOLOGY_LABEL)
    if not topology:
        return 1
    try:
        chips = 1
        for dim in topology.lower().split("x"):
            chips *= int(dim)
    except ValueError:
        return 1
    chip_quantity = node.status.allocatable.get(TPU_CHIP_RESOURCE)
    chips_per_host = (
        int(chip_quantity.to_float())
        if chip_quantity is not None and chip_quantity.to_float() > 0
        else _DEFAULT_CHIPS_PER_HOST
    )
    return max(1, -(-chips // chips_per_host))


# ---------------------------------------------------------------------------
# Pub/Sub subscription queue — the GCP analog of the reference's SQS queue
# (reference: pkg/cloudprovider/aws/sqsqueue.go). Depth and age come from
# Cloud Monitoring's subscription/num_undelivered_messages and
# subscription/oldest_unacked_message_age metrics, read through a
# duck-typed seam like every other cloud API here.
# ---------------------------------------------------------------------------

GCP_PUBSUB_SUBSCRIPTION = "GCPPubSubSubscription"

_SUBSCRIPTION_ID_RE = re.compile(
    r"^projects/(?P<project>[^/]+)/subscriptions/(?P<name>[^/]+)$"
)


def parse_subscription_id(id_: str) -> Tuple[str, str]:
    m = _SUBSCRIPTION_ID_RE.match(id_)
    if m is None:
        raise ValueError(
            f"invalid subscription id {id_!r}; want "
            "projects/<project>/subscriptions/<name>"
        )
    return m.group("project"), m.group("name")


class PubSubMetricsAPI(Protocol):
    """Bind a Cloud Monitoring client (or a fake) here."""

    def num_undelivered_messages(
        self, project: str, subscription: str
    ) -> int: ...

    def oldest_unacked_message_age_seconds(
        self, project: str, subscription: str
    ) -> int: ...


class PubSubSubscriptionQueue:
    """Queue SPI over a Pub/Sub subscription. The reference's SQS stub
    never implemented message age (sqsqueue.go:78-80); Monitoring exposes
    it directly, so both gauges are real here."""

    def __init__(self, id_: str, api: PubSubMetricsAPI):
        self.project, self.subscription = parse_subscription_id(id_)
        self.api = api

    def name(self) -> str:
        return self.subscription

    def length(self) -> int:
        try:
            return int(
                self.api.num_undelivered_messages(
                    self.project, self.subscription
                )
            )
        except RetryableError:
            raise
        except Exception as e:  # noqa: BLE001 — monitoring blips are
            # transient, same posture as the pool API reads
            wrapped = RetryableError(str(e), code="QueueReadFailed")
            raise wrapped from e

    def oldest_message_age_seconds(self) -> int:
        try:
            return int(
                self.api.oldest_unacked_message_age_seconds(
                    self.project, self.subscription
                )
            )
        except RetryableError:
            raise
        except Exception as e:  # noqa: BLE001
            wrapped = RetryableError(str(e), code="QueueReadFailed")
            raise wrapped from e


class _NotImplementedPubSubAPI:
    def num_undelivered_messages(self, project, subscription):
        raise RuntimeError(
            "no Pub/Sub metrics client bound; inject one into TPUFactory "
            "to read subscription queues"
        )

    def oldest_unacked_message_age_seconds(self, project, subscription):
        raise RuntimeError(
            "no Pub/Sub metrics client bound; inject one into TPUFactory "
            "to read subscription queues"
        )


class TPUFactory:
    """Provider factory for TPU pod-slice pools + Pub/Sub subscription
    queues; anything else falls back to not-implemented."""

    def __init__(
        self,
        options: Optional[Options] = None,
        container_api: Optional[ContainerAPI] = None,
        pubsub_api: Optional[PubSubMetricsAPI] = None,
        sdk_autobind: bool = False,
    ):
        # resolution per seam: explicit injection, then — only via the
        # registry (operator selected the provider, a live client is
        # wanted) — the google-cloud binding (gke_sdk), then the
        # fail-with-guidance stub; direct construction never builds live
        # cloud clients as a side effect of an ambient SDK install
        options = options or Options()
        self.store = options.store
        if sdk_autobind:
            from karpenter_tpu.cloudprovider import gke_sdk

            container_api = container_api or gke_sdk.bind_container()
            pubsub_api = pubsub_api or gke_sdk.bind_pubsub_metrics()
        self.container_api = container_api or _NotImplementedContainerAPI()
        self.pubsub_api = pubsub_api or _NotImplementedPubSubAPI()
        self._fallback = FakeFactory.not_implemented()
        # one actuation fence per factory — every controller incarnation
        # actuating through it races the same highest-seen generation
        from karpenter_tpu.recovery.fence import FenceValidator

        self.fence_validator = FenceValidator()

    def node_group_for(self, spec):
        if spec.type == TPU_POD_SLICE_POOL:
            return TPUPodSlicePool(
                spec.id, self.container_api, self.store,
                fence=self.fence_validator,
            )
        return self._fallback.node_group_for(spec)

    def queue_for(self, spec):
        if spec.type == GCP_PUBSUB_SUBSCRIPTION:
            return PubSubSubscriptionQueue(spec.id, self.pubsub_api)
        return self._fallback.queue_for(spec)


def _validate_pool(spec) -> None:
    parse_pool_id(spec.id)


def _validate_subscription(spec) -> None:
    parse_subscription_id(spec.id)


register_scalable_node_group_validator(TPU_POD_SLICE_POOL, _validate_pool)
register_queue_validator(GCP_PUBSUB_SUBSCRIPTION, _validate_subscription)
