"""Supervised-degradation primitives: jittered backoff + circuit breaker.

The reference's failure handling is a taxonomy (errors.go) consumed by
one controller; requeues stay fixed-interval and a flapping provider is
retried forever at full cadence. These primitives are the ladder the
TPU build layers on top (docs/resilience.md):

  * DecorrelatedJitterBackoff — the engine's per-object requeue delay
    under repeated retryable failures. Monotone non-decreasing (each
    delay is drawn from [prev, prev*3], so retries never speed back up
    mid-outage) and bounded by `cap_s`; the jitter decorrelates a fleet
    of failing objects so recovery doesn't thundering-herd the provider.
  * CircuitBreaker — closed → open after `failure_threshold` consecutive
    failures → half-open after `reset_s` (one probe admitted) → closed
    on probe success / open again on probe failure. The SNG controller
    keeps one per node group so a flapping cloud API stops eating the
    reconcile tick.

Both are clock-injected and RNG-seeded: deterministic under test, which
is what lets the chaos suite assert exact ladder behavior.
"""

from __future__ import annotations

import random
import time as _time
from typing import Callable, Optional

SUBSYSTEM = "resilience"

# Circuit states, exported as gauge values on
# karpenter_resilience_circuit_state{name=<group>}
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"
CIRCUIT_STATE_VALUE = {CLOSED: 0.0, HALF_OPEN: 0.5, OPEN: 1.0}


class DecorrelatedJitterBackoff:
    """next(prev) draws the next retry delay.

    Variant of AWS's decorrelated jitter with a floor at the previous
    delay: delay_n = min(cap, uniform(prev_n-1, prev_n-1 * 3)), starting
    from uniform(base, base*3). The floor makes the sequence monotone
    non-decreasing (a property the engine's requeue ladder pins in
    tests) while keeping the spread that decorrelates concurrent
    failers.
    """

    def __init__(
        self, base_s: float = 1.0, cap_s: float = 60.0, seed: int = 0
    ):
        if base_s <= 0 or cap_s < base_s:
            raise ValueError(
                f"need 0 < base_s <= cap_s, got {base_s}/{cap_s}"
            )
        self.base_s = base_s
        self.cap_s = cap_s
        self._rng = random.Random(seed)

    def next(self, prev: float = 0.0) -> float:
        low = max(self.base_s, prev)
        return min(self.cap_s, self._rng.uniform(low, low * 3.0))


class CircuitBreaker:
    """Per-resource breaker around a flaky dependency.

    allow() gates the call: True in closed state, True once per
    `reset_s` window while open (the half-open probe), else False.
    Callers report outcomes with record_success()/record_failure(code).
    """

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_s: float = 30.0,
        clock: Callable[[], float] = _time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.reset_s = reset_s
        self.clock = clock
        self.state = CLOSED
        self.consecutive_failures = 0
        self.opened_at: Optional[float] = None
        self.last_error_code = ""
        self.opens_total = 0

    def allow(self) -> bool:
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if self.clock() - (self.opened_at or 0.0) >= self.reset_s:
                self.state = HALF_OPEN
                return True  # the one probe this window
            return False
        # HALF_OPEN: a probe is already in flight this window; further
        # callers stay blocked until its outcome is recorded
        return False

    def record_success(self) -> None:
        self.state = CLOSED
        self.consecutive_failures = 0
        self.opened_at = None
        self.last_error_code = ""

    def record_failure(self, code: str = "") -> None:
        self.consecutive_failures += 1
        if code:
            self.last_error_code = code
        if self.state == HALF_OPEN:
            # failed probe: back to open for a fresh reset window
            self._open()
        elif (
            self.state == CLOSED
            and self.consecutive_failures >= self.failure_threshold
        ):
            self._open()

    def _open(self) -> None:
        self.state = OPEN
        self.opened_at = self.clock()
        self.opens_total += 1

    def retry_in(self) -> float:
        """Seconds until the next half-open probe is admitted (0 when
        not open) — surfaced in the ActuationCircuitOpen condition."""
        if self.state != OPEN or self.opened_at is None:
            return 0.0
        return max(0.0, self.reset_s - (self.clock() - self.opened_at))

    def state_value(self) -> float:
        return CIRCUIT_STATE_VALUE[self.state]
