"""Leader election over the object store's optimistic concurrency.

reference: cmd/controller/main.go:58-59 enables controller-runtime's
lease-based leader election (lease RBAC at config/rbac/role.yaml:62-71) so
exactly one controller replica reconciles at a time. Here the lease is an
object in the store (the apiserver-bus analog), acquired and renewed with
compare-and-swap semantics: a stale resourceVersion loses the race, so two
candidates can never both hold the lease — same invariant, same transport
as all other cross-controller coordination.

Clock discipline (docs/resilience.md "Replicated control plane"): the
`renew_time` stamped INTO the lease is wall-clock (it is shared state other
candidates read), but every LOCAL freshness judgement runs on a monotonic
clock — our own leadership lapses `lease_duration` of monotonic time after
our last successful renew, and another holder's lease is aged by how long
WE have watched the same (holder, renew_time) stamp stand still. A wall
clock stepped backward therefore cannot extend a stale lease (the
monotonic observation keeps aging it), and a wall clock stepped forward by
less than `skew_tolerance` cannot prematurely expire a fresh one.

Chaos seams: each election round passes through the fault-injection points
`lease.acquire.<identity>` / `lease.renew.<identity>` (faults/registry.py)
— an injected retryable error is a store partition for that candidate (the
round fails, leadership lapses when renews keep failing), a crash plan is
the replica dying mid-round.
"""

from __future__ import annotations

import time as _time
import uuid
from dataclasses import dataclass, field
from typing import Optional, Tuple

from karpenter_tpu.api.core import ObjectMeta
from karpenter_tpu.controllers.errors import RetryableError
from karpenter_tpu.faults import inject
from karpenter_tpu.store.store import ConflictError, Store

DEFAULT_LEASE_NAME = "karpenter-leader"
DEFAULT_LEASE_NAMESPACE = "kube-system"
DEFAULT_LEASE_DURATION = 15.0
# slack added to another holder's expiry before we contend for takeover:
# a wall clock stepped forward by less than this cannot steal a lease the
# holder is still renewing on time
DEFAULT_SKEW_TOLERANCE = 1.0


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease analog."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder: str = ""
    renew_time: float = 0.0
    lease_duration: float = DEFAULT_LEASE_DURATION


class LeaderElector:
    """Acquire-or-renew on every tick; leadership is only ever held for one
    lease_duration past the last successful renew (monotonic — module
    docstring)."""

    def __init__(
        self,
        store: Store,
        identity: Optional[str] = None,
        name: str = DEFAULT_LEASE_NAME,
        namespace: str = DEFAULT_LEASE_NAMESPACE,
        lease_duration: float = DEFAULT_LEASE_DURATION,
        clock=_time.time,
        monotonic=None,
        skew_tolerance: float = DEFAULT_SKEW_TOLERANCE,
    ):
        self.store = store
        self.identity = identity or f"karpenter-{uuid.uuid4().hex[:8]}"
        self.name = name
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.clock = clock
        # scripted clocks (tests, SimLab) double as the monotonic source
        # — only the real wall clock needs a separate monotonic reading
        if monotonic is None:
            monotonic = _time.monotonic if clock is _time.time else clock
        self.monotonic = monotonic
        self.skew_tolerance = skew_tolerance
        # monotonic timestamp of OUR last successful acquire/renew: the
        # only input to our own freshness — a stepped wall clock cannot
        # stretch (or clip) how long we believe we lead
        self._renewed_mono: Optional[float] = None
        # last (holder, renew_time) stamp seen on the lease + the
        # monotonic time we FIRST saw it: ages another holder's lease on
        # our own clock, so a backward wall step cannot keep it fresh
        self._observed: Optional[Tuple[Tuple[str, float], float]] = None

    # -- freshness ---------------------------------------------------------

    def _holding(self, now_mono: float) -> bool:
        """Whether WE believe we hold the lease right now (monotonic)."""
        return (
            self._renewed_mono is not None
            and now_mono - self._renewed_mono <= self.lease_duration
        )

    def _expired(self, lease: Lease, now: float, now_mono: float) -> bool:
        """Whether ANOTHER holder's lease has lapsed. Wall expiry (with
        the skew margin) is the fast path a fresh candidate needs to
        take over after a real death; the monotonic observation age is
        the backstop a stepped wall clock cannot fake."""
        stamp = (lease.holder, lease.renew_time)
        if self._observed is None or self._observed[0] != stamp:
            self._observed = (stamp, now_mono)
        observed_age = now_mono - self._observed[1]
        margin = lease.lease_duration + self.skew_tolerance
        # inclusive: a challenger observing exactly margin-old evidence
        # may steal — the margin IS the grace, not one tick more
        return now >= lease.renew_time + margin or observed_age >= margin

    # -- the election round ------------------------------------------------

    def try_acquire(self) -> bool:
        """One election round: returns True iff this identity holds the
        lease after the round. Safe to call every tick."""
        now = self.clock()
        now_mono = self.monotonic()
        verb = "renew" if self._holding(now_mono) else "acquire"
        try:
            inject(f"lease.{verb}.{self.identity}")
        except RetryableError:
            # injected partition: this candidate cannot reach the store
            # this round — it neither renews nor contends
            return False
        lease = self.store.try_get("Lease", self.namespace, self.name)
        if lease is None:
            return self._create_fresh(now, now_mono)
        held_by_other = lease.holder != self.identity
        if held_by_other and not self._expired(lease, now, now_mono):
            return False
        # already ours and fresh: skip the write until a third of the lease
        # has elapsed (k8s renewDeadline posture) — renewing every tick
        # churns the store bus with resourceVersion bumps + watch events
        if (
            not held_by_other
            and self._renewed_mono is not None
            and now_mono - self._renewed_mono < self.lease_duration / 3
        ):
            return True
        # renew (ours) or take over (expired): CAS via resourceVersion
        lease.holder = self.identity
        lease.renew_time = now
        try:
            self.store.update(lease)
            self._renewed_mono = now_mono
            return True
        except ConflictError:
            return False  # lost the race this round

    def _create_fresh(self, now: float, now_mono: float) -> bool:
        """No Lease object yet: first creator wins."""
        try:
            self.store.create(
                Lease(
                    metadata=ObjectMeta(
                        name=self.name, namespace=self.namespace
                    ),
                    holder=self.identity,
                    renew_time=now,
                    lease_duration=self.lease_duration,
                )
            )
            self._renewed_mono = now_mono
            return True
        except ConflictError:
            return False  # another candidate created it first

    def release(self) -> None:
        """Graceful surrender: zero the holder so a successor takes over
        without waiting out the lease. Best-effort — losing the CAS (or
        never having held) just leaves expiry to do the work."""
        self._renewed_mono = None
        lease = self.store.try_get("Lease", self.namespace, self.name)
        if lease is None or lease.holder != self.identity:
            return
        lease.holder = ""
        lease.renew_time = 0.0
        try:
            self.store.update(lease)
        except ConflictError:
            pass

    def is_leader(self) -> bool:
        lease = self.store.try_get("Lease", self.namespace, self.name)
        return (
            lease is not None
            and lease.holder == self.identity
            and self._holding(self.monotonic())
        )
