"""Leader election over the object store's optimistic concurrency.

reference: cmd/controller/main.go:58-59 enables controller-runtime's
lease-based leader election (lease RBAC at config/rbac/role.yaml:62-71) so
exactly one controller replica reconciles at a time. Here the lease is an
object in the store (the apiserver-bus analog), acquired and renewed with
compare-and-swap semantics: a stale resourceVersion loses the race, so two
candidates can never both hold the lease — same invariant, same transport
as all other cross-controller coordination.
"""

from __future__ import annotations

import time as _time
import uuid
from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.api.core import ObjectMeta
from karpenter_tpu.store.store import ConflictError, Store

DEFAULT_LEASE_NAME = "karpenter-leader"
DEFAULT_LEASE_NAMESPACE = "kube-system"
DEFAULT_LEASE_DURATION = 15.0


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease analog."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder: str = ""
    renew_time: float = 0.0
    lease_duration: float = DEFAULT_LEASE_DURATION


class LeaderElector:
    """Acquire-or-renew on every tick; leadership is only ever held for one
    lease_duration past the last successful renew."""

    def __init__(
        self,
        store: Store,
        identity: Optional[str] = None,
        name: str = DEFAULT_LEASE_NAME,
        namespace: str = DEFAULT_LEASE_NAMESPACE,
        lease_duration: float = DEFAULT_LEASE_DURATION,
        clock=_time.time,
    ):
        self.store = store
        self.identity = identity or f"karpenter-{uuid.uuid4().hex[:8]}"
        self.name = name
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.clock = clock

    def try_acquire(self) -> bool:
        """One election round: returns True iff this identity holds the
        lease after the round. Safe to call every tick."""
        now = self.clock()
        lease = self.store.try_get("Lease", self.namespace, self.name)
        if lease is None:
            try:
                self.store.create(
                    Lease(
                        metadata=ObjectMeta(
                            name=self.name, namespace=self.namespace
                        ),
                        holder=self.identity,
                        renew_time=now,
                        lease_duration=self.lease_duration,
                    )
                )
                return True
            except ConflictError:
                return False  # another candidate created it first
        held_by_other = lease.holder != self.identity
        expired = now > lease.renew_time + lease.lease_duration
        if held_by_other and not expired:
            return False
        # already ours and fresh: skip the write until a third of the lease
        # has elapsed (k8s renewDeadline posture) — renewing every tick
        # churns the store bus with resourceVersion bumps + watch events
        if not held_by_other and now < lease.renew_time + lease.lease_duration / 3:
            return True
        # renew (ours) or take over (expired): CAS via resourceVersion
        lease.holder = self.identity
        lease.renew_time = now
        try:
            self.store.update(lease)
            return True
        except ConflictError:
            return False  # lost the race this round

    def is_leader(self) -> bool:
        lease = self.store.try_get("Lease", self.namespace, self.name)
        return (
            lease is not None
            and lease.holder == self.identity
            and self.clock() <= lease.renew_time + lease.lease_duration
        )
