"""Process entry point: `python -m karpenter_tpu`.

reference: cmd/controller/main.go:40-77 — flag parsing, logging, a
leader-elected manager serving /metrics on :8080, cloud-provider registry,
factory graph, controller registration, run-until-signalled. Same wiring
here. Admission runs in-process (store-side validation) when the store is
the bus; --webhook-port additionally serves the same rules as k8s
AdmissionReview webhooks for real-cluster mode (reference port 9443).
"""

from __future__ import annotations

import argparse
import sys
import time

from karpenter_tpu.leaderelection import LeaderElector
from karpenter_tpu.observability import MetricsServer, start_profiler_server
from karpenter_tpu.runtime import KarpenterRuntime, Options
from karpenter_tpu.utils.log import setup as log_setup


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="karpenter_tpu",
        description="TPU-native metrics-driven autoscaling control plane",
    )
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument(
        "--prometheus-uri",
        default=None,
        help="Prometheus HTTP API base URI; omit to read the in-process "
        "gauge registry directly",
    )
    parser.add_argument("--metrics-port", type=int, default=8080)
    parser.add_argument(
        "--webhook-port",
        type=int,
        default=0,
        help="serve AdmissionReview validate/mutate webhooks on this port "
        "(0 = off; real-cluster mode uses 9443 like the reference)",
    )
    parser.add_argument(
        "--webhook-cert-dir",
        default=None,
        help="directory holding tls.crt/tls.key for the webhook server "
        "(plain HTTP when omitted)",
    )
    parser.add_argument(
        "--cloud-provider",
        default=None,
        help="provider name from the registry (fake, aws, ...); defaults to "
        "KARPENTER_CLOUD_PROVIDER or the not-implemented fake",
    )
    parser.add_argument(
        "--solver-uri",
        default=None,
        help="host:port of a solver sidecar (python -m karpenter_tpu.sidecar);"
        " omit to solve in-process",
    )
    parser.add_argument(
        "--data-dir",
        default=None,
        help="directory for the durable store (WAL + snapshots); omit for "
        "in-memory only",
    )
    parser.add_argument(
        "--journal-dir",
        default=None,
        help="directory for the crash-safe protective-state journal "
        "(FSM phases, holds, budgets, breakers, backoff, forecast "
        "history) + actuation fence generation; omit for ephemeral "
        "state and unfenced actuation (docs/resilience.md 'Crash "
        "recovery')",
    )
    parser.add_argument(
        "--recovery-warmup-ticks",
        type=int,
        default=1,
        help="full reconcile ticks a RECOVERED boot holds the "
        "conservative warm-up (no consolidation or preemption) while "
        "fleet state is confirmed; first boots skip it",
    )
    parser.add_argument(
        "--apiserver",
        default=None,
        help="kube-apiserver base URL for real-cluster mode (e.g. "
        "https://kubernetes.default.svc); in-cluster token/CA are picked "
        "up automatically. Omit to run on the in-process store.",
    )
    parser.add_argument(
        "--kube-token-file",
        default=None,
        help="bearer-token file for --apiserver (default: the in-cluster "
        "serviceaccount token)",
    )
    parser.add_argument(
        "--kube-ca",
        default=None,
        help="CA bundle for --apiserver (default: the in-cluster CA)",
    )
    parser.add_argument(
        "--kube-insecure",
        action="store_true",
        help="skip TLS verification for --apiserver (dev only)",
    )
    parser.add_argument(
        "--leader-elect",
        action=argparse.BooleanOptionalAction,
        default=True,
    )
    parser.add_argument(
        "--partitions",
        type=int,
        default=0,
        metavar="N",
        help="replicated control plane (docs/resilience.md 'Replicated "
        "control plane'): partition tenants across N CAS leases and "
        "run this process as one leader-elected replica — rendezvous-"
        "hash assignment, fenced tenant handoff, /debug/replicas "
        "scoreboard. 0 (default) = single-replica wire, byte-identical "
        "and lease-traffic-free; with N > 0 the global --leader-elect "
        "gate is superseded by the per-partition leases",
    )
    parser.add_argument(
        "--replica-id",
        default=None,
        metavar="ID",
        help="this replica's identity on the lease plane (heartbeat "
        "lease name, rendezvous ranking, /debug/replicas); default: a "
        "generated karpenter-<hex> id — set it in real fleets so "
        "scoreboards correlate across processes",
    )
    parser.add_argument(
        "--lease-duration",
        type=float,
        default=15.0,
        metavar="SECONDS",
        help="partition/heartbeat lease duration: the failover "
        "detection horizon — a dead replica's tenants become adoptable "
        "one lease duration (plus skew tolerance) after its last renew",
    )
    parser.add_argument(
        "--profiler-port",
        type=int,
        default=0,
        help="start the JAX profiler server on this port (0 = off)",
    )
    parser.add_argument(
        "--trace-export",
        default=None,
        metavar="FILE",
        help="export reconcile traces as Chrome-trace/Perfetto JSONL to "
        "FILE at exit (docs/observability.md); with --simulate and no "
        "other scenario flag, replays a seeded end-to-end scenario "
        "(tick -> coalesced solver dispatch -> actuation) and exports "
        "its trace. With --provenance, the decision ledger is dumped "
        "next to it as FILE's .decisions.jsonl sibling",
    )
    parser.add_argument(
        "--provenance",
        action=argparse.BooleanOptionalAction,
        default=False,
        help="record decision provenance (docs/observability.md "
        "'Decision provenance'): every HorizontalAutoscaler decision's "
        "full input chain — observed metrics, forecast blend, cost "
        "ladder + clamps, warm-pool headroom, solver rung, tenant/"
        "admission round, trace id — into a bounded columnar ring "
        "served at /debug/decisions and dumped as JSONL next to "
        "--trace-export. Default off (byte-identical decisions either "
        "way; ~zero cost when off)",
    )
    parser.add_argument(
        "--profile",
        choices=("production",),
        default=None,
        help="opinionated flag preset (docs/OPERATIONS.md 'Profiles'): "
        "'production' turns on --event-driven, --prewarm-compile and "
        "--fused-tick and tightens the --selfslo-objective default to "
        "0.5s (the sub-second posture the event-driven plane is built "
        "to hold); every explicit flag still wins over the preset",
    )
    parser.add_argument(
        "--fused-tick",
        action=argparse.BooleanOptionalAction,
        default=None,
        help="fuse the steady-state forecast -> decide -> cost chain "
        "into ONE compiled program per tenant batch "
        "(docs/solver-service.md 'Fused tick'): no host round-trips "
        "between stages, 3+ dispatches per tick collapse to 1 "
        "(karpenter_solver_dispatches_per_tick). Decisions are "
        "property-pinned bitwise identical to the chained path; off "
        "(the default outside --profile production) keeps the unfused "
        "wire byte-identical",
    )
    parser.add_argument(
        "--poolgroups",
        action="store_true",
        help="enable the joint pool-group allocator "
        "(docs/poolgroups.md): PoolGroup CRDs name member autoscalers "
        "with cross-pool ratio bands and a shared budget; members "
        "leave the independent per-pool cost ladders and ride ONE "
        "batched joint dispatch (SolverService.poolgroup). Off (the "
        "default, or a fleet with no PoolGroup objects) keeps the "
        "uncoordinated wire byte-identical. With --simulate: run the "
        "seeded traffic-mix-shift world instead "
        "(prefill/decode pools through a decode-heavy storm)",
    )
    parser.add_argument(
        "--compile-cache-dir",
        default=None,
        metavar="DIR",
        help="persistent jit compile cache directory (the first-class "
        "form of the KARPENTER_COMPILE_CACHE env var, matching the "
        "sidecar's flag): restarted processes reload compiled solver "
        "programs from disk instead of recompiling — with "
        "--prewarm-compile the boot warm-up becomes a disk read "
        "(docs/solver-service.md 'Compile pre-warm'). The flag wins "
        "over the env var when both are set",
    )
    parser.add_argument(
        "--event-driven",
        action=argparse.BooleanOptionalAction,
        default=None,
        help="watch events schedule debounced coalesced event passes "
        "(sub-second reaction; docs/solver-service.md 'Event-driven "
        "reconcile'), demoting the periodic tick to a resync backstop; "
        "off (the default outside --profile production) keeps the "
        "tick-paced loop byte-identical to previous releases",
    )
    parser.add_argument(
        "--event-debounce",
        type=float,
        default=0.05,
        metavar="SECONDS",
        help="event-pass debounce window: watch events landing within "
        "this window coalesce into ONE partial reconcile pass (bounds "
        "solve amplification under churn storms); only meaningful with "
        "--event-driven",
    )
    parser.add_argument(
        "--prewarm-compile",
        action=argparse.BooleanOptionalAction,
        default=None,
        help="compile the smallest bucket rungs of the always-on kernel "
        "families (solve + decide) at boot, so a cold plane's first "
        "event pass doesn't pay a first-touch jit compile "
        "(docs/solver-service.md 'Compile pre-warm'); rungs the "
        "compile cache already knows are skipped",
    )
    parser.add_argument(
        "--introspect",
        action=argparse.BooleanOptionalAction,
        default=False,
        help="enable the solver introspection plane "
        "(docs/observability.md 'Device telemetry & introspection'): "
        "a compile ledger recording every compile-cache miss with "
        "rung/extents/wall time/trace ids + XLA flops/bytes "
        "attribution (karpenter_solver_compile_seconds, compile_storm "
        "flight-recorder trips), per-tick device memory telemetry "
        "(karpenter_device_*, resident-LRU byte accounting, the "
        "self-SLO memory source), and the /debug/solver posture "
        "document. Default off (decisions byte-identical either way; "
        "~zero cost when off)",
    )
    parser.add_argument(
        "--introspect-storm-threshold",
        type=int,
        default=4,
        help="compile-cache misses inside one tick window (after the "
        "plane reached steady state) that count as a compile storm "
        "and dump the flight-recorder ring; only meaningful with "
        "--introspect",
    )
    parser.add_argument(
        "--selfslo-objective",
        type=float,
        default=None,
        help="the control plane's own e2e-latency objective in seconds "
        "(against karpenter_reconcile_e2e_seconds; pick a histogram "
        "bucket bound) for the self-SLO burn-rate monitor "
        "(docs/observability.md 'Self-SLO monitoring'); defaults to "
        "1.0, or 0.5 under --profile production",
    )
    parser.add_argument(
        "--selfslo-target",
        type=float,
        default=0.99,
        help="the self-SLO success-ratio target the multi-window burn "
        "rates measure against (error budget = 1 - target); must be "
        "strictly between 0 and 1",
    )
    parser.add_argument(
        "--duration",
        type=float,
        default=float("inf"),
        help="seconds to run before exiting (default: forever)",
    )
    parser.add_argument("--tick", type=float, default=0.1)
    parser.add_argument(
        "--simulate",
        action="store_true",
        help="dry-run: solve the pending-pods problem once, print a JSON "
        "report with per-pod-shape assignments, and exit without "
        "mutating anything (docs/OPERATIONS.md 'What-if simulation')",
    )
    parser.add_argument(
        "--what-if",
        default=None,
        metavar="FILE",
        help="with --simulate: JSON/YAML file of hypothetical node groups "
        "([{name, allocatable, labels, taints}]) appended to the solve; "
        "the report then includes baseline vs what-if and the delta",
    )
    parser.add_argument(
        "--sim-seed",
        type=int,
        default=None,
        metavar="N",
        help="with --simulate: one seed threaded through every SEEDED "
        "scenario's RNG streams (docs/simulator.md); omit for each "
        "scenario's pinned default seed, keeping published replay "
        "digests byte-identical",
    )
    parser.add_argument(
        "--list",
        action="store_true",
        help="with --simulate: print the registered scenario catalog "
        "(name, selection flags, seedability, one-line description) "
        "from the simlab registry and exit without running anything",
    )
    parser.add_argument(
        "--backoff-base",
        type=float,
        default=1.0,
        help="engine requeue backoff base seconds under retryable "
        "failures (docs/resilience.md)",
    )
    parser.add_argument(
        "--backoff-cap",
        type=float,
        default=60.0,
        help="engine requeue backoff cap seconds",
    )
    parser.add_argument(
        "--circuit-threshold",
        type=int,
        default=5,
        help="consecutive provider failures before a node group's "
        "actuation circuit opens",
    )
    parser.add_argument(
        "--circuit-reset",
        type=float,
        default=120.0,
        help="seconds an open actuation circuit waits before a "
        "half-open probe reconcile",
    )
    parser.add_argument(
        "--solver-watchdog-timeout",
        type=float,
        default=30.0,
        help="seconds a solver device call may run before the watchdog "
        "restarts the worker and drains to numpy (0 = off)",
    )
    parser.add_argument(
        "--shard-threshold",
        type=int,
        default=1 << 24,
        help="pods x groups cell count at which a solve routes through "
        "the multi-device mesh instead of the single-device program "
        "(docs/solver-service.md 'Sharded dispatch'); 0 disables "
        "sharding",
    )
    parser.add_argument(
        "--shard-devices",
        type=int,
        default=None,
        help="cap the sharded-dispatch mesh at N devices (default: "
        "every visible device; < 2 leaves the mesh unbuilt)",
    )
    parser.add_argument(
        "--shard-mesh",
        default=None,
        metavar="PODSxGROUPS",
        help="explicit mesh extents for the sharded dispatch, e.g. "
        "'8x1' (default: pods-major factorization of the device count)",
    )
    parser.add_argument(
        "--no-resident",
        action="store_true",
        help="disable the device-resident fleet state (docs/"
        "solver-service.md 'Device-resident fleet state'): every solve "
        "dispatch re-uploads its full operand stack instead of serving "
        "resident buffers with scatter updates; outputs are "
        "bit-identical either way",
    )
    parser.add_argument(
        "--consolidate",
        action="store_true",
        help="enable the consolidation engine (batched node-drain "
        "planning + cordon/verify/drain actuation; "
        "docs/consolidation.md). With --simulate: print the dry-run "
        "drain plan instead of the pending-pods report and exit "
        "without mutating anything",
    )
    parser.add_argument(
        "--preempt",
        action="store_true",
        help="enable the preemption engine (batched eviction planning "
        "for high-priority pending pods + budgeted eviction actuation; "
        "docs/preemption.md). With --simulate: replay a seeded "
        "spot-reclaim storm and report evictions vs scale-ups vs "
        "pending-pod recovery, mutating nothing",
    )
    parser.add_argument(
        "--preempt-budget",
        type=int,
        default=1,
        help="default max concurrent evictions charged against one "
        "node group per hold window (120s; spec.eviction_budget "
        "overrides per group)",
    )
    parser.add_argument(
        "--default-priority",
        type=int,
        default=0,
        help="priority assumed for pods naming an unknown "
        "PriorityClass (resolved spec.priority and the system classes "
        "always win; docs/preemption.md)",
    )
    parser.add_argument(
        "--constraints",
        action="store_true",
        help="with --simulate: replay a seeded spread-constrained "
        "serving fleet with a gold reservation through a zonal outage "
        "and report per-group spread skew and reservation fill "
        "before/after (docs/constraints.md)",
    )
    parser.add_argument(
        "--eventloop",
        action="store_true",
        help="with --simulate: replay a seeded pod-arrival trace "
        "tick-paced vs event-driven and report e2e p50/p99 off the "
        "karpenter_reconcile_e2e_seconds histogram, the solve-"
        "amplification factor, and the churn-storm coalescing proof "
        "(docs/solver-service.md 'Event-driven reconcile'); "
        "--event-debounce tunes the replayed window",
    )
    parser.add_argument(
        "--eventloop-arrivals",
        type=int,
        default=60,
        help="with --simulate --eventloop: seeded pod arrivals in the "
        "replayed trace",
    )
    parser.add_argument(
        "--eventloop-storm",
        type=int,
        default=1000,
        help="with --simulate --eventloop: churn-storm events injected "
        "into one debounce window",
    )
    parser.add_argument(
        "--restart-storm",
        action="store_true",
        help="with --simulate: replay a seeded kill-and-restart storm "
        "against a consolidating fleet (crash mid-drain, reboot from "
        "the protective-state journal, repeat) and report exactly-once "
        "actuation, FSM resumption, and the fence rejecting a stale "
        "incarnation's replay (docs/resilience.md 'Crash recovery')",
    )
    parser.add_argument(
        "--storm-crashes",
        type=int,
        default=3,
        help="with --simulate --restart-storm: kill/reboot cycles",
    )
    parser.add_argument(
        "--failover",
        action="store_true",
        help="with --simulate: replay a seeded leader-kill failover "
        "across a replicated control plane (N tenants partitioned over "
        "R replicas, the biggest owner SIGKILLed mid-storm) and report "
        "handoff blackout, exactly-once actuation across the handoff, "
        "the deposed replica's fence-rejected late write, and "
        "reconvergence ticks (docs/resilience.md 'Replicated control "
        "plane')",
    )
    parser.add_argument(
        "--replicas",
        type=int,
        default=3,
        help="with --simulate --failover: simulated replica count",
    )
    parser.add_argument(
        "--cost",
        action="store_true",
        help="with --simulate: replay a seeded diurnal ramp + spot-price "
        "step through two cost-aware worlds (warm pool on vs off) behind "
        "a lagged provider and report the hourly-cost and "
        "provisioning-lead-time deltas (docs/cost.md); cost-aware "
        "scaling in the running control plane is opt-in per HA via "
        "spec.behavior.slo and per group via spec.warmPool, no flag "
        "needed",
    )
    parser.add_argument(
        "--cost-default-hourly",
        type=float,
        default=1.0,
        help="hourly price for a node whose instance type the built-in "
        "cost catalog doesn't know (docs/cost.md); per-group overrides "
        "via the cost.karpenter.sh/hourly-cost annotation win",
    )
    parser.add_argument(
        "--cost-spot-multiplier",
        type=float,
        default=0.35,
        help="spot/preemptible-tier price as a fraction of on-demand "
        "in the cost model (docs/cost.md)",
    )
    parser.add_argument(
        "--pricing-file",
        default=None,
        metavar="FILE",
        help="JSON/YAML instance-type pricing catalog, reloaded on "
        "mtime change and consulted before the built-in catalog "
        "(docs/cost.md 'Pricing feeds'); omit for the built-in "
        "illustrative catalog",
    )
    parser.add_argument(
        "--tenant-config",
        default=None,
        metavar="FILE",
        help="JSON/YAML list of tenant specs ({id, weight, "
        "pricingFile, ...}) enabling the multi-tenant control plane "
        "(docs/multitenancy.md): per-tenant namespaced stacks over one "
        "shared solver service; omit for the single-tenant wiring "
        "(byte-identical to previous releases)",
    )
    parser.add_argument(
        "--tenant-deadline",
        type=float,
        default=None,
        metavar="SECONDS",
        help="tenant-weighted solve deadline (docs/multitenancy.md): "
        "bounds a deferred tenant's wait behind earlier admission "
        "rounds — the budget is SECONDS x weight / mean weight, and an "
        "exhausted budget serves the tenant immediately from the "
        "bit-identical mirror (counted in "
        "karpenter_tenant_deferrals_total); omit for unbounded waits",
    )
    parser.add_argument(
        "--multitenant",
        action="store_true",
        help="with --simulate: step N seeded tenant clusters in "
        "lockstep through one MultiTenantScheduler (cross-tenant "
        "concatenated decide/cost dispatches) and report aggregate "
        "decisions, dispatch counts, and per-tick digests "
        "(docs/multitenancy.md)",
    )
    parser.add_argument(
        "--tenants",
        type=int,
        default=16,
        help="with --simulate --multitenant: simulated tenant count",
    )
    parser.add_argument(
        "--tenant-id",
        default=None,
        help="this control plane's tenant id, stamped as gRPC metadata "
        "on every --solver-uri RPC so a shared solver sidecar can "
        "attribute traffic per tenant (docs/multitenancy.md)",
    )
    parser.add_argument(
        "--forecast",
        action="store_true",
        help="with --simulate: replay a synthetic diurnal ramp through "
        "a forecast-enabled and a reactive-only autoscaler and report "
        "the proactive provisioning lead (docs/forecasting.md); "
        "forecasting in the running control plane is opt-in per HA via "
        "spec.behavior.forecast, no flag needed",
    )
    parser.add_argument(
        "--forecast-horizon",
        type=float,
        default=60.0,
        help="with --simulate --forecast: horizon seconds for the replay",
    )
    parser.add_argument(
        "--forecast-model",
        default="holt-winters",
        choices=("holt-winters", "linear"),
        help="with --simulate --forecast: model for the replay",
    )
    parser.add_argument(
        "--forecast-history",
        type=int,
        default=64,
        help="metric-history ring capacity per series "
        "(docs/forecasting.md)",
    )
    parser.add_argument(
        "--stale-metric-max-age",
        type=float,
        default=60.0,
        help="seconds a history sample may stand in for a FAILED live "
        "metric query before the row errors instead (0 disables reuse)",
    )
    args = parser.parse_args(argv)
    # Resolve the --profile preset: flags parked on a None sentinel take
    # the profile's value; anything the user typed explicitly wins.
    production = args.profile == "production"
    if args.event_driven is None:
        args.event_driven = production
    if args.prewarm_compile is None:
        args.prewarm_compile = production
    if args.fused_tick is None:
        args.fused_tick = production
    if args.selfslo_objective is None:
        args.selfslo_objective = 0.5 if production else 1.0
    if not 0.0 < args.selfslo_target < 1.0:
        # a clean usage error instead of a ValueError traceback from
        # deep inside runtime construction (SelfSLOMonitor's guard)
        parser.error(
            f"--selfslo-target must be in (0, 1), got "
            f"{args.selfslo_target}"
        )
    if args.selfslo_objective <= 0:
        parser.error(
            f"--selfslo-objective must be > 0 seconds, got "
            f"{args.selfslo_objective}"
        )
    if args.introspect_storm_threshold < 1:
        parser.error(
            f"--introspect-storm-threshold must be >= 1, got "
            f"{args.introspect_storm_threshold}"
        )
    if args.event_debounce < 0:
        parser.error(
            f"--event-debounce must be >= 0 seconds (0 = dispatch the "
            f"pass immediately), got {args.event_debounce}"
        )
    return args


def _parse_mesh_shape(spec):
    """'8x1' -> (8, 1): the --shard-mesh override for the sharded
    dispatch strategy (docs/solver-service.md 'Sharded dispatch')."""
    if not spec:
        return None
    try:
        pods, groups = spec.lower().split("x")
        shape = (int(pods), int(groups))
    except ValueError:
        raise SystemExit(
            f"--shard-mesh {spec!r}: expected PODSxGROUPS, e.g. 8x1"
        )
    if shape[0] < 1 or shape[1] < 1:
        raise SystemExit(f"--shard-mesh {spec!r}: extents must be >= 1")
    return shape


def _run_simulation(args, store) -> int:
    """Registry-driven simulation dispatch (docs/simulator.md): the
    SimLab scenario catalog (karpenter_tpu/simlab) owns both the
    selection predicates — the old elif chain's precedence, preserved
    exactly — and the replay runners, so `--simulate --list` and this
    dispatch can never disagree about what a flag runs. `--sim-seed`
    threads through every seeded scenario inside the runners; the
    default reproduces each world's pinned digests byte-identically."""
    from karpenter_tpu.simlab import catalog_text, select_for

    if args.list:
        print(catalog_text())
        return 0
    return int(select_for(args).run(args, store) or 0)


def _export_trace(args) -> None:
    """Flush the reconcile-span ring as Chrome-trace JSONL when
    --trace-export names a file (docs/observability.md), and the
    decision-provenance ledger as its .decisions.jsonl sibling when
    --provenance recorded any."""
    if not args.trace_export:
        return
    from karpenter_tpu.observability import default_ledger, default_tracer

    events = default_tracer().export_jsonl(args.trace_export)
    print(
        f"exported {events} trace event(s) to {args.trace_export}",
        file=sys.stderr,
    )
    ledger = default_ledger()
    if ledger.enabled:
        from karpenter_tpu.observability.provenance import (
            export_next_to_trace,
        )

        path, records = export_next_to_trace(ledger, args.trace_export)
        print(
            f"exported {records} decision record(s) to {path}",
            file=sys.stderr,
        )


def _readiness(runtime):
    """/readyz wired to REAL state (docs/observability.md): NOT ready
    during the recovery warm-up (fleet state unconfirmed — disruption is
    gated too) and while the solver backend-health FSM is tripped
    (decisions are numpy-degraded). /healthz stays liveness-only."""
    from karpenter_tpu.solver.service import HEALTHY

    def check():
        recovery = runtime.recovery
        if recovery is not None and recovery.warmup_remaining > 0:
            return False, (
                f"recovery warm-up: {recovery.warmup_remaining} "
                "tick(s) remaining"
            )
        health = runtime.solver_service.backend_health()
        if health != HEALTHY:
            return False, f"solver backend {health}"
        return True, "ok"

    return check


def _make_store(args):
    """KubeStore when --apiserver names a real cluster, else None (the
    runtime then builds its in-process store)."""
    if not args.apiserver:
        return None
    from karpenter_tpu.store.kube import KubeClient, KubeStore

    return KubeStore(
        KubeClient(
            base_url=args.apiserver,
            token_file=args.kube_token_file,
            ca_file=args.kube_ca,
            insecure=args.kube_insecure,
        )
    )


def _start_webhook_server(args):
    if not args.webhook_port:
        return None
    import os.path

    from karpenter_tpu.webhook import WebhookServer

    cert = key = None
    if args.webhook_cert_dir:
        cert = os.path.join(args.webhook_cert_dir, "tls.crt")
        key = os.path.join(args.webhook_cert_dir, "tls.key")
    server = WebhookServer(
        port=args.webhook_port, cert_file=cert, key_file=key
    )
    wport = server.start()
    print(f"serving admission webhooks on :{wport}", file=sys.stderr)
    return server


def _run_loop(args, runtime, elector) -> None:
    """Tick until the duration elapses, SIGTERM arrives, or ^C.

    Clean shutdown on SIGTERM (what kubernetes sends on pod termination):
    finish the current tick, then run the same teardown as normal exit —
    the reference's manager stops on SIGTERM/SIGINT via controller-
    runtime's signal handler (main.go run-until-signalled)."""
    import signal

    stopping = {"flag": False}

    def _stop(signum, frame):
        stopping["flag"] = True

    previous_handler = None
    try:
        previous_handler = signal.signal(signal.SIGTERM, _stop)
    except ValueError:
        pass  # non-main thread (tests): rely on duration/interrupt

    deadline = runtime.clock() + args.duration
    try:
        while runtime.clock() < deadline and not stopping["flag"]:
            if elector is None or elector.try_acquire():
                runtime.manager.reconcile_all()
            time.sleep(args.tick)
    except KeyboardInterrupt:
        pass
    finally:
        if previous_handler is not None:
            # restore: after main() returns, SIGTERM must regain its
            # previous disposition (a stale handler flipping a dead flag
            # would make the process unkillable by TERM)
            signal.signal(signal.SIGTERM, previous_handler)


def _setup_backend(args) -> None:
    """Compile cache + backend probe, before the first jit.

    Standalone mode compiles the decision kernel (and, without
    --solver-uri, the bin-pack) in-process: honor the same persistent
    compile cache the sidecar offers, so control-plane restarts skip
    recompiles too. --compile-cache-dir is the first-class flag
    (matching the sidecar's), with KARPENTER_COMPILE_CACHE as the
    env fallback for existing deployments. And the batched HPA decision
    kernel ALWAYS runs in-process (only the bin-pack is optionally
    routed to a sidecar), so an unreachable TPU must degrade to CPU
    decisions unconditionally — not freeze the control plane at its
    first jit (utils/backend.py rationale)."""
    import os as _os

    from karpenter_tpu.utils.backend import (
        configure_compile_cache,
        ensure_usable_backend,
    )

    configure_compile_cache(
        args.compile_cache_dir
        or _os.environ.get("KARPENTER_COMPILE_CACHE", "")
    )
    note = ensure_usable_backend()
    if note:
        print(f"decision backend: {note}", file=sys.stderr)


def main(argv=None) -> int:
    args = parse_args(argv)
    log_setup(verbose=args.verbose)
    _setup_backend(args)
    store = _make_store(args)
    if args.simulate:
        try:
            return _run_simulation(args, store)
        finally:
            _export_trace(args)
            if store is not None:
                store.close()
    runtime = KarpenterRuntime(
        Options(
            prometheus_uri=args.prometheus_uri,
            cloud_provider=args.cloud_provider,
            solver_uri=args.solver_uri,
            data_dir=args.data_dir,
            verbose=args.verbose,
            consolidate=args.consolidate,
            preempt=args.preempt,
            preempt_budget=args.preempt_budget,
            default_pod_priority=args.default_priority,
            journal_dir=args.journal_dir,
            recovery_warmup_ticks=args.recovery_warmup_ticks,
            backoff_base_s=args.backoff_base,
            backoff_cap_s=args.backoff_cap,
            circuit_failure_threshold=args.circuit_threshold,
            circuit_reset_s=args.circuit_reset,
            solver_watchdog_timeout_s=args.solver_watchdog_timeout,
            solver_shard_threshold=args.shard_threshold,
            solver_shard_devices=args.shard_devices,
            solver_shard_mesh=_parse_mesh_shape(args.shard_mesh),
            solver_resident=not args.no_resident,
            forecast_history=args.forecast_history,
            stale_metric_max_age_s=args.stale_metric_max_age,
            cost_default_hourly=args.cost_default_hourly,
            cost_spot_multiplier=args.cost_spot_multiplier,
            pricing_file=args.pricing_file,
            tenant_config=args.tenant_config,
            tenant_deadline_s=args.tenant_deadline,
            tenant_id=args.tenant_id,
            provenance=args.provenance,
            introspect=args.introspect,
            introspect_storm_threshold=args.introspect_storm_threshold,
            selfslo_objective_s=args.selfslo_objective,
            selfslo_target=args.selfslo_target,
            event_driven=args.event_driven,
            event_debounce_s=args.event_debounce,
            prewarm_compile=args.prewarm_compile,
            fused_tick=args.fused_tick,
            poolgroups=args.poolgroups,
            # already applied above (before the first compile); carried
            # on Options so embedded runtimes resolve identically
            compile_cache_dir=args.compile_cache_dir,
            partitions=args.partitions,
            replica_id=args.replica_id,
            lease_duration_s=args.lease_duration,
        ),
        store=store,
    )
    metrics_server = MetricsServer(
        runtime.registry,
        port=args.metrics_port,
        readiness=_readiness(runtime),
        ledger=runtime.decision_ledger,
        selfslo=runtime.selfslo,
        introspection=runtime.solver_introspection,
        # /debug/profile captures land next to the flight-recorder
        # dumps (and the recovery journal) — one incident directory
        profile_dir=args.journal_dir,
        replication=runtime.replication,
    )
    port = metrics_server.start()
    print(f"serving /metrics and /healthz on :{port}", file=sys.stderr)
    webhook_server = _start_webhook_server(args)
    if args.profiler_port and start_profiler_server(args.profiler_port):
        print(
            f"jax profiler listening on :{args.profiler_port}",
            file=sys.stderr,
        )

    # with --partitions the per-partition lease plane IS the election:
    # every replica must tick (each serves its owned partitions), so
    # the global whole-process gate is superseded
    elector = (
        LeaderElector(runtime.store, clock=runtime.clock)
        if args.leader_elect and not args.partitions
        else None
    )
    try:
        _run_loop(args, runtime, elector)
    finally:
        _export_trace(args)
        metrics_server.stop()
        if webhook_server is not None:
            webhook_server.stop()
        runtime.close()
        if store is not None:
            store.close()  # CLI-owned KubeStore: stop the watch threads
    return 0


if __name__ == "__main__":
    sys.exit(main())
