"""Multi-tenant control plane (docs/multitenancy.md).

One solver service, thousands of tenant clusters:

  * TenantRegistry (tenancy/registry.py) — namespaces the full
    per-cluster stack (store, forecaster history, cost model/engine
    with per-tenant pricing feeds, warm-pool state, journal/fence
    dirs, gauge label sets) under a tenant id; per-tenant
    karpenter_tenant_* series retire with the tenant.
  * MultiTenantScheduler (tenancy/scheduler.py) — concatenates
    cross-tenant decide/cost/forecast matrices into single device
    programs (bit-identical per-tenant slices) and rides the existing
    coalescing queue for cross-tenant bin-packs.
  * WeightedAdmission (tenancy/fairness.py) — deficit-weighted
    round-robin row budgets so a noisy tenant cannot starve the queue.
  * TenantBreakerBoard (tenancy/isolation.py) — per-tenant breakers:
    a tripped tenant serves from its numpy mirror alone while healthy
    tenants stay on device.
"""

from karpenter_tpu.tenancy.fairness import WeightedAdmission
from karpenter_tpu.tenancy.isolation import TenantBreakerBoard
from karpenter_tpu.tenancy.registry import (
    TenantContext,
    TenantMetrics,
    TenantRegistry,
    TenantSpec,
    load_tenant_config,
)
from karpenter_tpu.tenancy.scheduler import (
    MultiTenantScheduler,
    TenancyStatistics,
    concat_cost_inputs,
    concat_decision_inputs,
    slice_cost_outputs,
    slice_decision_outputs,
)

__all__ = [
    "MultiTenantScheduler",
    "TenancyStatistics",
    "TenantBreakerBoard",
    "TenantContext",
    "TenantMetrics",
    "TenantRegistry",
    "TenantSpec",
    "WeightedAdmission",
    "concat_cost_inputs",
    "concat_decision_inputs",
    "load_tenant_config",
    "slice_cost_outputs",
    "slice_decision_outputs",
]
